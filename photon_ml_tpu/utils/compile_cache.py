"""Persistent XLA compilation cache.

GLMix cold starts are compile-bound: CD iteration 0 pays one fresh
LBFGS/TRON compile per (K, S) entity-block bucket plus the fixed-effect
solves (round-3 measurement: 245s first sweep vs 3.2s steady state on the
3-coordinate example). The JAX persistent compilation cache survives
processes — measured through the axon remote tunnel: an 86s first-call
optimize() drops to 15s on the next process with the cache warm (5.8x).

Enabled by default from the CLI drivers/bench; set PHOTON_COMPILE_CACHE to
relocate it or PHOTON_COMPILE_CACHE=0 to disable.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger("photon_ml_tpu")


def enable_persistent_compilation_cache(path: str | None = None) -> str | None:
    """Best-effort: point jax at an on-disk compilation cache. Returns the
    cache dir, or None when disabled/unavailable."""
    env = os.environ.get("PHOTON_COMPILE_CACHE")
    if env == "0":
        return None
    path = path or env or os.path.join(
        os.path.expanduser("~"), ".cache", "photon-ml-tpu-xla"
    )
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # only persist compiles worth the disk round trip
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:  # never fail a run over a cache
        from .. import obs

        obs.swallowed_error("compile_cache.enable")
        logger.info("persistent compilation cache unavailable: %s", e)
        return None
    return path


_compile_hook_installed = False


def install_compile_metrics_hook() -> bool:
    """Best-effort: register a jax monitoring listener that feeds XLA
    compile durations into the obs layer (span ``compile_s`` attribution
    plus ``photon_jax_compile_*`` registry series). Idempotent; returns
    True when the hook is (already) installed."""
    global _compile_hook_installed
    if _compile_hook_installed:
        return True
    try:
        from jax._src import monitoring
    except Exception as e:  # private API: degrade to no compile attribution
        from .. import obs

        obs.swallowed_error("compile_cache.monitoring_import")
        logger.info("jax monitoring hook unavailable: %s", e)
        return False

    from .. import obs

    def _on_duration(event: str, duration: float, **kwargs) -> None:
        if "compile" not in event:
            return
        obs.add_compile_seconds(duration)
        reg = obs.current_run().registry
        reg.counter(
            "photon_jax_compile_total", "XLA compile events by jax event name"
        ).labels(event=event).inc()
        reg.summary(
            "photon_jax_compile_seconds", "XLA compile seconds by jax event name"
        ).labels(event=event).observe(duration)

    try:
        monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception as e:
        obs.swallowed_error("compile_cache.monitoring_register")
        logger.info("jax monitoring hook registration failed: %s", e)
        return False
    _compile_hook_installed = True
    return True
