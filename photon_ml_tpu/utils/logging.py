"""Job logging (the PhotonLogger role: leveled logs to console + a per-job
file; reference: photon-lib .../util/PhotonLogger.scala:34-553)."""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

_FORMAT = "%(asctime)s %(levelname)s %(name)s: %(message)s"


def setup_logging(level: str = "INFO", log_file: Optional[str] = None):
    logger = logging.getLogger("photon_ml_tpu")
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    logger.handlers.clear()
    console = logging.StreamHandler(sys.stderr)
    console.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(console)
    if log_file:
        os.makedirs(os.path.dirname(log_file) or ".", exist_ok=True)
        fh = logging.FileHandler(log_file)
        fh.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(fh)
    return logger
