"""Event hook system: typed training events to pluggable listeners.

Reference: photon-client .../event/EventEmitter.scala:23-72 (lock-guarded
listener registry whose ``sendEvent`` swallows listener errors — a failing
telemetry hook must never fail training) and Event.scala:44-61 (the typed
event vocabulary the legacy driver emits: setup, training start/finish, and
per-model optimization log events).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional

logger = logging.getLogger("photon_ml_tpu")


def _swallowed_error(site: str) -> None:
    """Lazy obs.swallowed_error: utils.events sits BELOW obs in the import
    graph (obs.run imports EventEmitter from here), so the counter import
    must happen at call time; by then obs is always importable. Registry
    increments emit no events, so counting inside event-dispatch error
    handling cannot recurse."""
    from .. import obs

    obs.swallowed_error(site)


class Event:
    """Base class of all emitted events."""


@dataclasses.dataclass(frozen=True)
class SetupEvent(Event):
    """Job configured (PhotonSetupEvent minus the SparkContext)."""

    params: Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TrainingStartEvent(Event):
    time: float  # unix seconds


@dataclasses.dataclass(frozen=True)
class TrainingFinishEvent(Event):
    time: float


@dataclasses.dataclass(frozen=True)
class OptimizationLogEvent(Event):
    """One trained configuration (PhotonOptimizationLogEvent): reg weights,
    per-coordinate optimization trackers, validation metrics."""

    reg_weights: Dict[str, float]
    trackers: Dict[str, Any]
    metrics: Optional[Dict[str, float]] = None


class EventListener:
    """Consumer interface (EventListener.scala)."""

    def handle(self, event: Event) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class EventEmitter:
    """Thread-safe listener registry; listener errors are logged, never
    raised (EventEmitter.scala's Try(...) semantics)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._listeners: List[EventListener] = []

    def register_listener(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def has_listeners(self) -> bool:
        with self._lock:
            return bool(self._listeners)

    def listeners(self) -> List[EventListener]:
        with self._lock:
            return list(self._listeners)

    def clear_listeners(self) -> None:
        with self._lock:
            for l in self._listeners:
                try:
                    l.close()
                except Exception:
                    _swallowed_error("events.listener_close")
                    logger.exception("event listener close failed")
            self._listeners = []

    def send_event(self, event: Event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for l in listeners:
            try:
                l.handle(event)
            except Exception:
                # per-listener-type site: a run summary showing 40 swallowed
                # JsonlSink errors vs 40 anonymous ones is the difference
                # between "disk full" and a shrug
                _swallowed_error(f"events.listener_handle.{type(l).__name__}")
                logger.exception(
                    "event listener %r failed on %s", l, type(event).__name__
                )
