"""Date-ranged input directories.

Reference: photon-client .../util/DateRange.scala:107 ("yyyyMMdd-yyyyMMdd"),
DaysRange.scala:80 ("start-end" days before today, start >= end >= 0), and
IOUtils.getInputPathsWithinDateRange (photon-client .../util/IOUtils.scala:113-154):
input data lives in daily directories ``<base>/yyyy/MM/dd``; a range selects
the existing day directories, optionally erroring on missing days.
"""

from __future__ import annotations

import dataclasses
import datetime as _dt
import os
from typing import List, Optional, Sequence

DATE_PATTERN = "%Y%m%d"
DELIMITER = "-"


@dataclasses.dataclass(frozen=True)
class DateRange:
    start: _dt.date
    end: _dt.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"Invalid range: start date {self.start} comes after end date {self.end}."
            )

    def days(self) -> List[_dt.date]:
        n = (self.end - self.start).days
        return [self.start + _dt.timedelta(days=i) for i in range(n + 1)]

    def __str__(self) -> str:
        return (
            f"{self.start.strftime(DATE_PATTERN)}{DELIMITER}"
            f"{self.end.strftime(DATE_PATTERN)}"
        )

    @staticmethod
    def from_string(range_str: str) -> "DateRange":
        """Parse 'yyyyMMdd-yyyyMMdd' (DateRange.fromDateString)."""
        parts = range_str.split(DELIMITER)
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse the range {range_str!r} using delimiter {DELIMITER!r}."
            )
        try:
            start = _dt.datetime.strptime(parts[0], DATE_PATTERN).date()
            end = _dt.datetime.strptime(parts[1], DATE_PATTERN).date()
        except ValueError as e:
            raise ValueError(f"Couldn't parse the date range: {range_str}") from e
        return DateRange(start, end)


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """Days before today: start >= end >= 0 (DaysRange.scala)."""

    start_days: int
    end_days: int

    def __post_init__(self):
        if self.start_days < 0 or self.end_days < 0:
            raise ValueError("Invalid range: days ago must be >= 0")
        if self.start_days < self.end_days:
            raise ValueError(
                f"Invalid range: start of range {self.start_days} is fewer days "
                f"ago than end of range {self.end_days}."
            )

    def to_date_range(self, today: Optional[_dt.date] = None) -> DateRange:
        today = today or _dt.date.today()
        return DateRange(
            today - _dt.timedelta(days=self.start_days),
            today - _dt.timedelta(days=self.end_days),
        )

    def __str__(self) -> str:
        return f"{self.start_days}{DELIMITER}{self.end_days}"

    @staticmethod
    def from_string(range_str: str) -> "DaysRange":
        parts = range_str.split(DELIMITER)
        if len(parts) != 2:
            raise ValueError(
                f"Couldn't parse the range {range_str!r} using delimiter {DELIMITER!r}."
            )
        return DaysRange(int(parts[0]), int(parts[1]))


def input_paths_within_date_range(
    base_dirs: Sequence[str] | str,
    date_range: DateRange,
    error_on_missing: bool = False,
) -> List[str]:
    """Existing '<base>/yyyy/MM/dd' day directories within the range
    (IOUtils.getInputPathsWithinDateRange semantics: filter missing days
    unless error_on_missing; error when nothing matches)."""
    if isinstance(base_dirs, str):
        base_dirs = [base_dirs]
    out: List[str] = []
    for base in base_dirs:
        paths = [
            os.path.join(base, day.strftime("%Y/%m/%d"))
            for day in date_range.days()
        ]
        if error_on_missing:
            for p in paths:
                if not os.path.exists(p):
                    raise FileNotFoundError(f"Path {p} does not exist")
        out.extend(p for p in paths if os.path.exists(p))
    if not out:
        raise FileNotFoundError(
            f"No data folder found between {date_range.start} and "
            f"{date_range.end} in {list(base_dirs)}"
        )
    return out
