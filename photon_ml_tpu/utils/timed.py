"""Named wall-clock sections (reference: photon-lib .../util/Timed.scala:33-83,
used at every driver/estimator stage).

``timed`` keeps its historical log line but now also opens an ``obs`` span of
the same name, so every existing timed section participates in hierarchical
tracing (parent/child nesting, compile-second attribution, sink output) for
free. With no telemetry sinks registered the span is pure host bookkeeping.
"""

from __future__ import annotations

import contextlib
import logging
import time

from ..obs.tracing import span

logger = logging.getLogger("photon_ml_tpu")


@contextlib.contextmanager
def timed(name: str, level: int = logging.DEBUG, **attrs):
    """Log the section's wall time; extra kwargs become span attributes
    (e.g. ``phase=`` for the timeline profiler's phase attribution)."""
    t0 = time.perf_counter()
    try:
        with span(name, **attrs):
            yield
    finally:
        logger.log(level, "%s took %.3fs", name, time.perf_counter() - t0)
