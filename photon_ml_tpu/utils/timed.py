"""Named wall-clock sections (reference: photon-lib .../util/Timed.scala:33-83,
used at every driver/estimator stage)."""

from __future__ import annotations

import contextlib
import logging
import time

logger = logging.getLogger("photon_ml_tpu")


@contextlib.contextmanager
def timed(name: str, level: int = logging.DEBUG):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        logger.log(level, "%s took %.3fs", name, time.perf_counter() - t0)
