"""Daemon-thread futures for background decode pipelines.

Extracted from cli/train's background validation decode so io/data's chunked
training-data reader can share it (one-part lookahead decode).
"""

from __future__ import annotations

import threading


class DaemonFuture:
    """Future-shaped handle on a fn run in a DAEMON thread.

    Replaces ThreadPoolExecutor for background decodes: executor threads are
    non-daemon and concurrent.futures joins them at interpreter exit, so a
    training crash mid-decode used to block process exit on a full decode
    nobody will consume. A daemon thread is abandoned at exit — a crash
    anywhere exits bounded. The flip side: "cancellation" is only ever
    not-waiting; work that already STARTED runs to completion in the
    background (the thread starts on construction, so a live decode is never
    killed, merely never joined)."""

    def __init__(self, fn):
        self._done = threading.Event()
        self._value = None
        self._error = None

        def _work():
            try:
                self._value = fn()
            # photon: ignore[R4] — future semantics: stored, re-raised in result()
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        self._thread = threading.Thread(
            target=_work, name="photon-bg-decode", daemon=True
        )
        self._thread.start()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("background work still running")
        if self._error is not None:
            raise self._error
        return self._value
