"""Daemon-thread futures, worker pools, and bounded prefetch queues.

Extracted from cli/train's background validation decode so io/data's chunked
training-data reader can share it (one-part lookahead decode).
:class:`PrefetchQueue` generalizes the single lookahead into a bounded-depth
producer lane over an N-worker :class:`WorkerPool`; the sweep pipelining
layer (game/pipeline.py) and the chunked ingest reader both build on it.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Optional, Tuple


class DaemonFuture:
    """Future-shaped handle on a fn run in a DAEMON thread.

    Replaces ThreadPoolExecutor for background decodes: executor threads are
    non-daemon and concurrent.futures joins them at interpreter exit, so a
    training crash mid-decode used to block process exit on a full decode
    nobody will consume. A daemon thread is abandoned at exit — a crash
    anywhere exits bounded. The flip side: "cancellation" is only ever
    not-waiting; work that already STARTED runs to completion in the
    background (the thread starts on construction, so a live decode is never
    killed, merely never joined)."""

    def __init__(self, fn):
        self._done = threading.Event()
        # ownership handoff at the _done barrier: _work (the daemon thread)
        # is the only writer, and result() reads only after _done.wait()
        self._value = None  # photon: thread-confined
        self._error = None  # photon: thread-confined

        def _work():
            try:
                self._value = fn()
            # photon: ignore[R4] — future semantics: stored, re-raised in result()
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        self._thread = threading.Thread(
            target=_work, name="photon-bg-decode", daemon=True
        )
        self._thread.start()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("background work still running")
        if self._error is not None:
            raise self._error
        return self._value


class PoolFuture:
    """Future-shaped handle on a fn submitted to a :class:`WorkerPool`.

    Same ``done()``/``result()`` surface as :class:`DaemonFuture` so callers
    holding either kind (cli/train's validation decode) stay agnostic. The
    fn runs on a pool worker instead of a dedicated thread; the crash
    contract is the pool's (daemon workers, never joined)."""

    def __init__(self):
        self._done = threading.Event()
        self._value = None
        self._error = None

    def _run(self, fn) -> None:
        try:
            self._value = fn()
        # photon: ignore[R4] — future semantics: stored, re-raised in result()
        except BaseException as e:
            self._error = e
        finally:
            self._done.set()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("background work still running")
        if self._error is not None:
            raise self._error
        return self._value


class WorkerPool:
    """``workers`` daemon threads draining a FIFO task deque.

    The fleet-decode analogue of :class:`DaemonFuture`: submissions run in
    submit order (exactly sequential at ``workers=1``), each behind a
    :class:`PoolFuture`. Same crash contract — workers are daemon threads
    that are never joined, so a process crash abandons in-flight work
    instead of blocking exit on it.

    :meth:`close` stops accepting NEW submissions but lets already-queued
    tasks drain: a caller may submit background work and close the pool
    immediately, keeping the handle alive only through the future."""

    def __init__(self, workers: int = 1, name: str = "photon-pool"):
        if workers < 1:
            raise ValueError(f"worker pool size must be >= 1: {workers}")
        self.workers = int(workers)
        self._tasks: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._closed = False
        for k in range(self.workers):
            threading.Thread(
                target=self._work, name=f"{name}-{k}", daemon=True
            ).start()

    def submit(self, fn: Callable[[], object]) -> PoolFuture:
        fut = PoolFuture()
        with self._cv:
            if self._closed:
                raise RuntimeError("WorkerPool is closed")
            self._tasks.append((fn, fut))
            self._cv.notify()
        return fut

    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._tasks:
                    if self._closed:
                        return
                    self._cv.wait()
                fn, fut = self._tasks.popleft()
            fut._run(fn)

    def close(self) -> None:
        """Stop accepting work; queued tasks still drain (daemon threads,
        never joined — in-flight work is abandoned at process exit)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()


class PrefetchQueue:
    """Bounded-depth generalization of :class:`DaemonFuture`'s one-item
    lookahead: ``workers`` pool workers produce ``produce(i)`` for
    ``i in 0..count-1`` (forever, cyclically, when ``cyclic=True``)
    concurrently, a sequencer re-emits finished items in production order,
    and :meth:`get` pops them FIFO. ``workers=1`` (the default) calls
    ``produce`` strictly sequentially in index order — behaviorally
    identical to the original single-daemon-worker queue.

    ``cost``/``budget`` bound the bytes in flight across the WHOLE pipeline:
    queued items, PLUS the item the consumer currently holds, PLUS every
    item any worker is currently producing. An item's cost is charged when
    its index is claimed (before ``produce`` starts) and released when the
    consumer moves past it, so N workers cannot collectively overshoot a
    bounded-RSS cap by starting N decodes at once. An empty pipeline always
    admits one item so progress is possible — the same 2-resident worst
    case (held + one in flight) as the inline double buffer this replaces.
    ``budget_stalls`` counts admissions deferred by the budget;
    ``peak_inflight`` is the high-water mark of charged bytes.

    Depth bounds the pipeline the same way: queued + staged + producing
    items never exceed ``depth``.

    Same crash contract as DaemonFuture: workers are daemon threads, an
    in-flight ``produce`` runs to completion but is never joined
    (:meth:`close` drops queued items without waiting), and a producer
    error is re-emitted in production order and re-raised by the matching
    :meth:`get` — items produced after the failing index are discarded,
    never emitted out of order."""

    def __init__(
        self,
        produce: Callable[[int], object],
        count: int,
        depth: int = 2,
        *,
        cyclic: bool = False,
        cost: Optional[Callable[[int], int]] = None,
        budget: Optional[int] = None,
        name: str = "photon-prefetch",
        workers: int = 1,
        pool: Optional[WorkerPool] = None,
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1: {depth}")
        if count < 1:
            raise ValueError(f"prefetch count must be >= 1: {count}")
        if workers < 1:
            raise ValueError(f"prefetch workers must be >= 1: {workers}")
        self._produce = produce
        self._count = int(count)
        self._depth = int(depth)
        self._cyclic = bool(cyclic)
        self._cost = cost
        self._budget = budget
        # (index, item, cost, error) in production order, ready for get()
        self._q: collections.deque = collections.deque()
        # finished out of order: global claim -> (index, item, cost, error)
        self._staging: dict = {}
        self._next = 0  # next global claim (produce index = claim % count)
        self._emit = 0  # next claim the sequencer re-emits into _q
        self._n_producing = 0
        self._outstanding = 0  # dispatched pool tasks that have not claimed yet
        self._held_cost = 0  # the item the consumer holds still occupies RSS
        self._inflight = 0  # queued + staged + producing + held cost
        self.peak_inflight = 0
        self.budget_stalls = 0
        self._closed = False
        self._exhausted = False
        self._draining = False  # an error is staged: stop claiming/emitting
        self._cv = threading.Condition()
        self._own_pool = pool is None
        self._pool = WorkerPool(workers, name=name) if pool is None else pool
        with self._cv:
            self._dispatch()

    def _claim(self) -> Optional[Tuple[int, int, int]]:
        """Claim the next produce index (under the lock, at task execution
        time) or return None when nothing is admissible — the task then
        no-ops and :meth:`get` re-dispatches when capacity frees up."""
        if self._closed or self._draining:
            return None
        if not self._cyclic and self._next >= self._count:
            return None
        idx = self._next % self._count if self._cyclic else self._next
        pipeline = len(self._q) + len(self._staging) + self._n_producing
        if pipeline >= self._depth:
            return None
        c = int(self._cost(idx)) if self._cost is not None else 0
        if self._budget is not None and pipeline > 0:
            if self._inflight + c > self._budget:
                self.budget_stalls += 1
                return None
        g = self._next
        self._next += 1
        self._n_producing += 1
        self._inflight += c
        self.peak_inflight = max(self.peak_inflight, self._inflight)
        return g, idx, c

    def _sequence(self) -> None:
        """Move contiguously-finished staged items into the FIFO (under the
        lock); stop at an error so it re-raises in production order."""
        while not self._draining and self._emit in self._staging:
            idx, item, c, error = self._staging.pop(self._emit)
            self._q.append((idx, item, c, error))
            self._emit += 1
            if error is not None:
                self._draining = True
        if not self._cyclic and not self._draining and self._emit >= self._count:
            self._exhausted = True

    def _dispatch(self) -> None:
        """Top up outstanding pool tasks to cover free pipeline slots (under
        the lock). Over-dispatch is harmless: a task that finds no
        admissible claim simply no-ops."""
        if self._closed or self._draining or self._exhausted:
            return
        pipeline = len(self._q) + len(self._staging) + self._n_producing
        want = self._depth - pipeline - self._outstanding
        if not self._cyclic:
            want = min(want, self._count - self._next - self._outstanding)
        for _ in range(want):
            self._outstanding += 1
            self._pool.submit(self._task)

    def _task(self) -> None:
        with self._cv:
            self._outstanding -= 1
            claim = self._claim()
            if claim is None:
                return
            g, idx, c = claim
        try:
            item, error = self._produce(idx), None
        # photon: ignore[R4] — future semantics: parked, re-raised in get()
        except BaseException as e:
            item, error = None, e
        with self._cv:
            self._n_producing -= 1
            if self._closed:
                return  # close() already reset the accounting; discard
            self._staging[g] = (idx, item, c, error)
            self._sequence()
            self._cv.notify_all()

    def get(self) -> Tuple[int, object]:
        """Pop the next item in production order (blocks until staged);
        implicitly releases the previously returned item's budget share."""
        with self._cv:
            while not self._q:
                if self._closed:
                    raise RuntimeError("PrefetchQueue is closed")
                if self._exhausted:
                    raise RuntimeError("PrefetchQueue is exhausted")
                self._dispatch()
                self._cv.wait()
            idx, item, c, error = self._q.popleft()
            self._inflight -= self._held_cost
            self._held_cost = c
            self._dispatch()
            self._cv.notify_all()
        if error is not None:
            self.close()
            raise error
        return idx, item

    def qsize(self) -> int:
        with self._cv:
            return len(self._q)

    def close(self) -> None:
        """Stop the workers and drop queued items; an in-flight ``produce``
        runs to completion in the background (never joined)."""
        with self._cv:
            self._closed = True
            self._q.clear()
            self._staging.clear()
            self._inflight = self._held_cost
            self._cv.notify_all()
        if self._own_pool:
            self._pool.close()
