"""Daemon-thread futures and bounded prefetch queues for background pipelines.

Extracted from cli/train's background validation decode so io/data's chunked
training-data reader can share it (one-part lookahead decode).
:class:`PrefetchQueue` generalizes the single lookahead into a bounded-depth
producer lane; the sweep pipelining layer (game/pipeline.py) and the chunked
ingest reader both build on it.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Optional, Tuple


class DaemonFuture:
    """Future-shaped handle on a fn run in a DAEMON thread.

    Replaces ThreadPoolExecutor for background decodes: executor threads are
    non-daemon and concurrent.futures joins them at interpreter exit, so a
    training crash mid-decode used to block process exit on a full decode
    nobody will consume. A daemon thread is abandoned at exit — a crash
    anywhere exits bounded. The flip side: "cancellation" is only ever
    not-waiting; work that already STARTED runs to completion in the
    background (the thread starts on construction, so a live decode is never
    killed, merely never joined)."""

    def __init__(self, fn):
        self._done = threading.Event()
        self._value = None
        self._error = None

        def _work():
            try:
                self._value = fn()
            # photon: ignore[R4] — future semantics: stored, re-raised in result()
            except BaseException as e:
                self._error = e
            finally:
                self._done.set()

        self._thread = threading.Thread(
            target=_work, name="photon-bg-decode", daemon=True
        )
        self._thread.start()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout=None):
        if not self._done.wait(timeout):
            raise TimeoutError("background work still running")
        if self._error is not None:
            raise self._error
        return self._value


class PrefetchQueue:
    """Bounded-depth generalization of :class:`DaemonFuture`'s one-item
    lookahead: a single daemon worker produces ``produce(i)`` for
    ``i in 0..count-1`` (forever, cyclically, when ``cyclic=True``) and parks
    up to ``depth`` finished items in a FIFO; :meth:`get` pops them in
    production order.

    ``cost``/``budget`` optionally bound the bytes in flight: the worker
    stalls while the queued items PLUS the item the consumer currently holds
    plus the next item would exceed ``budget``. An empty queue always admits
    one item so the pipeline can make progress — the same 2-resident worst
    case as the inline double buffer this replaces.

    Same crash contract as DaemonFuture: the worker is a daemon thread, an
    in-flight ``produce`` runs to completion but is never joined, and a
    worker error is parked in order and re-raised by the matching
    :meth:`get`."""

    def __init__(
        self,
        produce: Callable[[int], object],
        count: int,
        depth: int = 2,
        *,
        cyclic: bool = False,
        cost: Optional[Callable[[int], int]] = None,
        budget: Optional[int] = None,
        name: str = "photon-prefetch",
    ):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1: {depth}")
        if count < 1:
            raise ValueError(f"prefetch count must be >= 1: {count}")
        self._produce = produce
        self._count = int(count)
        self._depth = int(depth)
        self._cyclic = bool(cyclic)
        self._cost = cost
        self._budget = budget
        # (index, item, cost, error) in production order
        self._q: collections.deque = collections.deque()
        self._held_cost = 0  # the item the consumer holds still occupies HBM
        self._inflight = 0  # queued + held cost
        self.peak_inflight = 0
        self._closed = False
        self._exhausted = False
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._work, name=name, daemon=True)
        self._thread.start()

    def _admissible(self, next_cost: int) -> bool:
        if len(self._q) >= self._depth:
            return False
        if self._budget is None or not self._q:
            return True
        return self._inflight + next_cost <= self._budget

    def _work(self) -> None:
        i = 0
        while True:
            if not self._cyclic and i >= self._count:
                with self._cv:
                    self._exhausted = True
                    self._cv.notify_all()
                return
            c = int(self._cost(i)) if self._cost is not None else 0
            with self._cv:
                while not self._closed and not self._admissible(c):
                    self._cv.wait()
                if self._closed:
                    return
            try:
                item, error = self._produce(i), None
            # photon: ignore[R4] — future semantics: parked, re-raised in get()
            except BaseException as e:
                item, error = None, e
            with self._cv:
                if self._closed:
                    return
                self._q.append((i, item, c, error))
                self._inflight += c
                self.peak_inflight = max(self.peak_inflight, self._inflight)
                self._cv.notify_all()
                if error is not None:
                    self._exhausted = True
                    return
            i += 1
            if self._cyclic and i >= self._count:
                i = 0

    def get(self) -> Tuple[int, object]:
        """Pop the next item in production order (blocks until staged);
        implicitly releases the previously returned item's budget share."""
        with self._cv:
            while not self._q:
                if self._closed:
                    raise RuntimeError("PrefetchQueue is closed")
                if self._exhausted:
                    raise RuntimeError("PrefetchQueue is exhausted")
                self._cv.wait()
            idx, item, c, error = self._q.popleft()
            self._inflight -= self._held_cost
            self._held_cost = c
            self._cv.notify_all()
        if error is not None:
            self.close()
            raise error
        return idx, item

    def qsize(self) -> int:
        with self._cv:
            return len(self._q)

    def close(self) -> None:
        """Stop the worker and drop queued items; an in-flight ``produce``
        runs to completion in the background (never joined)."""
        with self._cv:
            self._closed = True
            self._q.clear()
            self._inflight = self._held_cost
            self._cv.notify_all()
