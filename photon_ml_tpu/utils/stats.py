"""Per-feature summary statistics.

Reference: photon-lib .../stat/FeatureDataStatistics.scala:44-139 (mean, var,
min, max, numNonZeros per feature) written by
ModelProcessingUtils.writeBasicStatistics as FeatureSummarizationResultAvro
records (GameTrainingDriver.scala:581-612). Also feeds NormalizationContext
construction.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..io.avro import write_avro_file
from ..io.data import RawDataset
from ..io.index_map import IndexMap, split_feature_key
from ..io.schemas import FEATURE_SUMMARIZATION_RESULT_AVRO


def compute_feature_statistics(raw: RawDataset, shard: str) -> Dict[str, np.ndarray]:
    """Weighted-count statistics over a shard's COO features (zeros included
    in mean/variance via implicit zero entries, matching a dense summary)."""
    rows, cols, vals = raw.shard_coo[shard]
    d = raw.shard_dims[shard]
    n = raw.n_rows
    s1 = np.zeros(d)
    s2 = np.zeros(d)
    np.add.at(s1, cols, vals)
    np.add.at(s2, cols, vals * vals)
    nnz = np.bincount(cols, minlength=d).astype(np.float64)
    mean = s1 / max(n, 1)
    var = np.maximum(s2 / max(n, 1) - mean**2, 0.0)
    fmin = np.zeros(d)
    fmax = np.zeros(d)
    np.minimum.at(fmin, cols, vals)
    np.maximum.at(fmax, cols, vals)
    max_mag = np.maximum(np.abs(fmin), np.abs(fmax))
    return {
        "mean": mean,
        "variance": var,
        "min": fmin,
        "max": fmax,
        "num_nonzeros": nnz,
        "max_magnitude": max_mag,
        "count": np.full(d, float(n)),
    }


def save_feature_statistics(path: str, stats: Dict[str, np.ndarray], index_map: IndexMap):
    """Write FeatureSummarizationResultAvro records (one per feature)."""
    d = len(index_map)

    def records():
        for i in range(d):
            key = index_map.get_feature_name(i)
            if key is None:
                continue
            name, term = split_feature_key(key)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(stats["mean"][i]),
                    "variance": float(stats["variance"][i]),
                    "min": float(stats["min"][i]),
                    "max": float(stats["max"][i]),
                    "numNonzeros": float(stats["num_nonzeros"][i]),
                },
            }

    write_avro_file(path, FEATURE_SUMMARIZATION_RESULT_AVRO, records())
