"""Per-feature summary statistics.

Reference: photon-lib .../stat/FeatureDataStatistics.scala:44-139 (mean, var,
min, max, numNonZeros per feature) written by
ModelProcessingUtils.writeBasicStatistics as FeatureSummarizationResultAvro
records (GameTrainingDriver.scala:581-612). Also feeds NormalizationContext
construction.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..io.avro import write_avro_file
from ..io.data import RawDataset
from ..io.index_map import IndexMap, split_feature_key
from ..io.schemas import FEATURE_SUMMARIZATION_RESULT_AVRO
from ..robust.retry import io_call


def compute_feature_statistics(raw: RawDataset, shard: str) -> Dict[str, np.ndarray]:
    """Weighted-count statistics over a shard's COO features (zeros included
    in mean/variance via implicit zero entries, matching a dense summary).

    Multi-process: each host computes moment sums over ITS row slice and the
    d-sized sums are allgathered and combined, so every host returns the
    GLOBAL statistics (the reference computes summaries over the full
    DataFrame, GameTrainingDriver.scala:555-612 — here the cross-host reduce
    is the d-vector exchange, not a row shuffle)."""
    rows, cols, vals = raw.shard_coo[shard]
    d = raw.shard_dims[shard]
    # padded rows (multi-process equal-share) carry no features and must not
    # inflate the count denominator
    n = raw.true_rows if raw.true_rows is not None else raw.n_rows
    s1 = np.zeros(d)
    s2 = np.zeros(d)
    np.add.at(s1, cols, vals)
    np.add.at(s2, cols, vals * vals)
    nnz = np.bincount(cols, minlength=d).astype(np.float64)
    fmin = np.zeros(d)
    fmax = np.zeros(d)
    np.minimum.at(fmin, cols, vals)
    np.maximum.at(fmax, cols, vals)

    import jax

    if jax.process_count() > 1:
        from ..parallel import multihost

        parts = multihost.allgather_object((s1, s2, nnz, fmin, fmax, n))
        s1 = np.sum([p[0] for p in parts], axis=0)
        s2 = np.sum([p[1] for p in parts], axis=0)
        nnz = np.sum([p[2] for p in parts], axis=0)
        fmin = np.min([p[3] for p in parts], axis=0)
        fmax = np.max([p[4] for p in parts], axis=0)
        n = sum(p[5] for p in parts)

    mean = s1 / max(n, 1)
    var = np.maximum(s2 / max(n, 1) - mean**2, 0.0)
    max_mag = np.maximum(np.abs(fmin), np.abs(fmax))
    return {
        "mean": mean,
        "variance": var,
        "min": fmin,
        "max": fmax,
        "num_nonzeros": nnz,
        "max_magnitude": max_mag,
        "count": np.full(d, float(n)),
    }


def save_feature_statistics(path: str, stats: Dict[str, np.ndarray], index_map: IndexMap):
    """Write FeatureSummarizationResultAvro records (one per feature)."""
    d = len(index_map)

    def records():
        for i in range(d):
            key = index_map.get_feature_name(i)
            if key is None:
                continue
            name, term = split_feature_key(key)
            yield {
                "featureName": name,
                "featureTerm": term,
                "metrics": {
                    "mean": float(stats["mean"][i]),
                    "variance": float(stats["variance"][i]),
                    "min": float(stats["min"][i]),
                    "max": float(stats["max"][i]),
                    "numNonzeros": float(stats["num_nonzeros"][i]),
                },
            }

    # atomic via write_avro_file; transient failures retry (Spark task-retry
    # parity — a stats write must not kill a run that just finished training)
    io_call(
        write_avro_file, path, FEATURE_SUMMARIZATION_RESULT_AVRO, list(records()),
        site="io.stats_save",
    )
