"""Optimizer dispatch: config -> solver run.

The functional analogue of the reference's OptimizerFactory + Optimizer.optimize
(photon-api .../optimization/OptimizerFactory.scala:30-74,
photon-lib .../optimization/Optimizer.scala:161-185): computes the relative ->
absolute tolerance conversion from the zero state, dispatches on optimizer
type (LBFGS / OWLQN / LBFGSB / TRON), and runs the whole solve on device.

``value_and_grad`` (and ``hvp`` for TRON) close over their data; whether that
data is a device-sharded global batch (fixed effect) or one lane of a vmapped
per-entity block (random effect) is invisible here — the reference needed a
Distributed/SingleNode class pair for this (SURVEY.md §2.2), we need one
function.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from .common import (
    HvpFn,
    OptimizerConfig,
    OptimizerType,
    SolverResult,
    ValueAndGradFn,
    abs_tolerances,
)
from .lbfgs import solve_lbfgs
from .tron import solve_tron

Array = jnp.ndarray


def optimize(
    value_and_grad: ValueAndGradFn,
    w0: Array,
    config: OptimizerConfig,
    hvp: Optional[HvpFn] = None,
) -> SolverResult:
    loss_tol, grad_tol = abs_tolerances(value_and_grad, w0, config.tolerance)
    kind = config.normalized_type()

    if kind in (OptimizerType.LBFGS, OptimizerType.LBFGSB, OptimizerType.OWLQN):
        box = config.box_constraints
        return solve_lbfgs(
            value_and_grad,
            w0,
            loss_tol,
            grad_tol,
            max_iterations=config.max_iterations,
            num_corrections=config.num_corrections,
            l1_weight=config.l1_weight if kind == OptimizerType.OWLQN else 0.0,
            box_constraints=box,
            max_line_search_iterations=config.max_line_search_iterations,
        )
    if kind == OptimizerType.TRON:
        if hvp is None:
            raise ValueError("TRON requires a Hessian-vector-product function")
        return solve_tron(
            value_and_grad,
            hvp,
            w0,
            loss_tol,
            grad_tol,
            max_iterations=config.max_iterations,
            max_cg_iterations=config.max_cg_iterations,
            max_improvement_failures=config.max_improvement_failures,
            box_constraints=config.box_constraints,
        )
    raise ValueError(f"Unknown optimizer type: {config.optimizer_type!r}")
