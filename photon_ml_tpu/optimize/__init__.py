from .common import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerType,
    SolverResult,
    abs_tolerances,
    project_box,
)
from .lbfgs import solve_lbfgs
from .tron import solve_tron
from .driver import optimize

__all__ = [
    "ConvergenceReason",
    "OptimizerConfig",
    "OptimizerType",
    "SolverResult",
    "abs_tolerances",
    "project_box",
    "solve_lbfgs",
    "solve_tron",
    "optimize",
]
