from .common import (
    ConvergenceReason,
    OptimizerConfig,
    OptimizerType,
    SolverResult,
    abs_tolerances,
    project_box,
)
from .lbfgs import solve_lbfgs
from .tron import solve_tron
from .driver import optimize
from .host_driver import host_optimize, solve_lbfgs_host, solve_tron_host

__all__ = [
    "ConvergenceReason",
    "OptimizerConfig",
    "OptimizerType",
    "SolverResult",
    "abs_tolerances",
    "project_box",
    "solve_lbfgs",
    "solve_tron",
    "optimize",
    "host_optimize",
    "solve_lbfgs_host",
    "solve_tron_host",
]
