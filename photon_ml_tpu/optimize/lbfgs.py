"""Pure-functional L-BFGS and OWL-QN with masked updates.

Replaces the reference's Breeze-backed LBFGS/OWLQN adapters
(photon-lib .../optimization/LBFGS.scala:38-154, OWLQN.scala:39-83) with a
single jit/vmap-safe implementation:

- fixed-size (m, d) correction history with circular indexing (static shapes
  for XLA; m = numCorrections, default 10);
- two-loop recursion preconditioned by the gamma = s.y/y.y scaling;
- strong-Wolfe line search by bisection/expansion (c1=1e-4, c2=0.9) run inside
  ``lax.while_loop`` with masked state so vmapped lanes freeze independently;
- OWL-QN (l1_weight > 0): pseudo-gradient, direction orthant projection, and
  orthant-constrained line-search steps; the correction pairs use the plain
  gradient, convergence uses the pseudo-gradient — matching the OWL-QN
  algorithm the reference delegates to Breeze for;
- box constraints (L-BFGS-B, reference LBFGSB.scala:39-92): gradient
  projection — the "gradient" driving the two-loop direction and the
  convergence test is the projected gradient w - P(w - g), which vanishes
  exactly at bound-held coordinates — with every line-search trial point
  projected onto the box and Armijo measured on the actual displacement
  f(P(w + t*d)) <= f + c1*g.(w_t - w). Unlike clamp-after-step this
  converges to the constrained KKT point when bounds are active.

Every lane of state carries a ``done`` flag; once set, all updates become
no-ops, which is what makes ``jax.vmap(solve_lbfgs, ...)`` correct for the
batched per-entity random-effect solves.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .common import (
    ConvergenceReason,
    SolverResult,
    ValueAndGradFn,
    as_partial,
    check_convergence,
)

Array = jax.Array

_C1 = 1e-4  # Armijo (sufficient decrease)
_C2 = 0.9  # curvature


def _norm(v: Array) -> Array:
    return jnp.sqrt(jnp.sum(v * v))


def _pseudo_gradient(w: Array, g: Array, l1: float) -> Array:
    """OWL-QN pseudo-gradient of f(w) + l1*||w||_1."""
    gp = g + l1
    gm = g - l1
    pg = jnp.where(w > 0, gp, jnp.where(w < 0, gm, 0.0))
    at_zero = jnp.where(gm > 0, gm, jnp.where(gp < 0, gp, 0.0))
    return jnp.where(w == 0, at_zero, pg)


def _two_loop(
    S: Array, Y: Array, rho: Array, count: Array, head: Array, g: Array
) -> Array:
    """Two-loop recursion over a circular history buffer.

    S, Y: [m, d]; rho: [m]; count = #valid pairs; head = index of next write.
    Slot order from newest to oldest: head-1, head-2, ...
    """
    m = S.shape[0]

    def newest_to_oldest(i):
        return (head - 1 - i) % m

    def loop1(i, carry):
        q, alphas = carry
        j = newest_to_oldest(i)
        valid = i < count
        alpha = jnp.where(valid, rho[j] * jnp.dot(S[j], q), 0.0)
        q = q - jnp.where(valid, alpha, 0.0) * Y[j]
        return q, alphas.at[i].set(alpha)

    q, alphas = jax.lax.fori_loop(
        0, m, loop1, (g, jnp.zeros(m, dtype=g.dtype))
    )

    # H0 = gamma * I with gamma from the newest pair
    newest = newest_to_oldest(0)
    ys = jnp.dot(S[newest], Y[newest])
    yy = jnp.dot(Y[newest], Y[newest])
    gamma = jnp.where((count > 0) & (yy > 0), ys / jnp.where(yy > 0, yy, 1.0), 1.0)
    r = gamma * q

    def loop2(i, r):
        # oldest to newest: i runs m-1 .. 0 over the newest_to_oldest index
        idx = m - 1 - i
        j = newest_to_oldest(idx)
        valid = idx < count
        beta = jnp.where(valid, rho[j] * jnp.dot(Y[j], r), 0.0)
        r = r + jnp.where(valid, alphas[idx] - beta, 0.0) * S[j]
        return r

    return jax.lax.fori_loop(0, m, loop2, r)


class _LineSearchState(NamedTuple):
    t: Array
    lo: Array
    hi: Array
    f_t: Array
    g_t: Array
    w_t: Array
    it: Array
    done: Array
    success: Array


def _line_search(
    value_and_grad: ValueAndGradFn,
    w: Array,
    f: Array,
    direction: Array,
    dg: Array,  # directional derivative of the (possibly l1-augmented) objective
    l1: float,
    orthant: Optional[Array],
    max_iters: int,
    box: Optional[Tuple[Array, Array]] = None,
    g_plain: Optional[Array] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Strong-Wolfe bisection line search; returns (w_new, f_new, g_new, success).

    For OWL-QN (orthant is not None) each trial point is projected onto the
    orthant and only the Armijo condition is enforced (standard OWL-QN
    backtracking); f and dg then refer to the l1-augmented objective.

    For L-BFGS-B (box is not None) each trial point is projected onto the box
    and Armijo is measured on the actual displacement
    f_t <= f + c1 * g.(w_t - w) (projected-gradient line search), again with
    no curvature condition.
    """
    dtype = w.dtype
    inf = jnp.asarray(jnp.inf, dtype)

    def trial(t):
        w_t = w + t * direction
        if orthant is not None:
            w_t = jnp.where(w_t * orthant < 0, 0.0, w_t)
        if box is not None:
            w_t = jnp.clip(w_t, box[0], box[1])
        f_t, g_t = value_and_grad(w_t)
        if l1 > 0.0:
            f_t = f_t + l1 * jnp.sum(jnp.abs(w_t))
        return w_t, f_t, g_t

    w0_t, f0_t, g0_t = trial(jnp.asarray(1.0, dtype))

    init = _LineSearchState(
        t=jnp.asarray(1.0, dtype),
        lo=jnp.asarray(0.0, dtype),
        hi=inf,
        f_t=f0_t,
        g_t=g0_t,
        w_t=w0_t,
        it=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
        success=jnp.asarray(False),
    )

    def cond(s: _LineSearchState):
        return jnp.logical_not(s.done)

    def body(s: _LineSearchState):
        if box is not None:
            armijo_ok = s.f_t <= f + _C1 * jnp.dot(g_plain, s.w_t - w)
        else:
            armijo_ok = s.f_t <= f + _C1 * s.t * dg
        if orthant is None and box is None:
            # weak Wolfe (Lewis-Overton bisection scheme): convergent under pure
            # bisection/expansion and still guarantees s.y > 0 for the history
            curv_ok = jnp.dot(s.g_t, direction) >= _C2 * dg
        else:
            curv_ok = jnp.asarray(True)
        accept = armijo_ok & curv_ok & jnp.isfinite(s.f_t)

        # bracket update
        new_hi = jnp.where(armijo_ok & jnp.isfinite(s.f_t), s.hi, s.t)
        new_lo = jnp.where(armijo_ok & jnp.isfinite(s.f_t) & ~curv_ok, s.t, s.lo)
        new_t = jnp.where(
            jnp.isinf(new_hi), 2.0 * new_lo + 1.0, 0.5 * (new_lo + new_hi)
        )
        # if Armijo failed, bisect downward
        new_t = jnp.where(armijo_ok & jnp.isfinite(s.f_t), new_t, 0.5 * (s.lo + s.t))

        hit_max = s.it + 1 >= max_iters
        done = accept | hit_max

        w_t, f_t, g_t = trial(new_t)
        # freeze trial values if done
        return _LineSearchState(
            t=jnp.where(done, s.t, new_t),
            lo=jnp.where(done, s.lo, new_lo),
            hi=jnp.where(done, s.hi, new_hi),
            f_t=jnp.where(done, s.f_t, f_t),
            g_t=jnp.where(done, s.g_t, g_t),
            w_t=jnp.where(done, s.w_t, w_t),
            it=s.it + 1,
            done=done,
            success=s.success | accept,
        )

    final = jax.lax.while_loop(cond, body, init)
    return final.w_t, final.f_t, final.g_t, final.success


class _LBFGSState(NamedTuple):
    w: Array
    f: Array  # objective incl. l1 term if OWL-QN
    g: Array  # plain gradient of the smooth part
    it: Array
    done: Array
    reason: Array
    S: Array
    Y: Array
    rho: Array
    count: Array
    head: Array
    loss_history: Array
    grad_norm_history: Array


@partial(
    jax.jit,
    static_argnames=(
        "max_iterations",
        "num_corrections",
        "l1_weight",
        "max_line_search_iterations",
        "has_box",
    ),
)
def _solve(
    value_and_grad: ValueAndGradFn,
    w0: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    max_iterations: int,
    num_corrections: int,
    l1_weight: float,
    max_line_search_iterations: int,
    has_box: bool,
    box_lower: Array,
    box_upper: Array,
) -> SolverResult:
    d = w0.shape[0]
    m = num_corrections
    dtype = w0.dtype
    box = (box_lower, box_upper) if has_box else None
    l1 = l1_weight

    def full_objective(w):
        f, g = value_and_grad(w)
        if l1 > 0.0:
            f = f + l1 * jnp.sum(jnp.abs(w))
        return f, g

    if box is not None:
        w0 = jnp.clip(w0, box[0], box[1])  # start feasible
    f0, g0 = full_objective(w0)

    hist = jnp.full((max_iterations + 1,), jnp.nan, dtype)

    def effective_grad(w, g):
        if l1 > 0.0:
            return _pseudo_gradient(w, g, l1)
        if box is not None:
            # projected gradient: zero at bound-held coordinates, so both the
            # quasi-Newton direction and the convergence test respect the
            # active set (LBFGSB.scala:39-92 semantics)
            return w - jnp.clip(w - g, box[0], box[1])
        return g

    pg0 = effective_grad(w0, g0)

    init = _LBFGSState(
        w=w0,
        f=f0,
        g=g0,
        it=jnp.asarray(0, jnp.int32),
        done=jnp.asarray(False),
        reason=jnp.asarray(0, jnp.int32),
        S=jnp.zeros((m, d), dtype),
        Y=jnp.zeros((m, d), dtype),
        rho=jnp.zeros((m,), dtype),
        count=jnp.asarray(0, jnp.int32),
        head=jnp.asarray(0, jnp.int32),
        loss_history=hist.at[0].set(f0),
        grad_norm_history=hist.at[0].set(_norm(pg0)),
    )

    def cond(s: _LBFGSState):
        return jnp.logical_not(jnp.all(s.done))

    def body(s: _LBFGSState):
        pg = effective_grad(s.w, s.g)
        direction = -_two_loop(s.S, s.Y, s.rho, s.count, s.head, pg)
        if l1 > 0.0:
            # project direction into the descent orthant of -pg
            direction = jnp.where(direction * pg >= 0, 0.0, direction)
        dg = jnp.dot(direction, pg)
        # fall back to steepest descent if not a descent direction
        bad = dg >= 0
        direction = jnp.where(bad, -pg, direction)
        dg = jnp.where(bad, -jnp.dot(pg, pg), dg)

        orthant = None
        if l1 > 0.0:
            orthant = jnp.where(s.w != 0, jnp.sign(s.w), -jnp.sign(pg))

        w_new, f_new, g_new, ls_ok = _line_search(
            value_and_grad, s.w, s.f, direction, dg, l1, orthant,
            max_line_search_iterations, box=box, g_plain=s.g,
        )

        improved = ls_ok & (f_new < s.f)

        # history update (only when improved)
        s_vec = w_new - s.w
        y_vec = g_new - s.g
        sy = jnp.dot(s_vec, y_vec)
        store = improved & (sy > 1e-10 * _norm(y_vec) ** 2)
        S = jnp.where(store, s.S.at[s.head].set(s_vec), s.S)
        Y = jnp.where(store, s.Y.at[s.head].set(y_vec), s.Y)
        rho = jnp.where(
            store, s.rho.at[s.head].set(1.0 / jnp.where(sy != 0, sy, 1.0)), s.rho
        )
        head = jnp.where(store, (s.head + 1) % m, s.head)
        count = jnp.where(store, jnp.minimum(s.count + 1, m), s.count)

        it_new = s.it + 1
        pg_new = effective_grad(w_new, g_new)
        reason = check_convergence(
            it_new,
            max_iterations,
            f_new,
            s.f,
            _norm(pg_new),
            loss_abs_tol,
            grad_abs_tol,
            objective_not_improving=~improved,
        )
        newly_done = reason != 0

        # masked commit: frozen lanes keep their state
        keep = s.done
        sel = lambda a, b: jnp.where(keep, a, b)
        w_out = sel(s.w, jnp.where(improved, w_new, s.w))
        f_out = sel(s.f, jnp.where(improved, f_new, s.f))
        g_out = sel(s.g, jnp.where(improved, g_new, s.g))
        it_out = jnp.where(keep, s.it, it_new)
        lh = jnp.where(keep, s.loss_history, s.loss_history.at[it_new].set(f_out))
        gh = jnp.where(
            keep,
            s.grad_norm_history,
            s.grad_norm_history.at[it_new].set(_norm(effective_grad(w_out, g_out))),
        )

        return _LBFGSState(
            w=w_out,
            f=f_out,
            g=g_out,
            it=it_out,
            done=keep | newly_done,
            reason=jnp.where(keep, s.reason, reason).astype(jnp.int32),
            S=jnp.where(keep, s.S, S),
            Y=jnp.where(keep, s.Y, Y),
            rho=jnp.where(keep, s.rho, rho),
            count=jnp.where(keep, s.count, count),
            head=jnp.where(keep, s.head, head),
            loss_history=lh,
            grad_norm_history=gh,
        )

    final = jax.lax.while_loop(cond, body, init)
    pg_final = effective_grad(final.w, final.g)
    return SolverResult(
        coefficients=final.w,
        loss=final.f,
        gradient=pg_final,
        iterations=final.it,
        reason=final.reason,
        loss_history=final.loss_history,
        grad_norm_history=final.grad_norm_history,
    )


def solve_lbfgs(
    value_and_grad: ValueAndGradFn,
    w0: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    max_iterations: int = 100,
    num_corrections: int = 10,
    l1_weight: float = 0.0,
    box_constraints: Optional[Tuple[Array, Array]] = None,
    max_line_search_iterations: int = 25,
) -> SolverResult:
    """Minimize f(w) (+ l1*||w||_1 when ``l1_weight`` > 0) starting at w0.

    ``value_and_grad`` must be a pure fn of w (closing over its batch); the
    absolute tolerances come from :func:`photon_ml_tpu.optimize.common.abs_tolerances`.
    """
    has_box = box_constraints is not None
    zero = jnp.zeros_like(w0)
    lower, upper = box_constraints if has_box else (zero, zero)
    return _solve(
        as_partial(value_and_grad),
        w0,
        jnp.asarray(loss_abs_tol, w0.dtype),
        jnp.asarray(grad_abs_tol, w0.dtype),
        max_iterations,
        num_corrections,
        float(l1_weight),
        max_line_search_iterations,
        has_box,
        lower,
        upper,
    )
