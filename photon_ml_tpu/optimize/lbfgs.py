"""Pure-functional L-BFGS and OWL-QN with masked updates.

Replaces the reference's Breeze-backed LBFGS/OWLQN adapters
(photon-lib .../optimization/LBFGS.scala:38-154, OWLQN.scala:39-83) with a
single jit/vmap-safe implementation:

- fixed-size (m, d) correction history with circular indexing (static shapes
  for XLA; m = numCorrections, default 10);
- two-loop recursion preconditioned by the gamma = s.y/y.y scaling;
- strong-Wolfe line search by bisection/expansion (c1=1e-4, c2=0.9) run inside
  ``lax.while_loop`` with masked state so vmapped lanes freeze independently;
- OWL-QN (l1_weight > 0): pseudo-gradient, direction orthant projection, and
  orthant-constrained line-search steps; the correction pairs use the plain
  gradient, convergence uses the pseudo-gradient — matching the OWL-QN
  algorithm the reference delegates to Breeze for;
- box constraints (L-BFGS-B, reference LBFGSB.scala:39-92): gradient
  projection — the "gradient" driving the two-loop direction and the
  convergence test is the projected gradient w - P(w - g), which vanishes
  exactly at bound-held coordinates — with every line-search trial point
  projected onto the box and Armijo measured on the actual displacement
  f(P(w + t*d)) <= f + c1*g.(w_t - w). Unlike clamp-after-step this
  converges to the constrained KKT point when bounds are active.

Every lane of state carries a ``done`` flag; once set, all updates become
no-ops, which is what makes ``jax.vmap(solve_lbfgs, ...)`` correct for the
batched per-entity random-effect solves.

Two batching modes serve the random-effect solve (SURVEY.md §2.1 P8):

- ``vmap(solve_lbfgs)`` over entity-leading blocks ``[E, K, S]`` — the
  original path, exact per-entity history bookkeeping;
- ``solve_lbfgs(..., batched=True)`` over **entity-minor** stacks: ``w`` is
  ``[S, E]`` and every reduction runs over axis 0, so the entity axis rides
  the TPU's 128-lane dimension regardless of S. With S=32 the entity-leading
  layout wastes 3/4 of every vector lane; entity-minor is fully packed. The
  one semantic difference: the correction history uses a shared circular
  cursor with per-lane validity (``rho == 0`` marks an invalid pair) instead
  of per-lane cursors, which only diverges in the rare curvature-guard case
  (``s.y`` too small on an improving step) — the optimum reached is the same.

The lane shape is fully generic (``lanes = jnp.shape(f0)``, reductions over
axis 0), so ``batched=True`` also drives lambda-lane stacks for lane-batched
hyperparameter sweeps (game/lanes.py): ``w`` is ``[d, L]`` with one reg
candidate per lane of a shared objective, or ``[S, E, L]`` for entity x
lambda random-effect stacks. Masked commits are what make the sweep safe —
a converged or diverged lambda lane freezes at its last committed iterate
(per-lane ``ConvergenceReason``) without stalling or perturbing neighbors.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import obs
from .common import (
    ConvergenceReason,
    SolverResult,
    ValueAndGradFn,
    _norm,
    _vdot,
    as_partial,
    check_convergence,
    finite_state,
)

Array = jax.Array

_C1 = 1e-4  # Armijo (sufficient decrease)
_C2 = 0.9  # curvature




def _pseudo_gradient(w: Array, g: Array, l1: float) -> Array:
    """OWL-QN pseudo-gradient of f(w) + l1*||w||_1."""
    gp = g + l1
    gm = g - l1
    pg = jnp.where(w > 0, gp, jnp.where(w < 0, gm, 0.0))
    at_zero = jnp.where(gm > 0, gm, jnp.where(gp < 0, gp, 0.0))
    return jnp.where(w == 0, at_zero, pg)


def _two_loop(
    S: Array, Y: Array, rho: Array, count: Array, head: Array, g: Array,
    unroll: bool = False,
) -> Array:
    """Two-loop recursion over a circular history buffer.

    S, Y: [m, d]; rho: [m]; count = #valid pairs; head = index of next write.
    Slot order from newest to oldest: head-1, head-2, ...

    ``unroll=True`` (the batched entity-minor mode) runs two fully-unrolled
    ``lax.scan``s over the history rotated into newest-first order (``roll``
    compiles to two slices + concat, not a gather). Unrolling matters there:
    the recursion is a dependency chain of 2m small ops, and a rolled
    ``fori_loop`` pays ms-scale per-step scheduling overhead on [d, E] stacks
    (measured ~11x on [32, 14k]). The vmapped/single-problem path keeps the
    opaque ``fori_loop``: it isolates the recursion from surrounding fusion,
    which is what keeps per-entity results bit-identical across bucket shapes
    (tests/test_re_build.py bucketed-vs-flat exactness).
    """
    m = S.shape[0]

    if unroll:
        # rotate so index 0 is the newest pair (head - 1), 1 the next, ...
        Sn = jnp.flip(jnp.roll(S, -head, axis=0), axis=0)
        Yn = jnp.flip(jnp.roll(Y, -head, axis=0), axis=0)
        rhon = jnp.flip(jnp.roll(rho, -head, axis=0), axis=0)
        valid = jnp.arange(m) < count  # newest-first validity

        def loop1s(q, x):
            Sj, Yj, rhoj, vld = x
            alpha = jnp.where(vld, rhoj * _vdot(Sj, q), 0.0)
            q = q - alpha * Yj
            return q, alpha

        q, alphas = jax.lax.scan(loop1s, g, (Sn, Yn, rhon, valid), unroll=m)

        # gamma from the newest pair; an invalid batched-mode pair stores
        # zeros, so the yy > 0 guard falls back to gamma = 1 per lane
        ys = _vdot(Sn[0], Yn[0])
        yy = _vdot(Yn[0], Yn[0])
        gamma = jnp.where(
            (count > 0) & (yy > 0), ys / jnp.where(yy > 0, yy, 1.0), 1.0
        )
        r = gamma * q

        def loop2s(r, x):
            Sj, Yj, rhoj, vld, alpha = x
            beta = jnp.where(vld, rhoj * _vdot(Yj, r), 0.0)
            r = r + jnp.where(vld, alpha - beta, 0.0) * Sj
            return r, None

        # oldest to newest = reverse scan over the newest-first order
        r, _ = jax.lax.scan(
            loop2s, r, (Sn, Yn, rhon, valid, alphas), reverse=True, unroll=m
        )
        return r

    def newest_to_oldest(i):
        return (head - 1 - i) % m

    def loop1(i, carry):
        q, alphas = carry
        j = newest_to_oldest(i)
        valid = i < count
        alpha = jnp.where(valid, rho[j] * _vdot(S[j], q), 0.0)
        q = q - jnp.where(valid, alpha, 0.0) * Y[j]
        return q, alphas.at[i].set(alpha)

    q, alphas = jax.lax.fori_loop(
        0, m, loop1, (g, jnp.zeros((m,) + g.shape[1:], dtype=g.dtype))
    )

    newest = newest_to_oldest(0)
    ys = _vdot(S[newest], Y[newest])
    yy = _vdot(Y[newest], Y[newest])
    gamma = jnp.where((count > 0) & (yy > 0), ys / jnp.where(yy > 0, yy, 1.0), 1.0)
    r = gamma * q

    def loop2(i, r):
        # oldest to newest: i runs m-1 .. 0 over the newest_to_oldest index
        idx = m - 1 - i
        j = newest_to_oldest(idx)
        valid = idx < count
        beta = jnp.where(valid, rho[j] * _vdot(Y[j], r), 0.0)
        r = r + jnp.where(valid, alphas[idx] - beta, 0.0) * S[j]
        return r

    return jax.lax.fori_loop(0, m, loop2, r)


class _LineSearchState(NamedTuple):
    t: Array
    lo: Array
    hi: Array
    f_t: Array
    g_t: Array
    w_t: Array
    it: Array
    done: Array
    success: Array


def _line_search(
    value_and_grad: ValueAndGradFn,
    w: Array,
    f: Array,
    direction: Array,
    dg: Array,  # directional derivative of the (possibly l1-augmented) objective
    l1: float,
    orthant: Optional[Array],
    max_iters: int,
    box: Optional[Tuple[Array, Array]] = None,
    g_plain: Optional[Array] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Strong-Wolfe bisection line search; returns (w_new, f_new, g_new, success).

    For OWL-QN (orthant is not None) each trial point is projected onto the
    orthant and only the Armijo condition is enforced (standard OWL-QN
    backtracking); f and dg then refer to the l1-augmented objective.

    For L-BFGS-B (box is not None) each trial point is projected onto the box
    and Armijo is measured on the actual displacement
    f_t <= f + c1 * g.(w_t - w) (projected-gradient line search), again with
    no curvature condition.
    """
    dtype = w.dtype
    # lane shape comes from f: () for a single problem, [E] for entity-minor
    lanes = jnp.shape(f)

    def trial(t):
        w_t = w + t * direction
        if orthant is not None:
            w_t = jnp.where(w_t * orthant < 0, 0.0, w_t)
        if box is not None:
            w_t = jnp.clip(w_t, box[0], box[1])
        f_t, g_t = value_and_grad(w_t)
        if l1 > 0.0:
            f_t = f_t + l1 * jnp.sum(jnp.abs(w_t), axis=0)
        return w_t, f_t, g_t

    w0_t, f0_t, g0_t = trial(jnp.asarray(1.0, dtype))

    init = _LineSearchState(
        t=jnp.full(lanes, 1.0, dtype),
        lo=jnp.zeros(lanes, dtype),
        hi=jnp.full(lanes, jnp.inf, dtype),
        f_t=f0_t,
        g_t=g0_t,
        w_t=w0_t,
        it=jnp.asarray(0, jnp.int32),
        done=jnp.zeros(lanes, bool),
        success=jnp.zeros(lanes, bool),
    )

    def cond(s: _LineSearchState):
        return jnp.logical_not(jnp.all(s.done))

    def body(s: _LineSearchState):
        if box is not None:
            armijo_ok = s.f_t <= f + _C1 * _vdot(g_plain, s.w_t - w)
        else:
            armijo_ok = s.f_t <= f + _C1 * s.t * dg
        if orthant is None and box is None:
            # weak Wolfe (Lewis-Overton bisection scheme): convergent under pure
            # bisection/expansion and still guarantees s.y > 0 for the history
            curv_ok = _vdot(s.g_t, direction) >= _C2 * dg
        else:
            curv_ok = jnp.ones(lanes, bool)
        accept = armijo_ok & curv_ok & jnp.isfinite(s.f_t)

        # bracket update
        new_hi = jnp.where(armijo_ok & jnp.isfinite(s.f_t), s.hi, s.t)
        new_lo = jnp.where(armijo_ok & jnp.isfinite(s.f_t) & ~curv_ok, s.t, s.lo)
        new_t = jnp.where(
            jnp.isinf(new_hi), 2.0 * new_lo + 1.0, 0.5 * (new_lo + new_hi)
        )
        # if Armijo failed, bisect downward
        new_t = jnp.where(armijo_ok & jnp.isfinite(s.f_t), new_t, 0.5 * (s.lo + s.t))

        hit_max = s.it + 1 >= max_iters
        done = accept | hit_max

        w_t, f_t, g_t = trial(new_t)
        # freeze trial values if done
        return _LineSearchState(
            t=jnp.where(done, s.t, new_t),
            lo=jnp.where(done, s.lo, new_lo),
            hi=jnp.where(done, s.hi, new_hi),
            f_t=jnp.where(done, s.f_t, f_t),
            g_t=jnp.where(done, s.g_t, g_t),
            w_t=jnp.where(done, s.w_t, w_t),
            it=s.it + 1,
            done=done,
            success=s.success | accept,
        )

    final = jax.lax.while_loop(cond, body, init)
    return final.w_t, final.f_t, final.g_t, final.success


class _LBFGSState(NamedTuple):
    w: Array
    f: Array  # objective incl. l1 term if OWL-QN
    g: Array  # plain gradient of the smooth part
    it: Array
    k: Array  # global loop counter (scalar; == it for never-frozen lanes)
    done: Array
    reason: Array
    S: Array
    Y: Array
    rho: Array
    count: Array
    head: Array
    loss_history: Array
    grad_norm_history: Array


@partial(
    jax.jit,
    static_argnames=(
        "max_iterations",
        "num_corrections",
        "l1_weight",
        "max_line_search_iterations",
        "has_box",
        "batched",
    ),
)
def _solve(
    value_and_grad: ValueAndGradFn,
    w0: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    max_iterations: int,
    num_corrections: int,
    l1_weight: float,
    max_line_search_iterations: int,
    has_box: bool,
    box_lower: Array,
    box_upper: Array,
    batched: bool = False,
) -> SolverResult:
    m = num_corrections
    dtype = w0.dtype
    box = (box_lower, box_upper) if has_box else None
    l1 = l1_weight

    def full_objective(w):
        f, g = value_and_grad(w)
        if l1 > 0.0:
            f = f + l1 * jnp.sum(jnp.abs(w), axis=0)
        return f, g

    if box is not None:
        w0 = jnp.clip(w0, box[0], box[1])  # start feasible
    f0, g0 = full_objective(w0)
    lanes = jnp.shape(f0)  # () single problem / [E] entity-minor batch

    hist = jnp.full((max_iterations + 1,) + lanes, jnp.nan, dtype)

    def effective_grad(w, g):
        if l1 > 0.0:
            return _pseudo_gradient(w, g, l1)
        if box is not None:
            # projected gradient: zero at bound-held coordinates, so both the
            # quasi-Newton direction and the convergence test respect the
            # active set (LBFGSB.scala:39-92 semantics)
            return w - jnp.clip(w - g, box[0], box[1])
        return g

    pg0 = effective_grad(w0, g0)

    # a lane whose data is already corrupt has no good iterate to roll back
    # to: freeze it at w0 immediately instead of letting NaN flow through the
    # two-loop recursion (every comparison against NaN is False, so nothing
    # downstream would ever catch it)
    bad0 = ~finite_state(f0, g0) & jnp.ones(lanes, bool)

    init = _LBFGSState(
        w=w0,
        f=f0,
        g=g0,
        it=jnp.zeros(lanes, jnp.int32),
        k=jnp.asarray(0, jnp.int32),
        done=bad0,
        reason=jnp.where(
            bad0, int(ConvergenceReason.NUMERICAL_DIVERGENCE), 0
        ).astype(jnp.int32),
        S=jnp.zeros((m,) + w0.shape, dtype),
        Y=jnp.zeros((m,) + w0.shape, dtype),
        rho=jnp.zeros((m,) + lanes, dtype),
        count=jnp.asarray(0, jnp.int32) if batched else jnp.zeros(lanes, jnp.int32),
        head=jnp.asarray(0, jnp.int32) if batched else jnp.zeros(lanes, jnp.int32),
        loss_history=hist.at[0].set(f0),
        grad_norm_history=hist.at[0].set(_norm(pg0)),
    )

    def cond(s: _LBFGSState):
        return jnp.logical_not(jnp.all(s.done))

    def body(s: _LBFGSState):
        pg = effective_grad(s.w, s.g)
        direction = -_two_loop(s.S, s.Y, s.rho, s.count, s.head, pg, unroll=batched)
        if l1 > 0.0:
            # project direction into the descent orthant of -pg
            direction = jnp.where(direction * pg >= 0, 0.0, direction)
        dg = _vdot(direction, pg)
        # fall back to steepest descent if not a descent direction
        bad = dg >= 0
        direction = jnp.where(bad, -pg, direction)
        dg = jnp.where(bad, -_vdot(pg, pg), dg)

        orthant = None
        if l1 > 0.0:
            orthant = jnp.where(s.w != 0, jnp.sign(s.w), -jnp.sign(pg))

        w_new, f_new, g_new, ls_ok = _line_search(
            value_and_grad, s.w, s.f, direction, dg, l1, orthant,
            max_line_search_iterations, box=box, g_plain=s.g,
        )

        # a non-finite trial outcome is numerical divergence: the masked
        # commit below keeps the last good iterate (rollback is free), and
        # excluding the lane from `improved` refuses the corrupted (s, y)
        # correction pair
        finite_new = finite_state(f_new, g_new)
        improved = ls_ok & (f_new < s.f) & finite_new

        # history update (only when improved)
        s_vec = w_new - s.w
        y_vec = g_new - s.g
        sy = _vdot(s_vec, y_vec)
        store = improved & (sy > 1e-10 * _norm(y_vec) ** 2)
        keep = s.done
        if batched:
            # shared circular cursor: every iteration writes the slot for all
            # lanes; a lane that must not store marks its pair invalid with
            # rho = 0 (the two-loop weights every history term by rho, so an
            # invalid pair contributes exactly nothing, and the gamma guard
            # falls back to 1 on all-zero newest pairs)
            S = s.S.at[s.head].set(jnp.where(store, s_vec, 0.0))
            Y = s.Y.at[s.head].set(jnp.where(store, y_vec, 0.0))
            rho = s.rho.at[s.head].set(
                jnp.where(store, 1.0 / jnp.where(sy != 0, sy, 1.0), 0.0)
            )
            head = (s.head + 1) % m
            count = jnp.minimum(s.count + 1, m)
        else:
            S = jnp.where(store, s.S.at[s.head].set(s_vec), s.S)
            Y = jnp.where(store, s.Y.at[s.head].set(y_vec), s.Y)
            rho = jnp.where(
                store, s.rho.at[s.head].set(1.0 / jnp.where(sy != 0, sy, 1.0)), s.rho
            )
            head = jnp.where(store & ~keep, (s.head + 1) % m, s.head)
            count = jnp.where(store & ~keep, jnp.minimum(s.count + 1, m), s.count)
            S = jnp.where(keep, s.S, S)
            Y = jnp.where(keep, s.Y, Y)
            rho = jnp.where(keep, s.rho, rho)

        it_new = s.it + 1
        pg_new = effective_grad(w_new, g_new)
        reason = check_convergence(
            it_new,
            max_iterations,
            f_new,
            s.f,
            _norm(pg_new),
            loss_abs_tol,
            grad_abs_tol,
            objective_not_improving=~improved,
            diverged=~finite_new,
        )
        newly_done = reason != 0

        # masked commit: frozen lanes keep their state
        sel = lambda a, b: jnp.where(keep, a, b)
        w_out = sel(s.w, jnp.where(improved, w_new, s.w))
        f_out = sel(s.f, jnp.where(improved, f_new, s.f))
        g_out = sel(s.g, jnp.where(improved, g_new, s.g))
        it_out = jnp.where(keep, s.it, it_new)
        # history writes go at the global counter row (active lanes all sit at
        # it == k): a row-mask select handles per-lane freezing without
        # per-lane scatter indices
        k_new = s.k + 1
        row = (
            jnp.arange(max_iterations + 1) == k_new
        ).reshape((max_iterations + 1,) + (1,) * len(lanes))
        write = row & ~keep
        lh = jnp.where(write, f_out, s.loss_history)
        gh = jnp.where(write, _norm(effective_grad(w_out, g_out)), s.grad_norm_history)

        return _LBFGSState(
            w=w_out,
            f=f_out,
            g=g_out,
            it=it_out,
            k=k_new,
            done=keep | newly_done,
            reason=jnp.where(keep, s.reason, reason).astype(jnp.int32),
            S=S,
            Y=Y,
            rho=rho,
            count=count,
            head=head,
            loss_history=lh,
            grad_norm_history=gh,
        )

    final = jax.lax.while_loop(cond, body, init)
    pg_final = effective_grad(final.w, final.g)
    return SolverResult(
        coefficients=final.w,
        loss=final.f,
        gradient=pg_final,
        iterations=final.it,
        reason=final.reason,
        loss_history=final.loss_history,
        grad_norm_history=final.grad_norm_history,
    )


def solve_lbfgs(
    value_and_grad: ValueAndGradFn,
    w0: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    max_iterations: int = 100,
    num_corrections: int = 10,
    l1_weight: float = 0.0,
    box_constraints: Optional[Tuple[Array, Array]] = None,
    max_line_search_iterations: int = 25,
    batched: bool = False,
) -> SolverResult:
    """Minimize f(w) (+ l1*||w||_1 when ``l1_weight`` > 0) starting at w0.

    ``value_and_grad`` must be a pure fn of w (closing over its batch); the
    absolute tolerances come from :func:`photon_ml_tpu.optimize.common.abs_tolerances`.

    ``batched=True`` solves an entity-minor stack of independent problems in
    lockstep: ``w0`` is ``[d, E]``, ``value_and_grad`` maps ``[d, E] ->
    ([E], [d, E])``, and the tolerances are per-lane ``[E]``.
    """
    has_box = box_constraints is not None
    zero = jnp.zeros_like(w0)
    lower, upper = box_constraints if has_box else (zero, zero)
    result = _solve(
        as_partial(value_and_grad),
        w0,
        jnp.asarray(loss_abs_tol, w0.dtype),
        jnp.asarray(grad_abs_tol, w0.dtype),
        max_iterations,
        num_corrections,
        float(l1_weight),
        max_line_search_iterations,
        has_box,
        lower,
        upper,
        batched,
    )
    obs.record_solver_metrics("lbfgs", result)
    return result
