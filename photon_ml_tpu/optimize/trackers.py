"""Optimization trackers: per-coordinate solve summaries for logging.

Reference: photon-api .../optimization/FixedEffectOptimizationTracker.scala:31
(wraps one solve's state history), RandomEffectOptimizationTracker.scala
(aggregates the per-entity solves: convergence-reason histogram + iteration
StatCounter; time-per-entity stats do not exist here because all entities
advance in LOCKSTEP through one vmapped solver — wall-clock is a property of
the whole block, which the Timed sections already record), and
CoordinateDescent.logOptimizationSummary (photon-lib
.../algorithm/CoordinateDescent.scala:230-248).

The reason histogram is enum-driven (``ConvergenceReason(int(u)).name``), so
lanes frozen by the divergence defense show up as NUMERICAL_DIVERGENCE rows
here with no tracker-side changes; ``obs.record_solver_metrics`` additionally
routes that reason into ``photon_solver_diverged_lanes_total``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import numpy as np

from ..analysis.runtime import logged_fetch
from .common import ConvergenceReason, SolverResult


@dataclasses.dataclass(frozen=True)
class StatCounter:
    """Spark StatCounter equivalent: count/mean/stdev/max/min of a sample."""

    count: int
    mean: float
    stdev: float
    max: float
    min: float

    @classmethod
    def of(cls, a: np.ndarray) -> "StatCounter":
        a = np.asarray(a, dtype=np.float64).ravel()
        if a.size == 0:
            return cls(0, 0.0, 0.0, 0.0, 0.0)
        return cls(
            count=int(a.size),
            mean=float(a.mean()),
            stdev=float(a.std()),
            max=float(a.max()),
            min=float(a.min()),
        )

    def __str__(self) -> str:
        return (
            f"(count: {self.count}, mean: {self.mean:.6g}, "
            f"stdev: {self.stdev:.6g}, max: {self.max:.6g}, min: {self.min:.6g})"
        )


@dataclasses.dataclass(frozen=True)
class FixedEffectOptimizationTracker:
    """One whole-dataset solve (FixedEffectOptimizationTracker.scala:31)."""

    result: SolverResult

    def to_summary_string(self) -> str:
        r = self.result
        reason_v, iters_v, loss_v, history = logged_fetch(
            "tracker_summary", (r.reason, r.iterations, r.loss, r.loss_history)
        )
        reason = ConvergenceReason(int(reason_v)).name
        losses = np.asarray(history, dtype=np.float64)
        losses = losses[np.isfinite(losses)]
        return (
            f"Convergence reason: {reason}\n"
            f"Iterations: {int(iters_v)}\n"
            f"Loss: {float(loss_v):.6g}"
            + (f" (initial {losses[0]:.6g})" if losses.size else "")
        )


@dataclasses.dataclass(frozen=True)
class RandomEffectOptimizationTracker:
    """Aggregate of the vmapped per-entity solves
    (RandomEffectOptimizationTracker.scala: convergence-reason counts +
    iteration stats over entities).

    The aggregates are LAZY: constructing a tracker must not fetch device
    arrays — trackers are built inside the coordinate-descent hot loop every
    sweep, and a host fetch there stalls the device pipeline for a full
    round trip (measured ~100-165 ms through the remote-harness link). The
    [E]-sized fetches happen on first access, typically when logs are
    enabled or the caller inspects the finished result."""

    result: SolverResult
    entity_mask: Optional[np.ndarray] = None

    def _aggregates(self):
        cached = self.__dict__.get("_agg")
        if cached is None:
            reasons, iters = logged_fetch(
                "tracker_aggregates", (self.result.reason, self.result.iterations)
            )
            reasons = np.ravel(reasons)
            iters = np.ravel(iters)
            if self.entity_mask is not None:
                mask = np.asarray(self.entity_mask, dtype=bool).ravel()
                reasons, iters = reasons[mask], iters[mask]
            uniq, counts = np.unique(reasons, return_counts=True)
            hist = {
                ConvergenceReason(int(u)).name: int(c)
                for u, c in zip(uniq, counts)
            }
            cached = (hist, StatCounter.of(iters))
            object.__setattr__(self, "_agg", cached)
        return cached

    @property
    def convergence_reasons(self) -> Dict[str, int]:
        return self._aggregates()[0]

    @property
    def iterations_stats(self) -> StatCounter:
        return self._aggregates()[1]

    @classmethod
    def from_result(
        cls, result: SolverResult, entity_mask: Optional[np.ndarray] = None
    ) -> "RandomEffectOptimizationTracker":
        return cls(result=result, entity_mask=entity_mask)

    def to_summary_string(self) -> str:
        return (
            f"Convergence reasons stats: {self.convergence_reasons}\n"
            f"Number of iterations stats: {self.iterations_stats}"
        )


def build_tracker(coordinate, result: Optional[SolverResult]):
    """SolverResult -> the right tracker for a coordinate (None for locked
    ModelCoordinates, which never train). No device fetch happens here —
    the reason array's NDIM distinguishes fixed (scalar) from per-entity
    results, and shape metadata is host-known."""
    if result is None:
        return None
    if getattr(result.reason, "ndim", 0) == 0:
        return FixedEffectOptimizationTracker(result=result)
    dataset = getattr(coordinate, "dataset", None)
    counts = getattr(dataset, "entity_counts", None)
    mask = None if counts is None else np.asarray(counts)[: result.reason.shape[0]] > 0
    return RandomEffectOptimizationTracker.from_result(result, entity_mask=mask)


def record_tracker_metrics(registry, coordinate_name: str, tracker) -> None:
    """Fold one coordinate update's tracker into the metrics registry:
    ``photon_cd_iterations`` (StatCounter-compatible summary) and
    ``photon_cd_convergence_reason_total`` per coordinate. Forces the
    tracker's lazy aggregates, so callers in the CD hot loop must gate this
    on ``obs.active()``."""
    if tracker is None:
        return
    iters = registry.summary(
        "photon_cd_iterations", "solver iterations per coordinate update"
    ).labels(coordinate=coordinate_name)
    reasons = registry.counter(
        "photon_cd_convergence_reason_total",
        "coordinate-update solves by termination reason",
    )
    # latest-update iterations as a gauge: the cumulative summary above
    # cannot be read back per sweep, but this gauge lands in every per-sweep
    # metrics.jsonl flush — the report's solver-iterations trajectory
    latest = registry.gauge(
        "photon_cd_update_iterations",
        "solver iterations of the latest coordinate update (entity mean "
        "for random effects)",
    ).labels(coordinate=coordinate_name)
    if isinstance(tracker, RandomEffectOptimizationTracker):
        st = tracker.iterations_stats
        iters.merge_stat(st.count, st.mean, st.stdev, st.max, st.min)
        latest.set(st.mean)
        for reason, n in tracker.convergence_reasons.items():
            reasons.labels(coordinate=coordinate_name, reason=reason).inc(n)
    else:
        r = tracker.result
        iters_v, reason_v, loss_v = logged_fetch(
            "tracker_metrics", (r.iterations, r.reason, r.loss)
        )
        iters.observe(int(iters_v))
        latest.set(int(iters_v))
        reasons.labels(
            coordinate=coordinate_name,
            reason=ConvergenceReason(int(reason_v)).name,
        ).inc()
        registry.gauge(
            "photon_cd_final_loss", "final training loss of the latest update"
        ).labels(coordinate=coordinate_name).set(float(loss_v))
