"""TRON: trust-region Newton with truncated conjugate-gradient inner solves.

Functional re-implementation of the trust-region Newton method of Lin & Moré
(the algorithm in Lin, Weng, Keerthi, "Trust region Newton method for
large-scale logistic regression", JMLR 2008) that the reference adapted from
LIBLINEAR (photon-lib .../optimization/TRON.scala:78-335). Constants are
parity-matched: eta = (1e-4, 0.25, 0.75), sigma = (0.25, 0.5, 4.0)
(TRON.scala:93-94), defaults tol 1e-5 / 15 iterations / 20 CG iterations /
5 improvement failures (TRON.scala:252-258), CG stops at
||r|| <= 0.1 * ||g||, and the first accepted step shrinks delta to
min(delta, ||step||).

The Hessian never materializes: CG consumes Hessian-vector products, which on
TPU are one extra fused matvec pair per CG step
(GLMObjective.hessian_vector — the reference's HessianVectorAggregator
treeAggregate, here an XLA all-reduce when the batch is sharded).

Masked state updates make the same code valid under vmap for batched
per-entity TRON solves. The lane shape is generic (``lanes = jnp.shape(f0)``,
reductions over axis 0), so the same solve also drives lambda-lane stacks for
lane-batched hyperparameter sweeps (game/lanes.py): ``w`` is ``[d, L]`` (one
reg candidate per lane) or ``[S, E, L]`` (entity x lambda), and masked
commits freeze converged/diverged lanes at their last committed iterate —
per-lane ``ConvergenceReason`` — without stalling or perturbing neighbors.
The one lockstep artifact: every lane runs until ALL lanes finish, so a
fast-converging lambda can accumulate a few extra (accepted, tiny) Newton
steps vs its sequential solve — parity is ~1e-3, not bitwise
(tests/test_sweep_lanes.py documents the tolerance).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .. import obs
from .common import (
    ConvergenceReason,
    HvpFn,
    SolverResult,
    ValueAndGradFn,
    _norm,
    _vdot,
    as_partial,
    check_convergence,
    finite_state,
    project_box,
)

Array = jax.Array

_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0




class _CGState(NamedTuple):
    step: Array
    residual: Array
    direction: Array
    rtr: Array
    it: Array
    done: Array


def _truncated_cg(
    hvp: HvpFn,
    w: Array,
    gradient: Array,
    delta: Array,
    max_cg_iterations: int,
) -> Tuple[Array, Array, Array]:
    """Approximately solve H step = -gradient within ||step|| <= delta.

    Returns (step, residual, cg_iterations). Residual r = -g - H.step is used
    by the caller for the predicted-reduction formula.
    """
    tol = 0.1 * _norm(gradient)
    r0 = -gradient
    init = _CGState(
        step=jnp.zeros_like(gradient),
        residual=r0,
        direction=r0,
        rtr=_vdot(r0, r0),
        it=jnp.zeros(jnp.shape(tol), jnp.int32),
        done=_norm(r0) <= tol,
    )

    def cond(s: _CGState):
        return jnp.logical_not(jnp.all(s.done)) & jnp.any(s.it < max_cg_iterations)

    def body(s: _CGState):
        hd = hvp(w, s.direction)
        dhd = _vdot(s.direction, hd)
        alpha = s.rtr / jnp.where(dhd != 0, dhd, 1.0)
        step_try = s.step + alpha * s.direction

        # Hits the trust-region boundary: back off to the boundary crossing.
        over = _norm(step_try) > delta
        std = _vdot(s.step, s.direction)
        sts = _vdot(s.step, s.step)
        dtd = _vdot(s.direction, s.direction)
        dsq = delta * delta
        rad = jnp.sqrt(jnp.maximum(std * std + dtd * (dsq - sts), 0.0))
        alpha_b = jnp.where(
            std >= 0,
            (dsq - sts) / jnp.where(std + rad != 0, std + rad, 1.0),
            (rad - std) / jnp.where(dtd != 0, dtd, 1.0),
        )
        alpha_eff = jnp.where(over, alpha_b, alpha)
        step_new = s.step + alpha_eff * s.direction
        residual_new = s.residual - alpha_eff * hd

        rtr_new = _vdot(residual_new, residual_new)
        beta = rtr_new / jnp.where(s.rtr != 0, s.rtr, 1.0)
        direction_new = residual_new + beta * s.direction

        converged = _norm(residual_new) <= tol
        done_new = over | converged
        it_new = s.it + 1
        hit_max = it_new >= max_cg_iterations

        keep = s.done
        return _CGState(
            step=jnp.where(keep, s.step, step_new),
            residual=jnp.where(keep, s.residual, residual_new),
            direction=jnp.where(keep, s.direction, direction_new),
            rtr=jnp.where(keep, s.rtr, rtr_new),
            it=jnp.where(keep, s.it, it_new),
            done=keep | done_new | hit_max,
        )

    final = jax.lax.while_loop(cond, body, init)
    return final.step, final.residual, final.it


class _TronState(NamedTuple):
    w: Array
    f: Array
    g: Array
    delta: Array
    it: Array
    failures: Array
    done: Array
    reason: Array
    loss_history: Array
    grad_norm_history: Array


@partial(
    jax.jit,
    static_argnames=(
        "max_iterations",
        "max_cg_iterations",
        "max_improvement_failures",
        "has_box",
    ),
)
def _solve(
    value_and_grad: ValueAndGradFn,
    hvp: HvpFn,
    w0: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    max_iterations: int,
    max_cg_iterations: int,
    max_improvement_failures: int,
    has_box: bool,
    box_lower: Array,
    box_upper: Array,
) -> SolverResult:
    dtype = w0.dtype
    box = (box_lower, box_upper) if has_box else None

    f0, g0 = value_and_grad(w0)
    lanes = jnp.shape(f0)  # () single problem / [E] entity-minor batch
    hist = jnp.full((max_iterations + 1,) + lanes, jnp.nan, dtype)

    # corrupt-at-start lane: no good iterate exists, freeze at w0 (same
    # defense as lbfgs._solve — NaN comparisons are all False, so nothing
    # below would ever terminate the lane for the right reason)
    bad0 = ~finite_state(f0, g0) & jnp.ones(lanes, bool)

    init = _TronState(
        w=w0,
        f=f0,
        g=g0,
        delta=_norm(g0),
        it=jnp.zeros(lanes, jnp.int32),
        failures=jnp.zeros(lanes, jnp.int32),
        done=bad0,
        reason=jnp.where(
            bad0, int(ConvergenceReason.NUMERICAL_DIVERGENCE), 0
        ).astype(jnp.int32),
        loss_history=hist.at[0].set(f0),
        grad_norm_history=hist.at[0].set(_norm(g0)),
    )

    def cond(s: _TronState):
        return jnp.logical_not(jnp.all(s.done))

    def body(s: _TronState):
        step, residual, _ = _truncated_cg(hvp, s.w, s.g, s.delta, max_cg_iterations)
        w_try = s.w + step
        gs = _vdot(s.g, step)
        predicted = -0.5 * (gs - _vdot(step, residual))
        f_try, g_try = value_and_grad(w_try)
        actual = s.f - f_try
        step_norm = _norm(step)

        # First-ever trial shrinks the initial bound (TRON.scala:190-193).
        delta0 = jnp.where(
            (s.it == 0) & (s.failures == 0), jnp.minimum(s.delta, step_norm), s.delta
        )

        denom = f_try - s.f - gs
        alpha = jnp.where(
            denom <= 0, _SIGMA3, jnp.maximum(_SIGMA1, -0.5 * gs / jnp.where(denom != 0, denom, 1.0))
        )

        a, p = actual, predicted
        delta_new = jnp.where(
            a < _ETA0 * p,
            jnp.minimum(jnp.maximum(alpha, _SIGMA1) * step_norm, _SIGMA2 * delta0),
            jnp.where(
                a < _ETA1 * p,
                jnp.maximum(_SIGMA1 * delta0, jnp.minimum(alpha * step_norm, _SIGMA2 * delta0)),
                jnp.where(
                    a < _ETA2 * p,
                    jnp.maximum(_SIGMA1 * delta0, jnp.minimum(alpha * step_norm, _SIGMA3 * delta0)),
                    jnp.maximum(delta0, jnp.minimum(alpha * step_norm, _SIGMA3 * delta0)),
                ),
            ),
        )

        # a non-finite trial is numerical divergence: never accept it (the
        # masked commit keeps the last good iterate), and keep the NaN out of
        # delta — alpha above is computed from f_try, so without this guard a
        # single NaN trial poisons the trust-region radius of the lane forever
        finite_try = finite_state(f_try, g_try)
        accepted = (actual > _ETA0 * predicted) & finite_try
        delta_new = jnp.where(finite_try, delta_new, s.delta)
        w_acc = project_box(w_try, box) if box is not None else w_try
        w_new = jnp.where(accepted, w_acc, s.w)
        f_new = jnp.where(accepted, f_try, s.f)
        g_new = jnp.where(accepted, g_try, s.g)
        it_new = jnp.where(accepted, s.it + 1, s.it)
        failures_new = jnp.where(accepted, s.failures, s.failures + 1)

        too_many_failures = failures_new >= max_improvement_failures
        reason = check_convergence(
            it_new,
            max_iterations,
            f_new,
            s.f,
            _norm(g_new),
            loss_abs_tol,
            grad_abs_tol,
            objective_not_improving=too_many_failures,
            diverged=~finite_try,
        )
        # a rejected trial alone isn't convergence; only repeated failure
        # (or divergence, which freezes the rolled-back lane) is
        reason = jnp.where(
            accepted | too_many_failures | ~finite_try, reason, 0
        ).astype(jnp.int32)
        newly_done = reason != 0

        keep = s.done
        # accepted-iteration counters diverge across lanes (rejected trials
        # don't advance it), so history writes use a row-mask select instead
        # of per-lane scatter indices
        row = (
            jnp.arange(max_iterations + 1).reshape(
                (max_iterations + 1,) + (1,) * len(lanes)
            )
            == it_new
        )
        write = row & accepted & ~keep
        lh = jnp.where(write, f_new, s.loss_history)
        gh = jnp.where(write, _norm(g_new), s.grad_norm_history)
        return _TronState(
            w=jnp.where(keep, s.w, w_new),
            f=jnp.where(keep, s.f, f_new),
            g=jnp.where(keep, s.g, g_new),
            delta=jnp.where(keep, s.delta, delta_new),
            it=jnp.where(keep, s.it, it_new),
            failures=jnp.where(keep, s.failures, failures_new),
            done=keep | newly_done,
            reason=jnp.where(keep, s.reason, reason).astype(jnp.int32),
            loss_history=lh,
            grad_norm_history=gh,
        )

    final = jax.lax.while_loop(cond, body, init)
    return SolverResult(
        coefficients=final.w,
        loss=final.f,
        gradient=final.g,
        iterations=final.it,
        reason=final.reason,
        loss_history=final.loss_history,
        grad_norm_history=final.grad_norm_history,
    )


def solve_tron(
    value_and_grad: ValueAndGradFn,
    hvp: HvpFn,
    w0: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    max_iterations: int = 15,
    max_cg_iterations: int = 20,
    max_improvement_failures: int = 5,
    box_constraints: Optional[Tuple[Array, Array]] = None,
) -> SolverResult:
    has_box = box_constraints is not None
    zero = jnp.zeros_like(w0)
    lower, upper = box_constraints if has_box else (zero, zero)
    result = _solve(
        as_partial(value_and_grad),
        as_partial(hvp),
        w0,
        jnp.asarray(loss_abs_tol, w0.dtype),
        jnp.asarray(grad_abs_tol, w0.dtype),
        max_iterations,
        max_cg_iterations,
        max_improvement_failures,
        has_box,
        lower,
        upper,
    )
    obs.record_solver_metrics("tron", result)
    return result
