"""Shared optimizer contracts: convergence reasons, configs, results.

TPU re-design of the reference's Optimizer base
(photon-lib .../optimization/Optimizer.scala:35-238): instead of a mutable
iterate-until-converged driver object, each solver is a pure function running
its whole loop inside ``lax.while_loop`` with *masked* state updates — the
same compiled code therefore serves the reference's two execution modes:

- scalar: one (possibly device-sharded) problem — the fixed-effect solve;
- vmapped: thousands of per-entity problems advancing in lockstep with
  per-lane ``done`` freezing — the random-effect solve (SURVEY.md §7.3).

Convergence semantics are parity-matched to Optimizer.scala:126-139:
tolerances are *relative*, converted to absolute using the state at zero
coefficients (loss(0) * tol, ||grad(0)|| * tol; Optimizer.scala:65-69,171),
and the reasons are checked in the reference's order.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

# Callable w -> (value, gradient)
ValueAndGradFn = Callable[[Array], Tuple[Array, Array]]
# Callable (w, v) -> H(w) v
HvpFn = Callable[[Array, Array], Array]


class ConvergenceReason(enum.IntEnum):
    """Reference: photon-lib .../optimization/ConvergenceReason.scala."""

    NOT_CONVERGED = 0
    MAX_ITERATIONS = 1
    OBJECTIVE_NOT_IMPROVING = 2
    FUNCTION_VALUES_CONVERGED = 3
    GRADIENT_CONVERGED = 4
    # Not in the reference enum: the lane's objective or gradient went
    # non-finite. The solvers roll the lane back to its last good iterate and
    # freeze it — without this, NaN comparisons (all False) sail straight
    # through every tolerance test below and the lane exits with a spurious
    # OBJECTIVE_NOT_IMPROVING after burning its whole line-search budget.
    NUMERICAL_DIVERGENCE = 5


class OptimizerType(str, enum.Enum):
    LBFGS = "LBFGS"
    OWLQN = "OWLQN"
    LBFGSB = "LBFGSB"
    TRON = "TRON"


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    """Mirrors the reference's OptimizerConfig + regularization plumbing.

    Defaults are the reference's (LBFGS.scala:149-154, TRON.scala:252-258).
    ``l1_weight`` routes LBFGS -> OWL-QN (reference: OptimizerFactory.scala:30-74).
    ``box_constraints`` = (lower[d], upper[d]): LBFGS/LBFGSB run the
    gradient-projection L-BFGS-B scheme (projected gradient + projected
    line-search trials, lbfgs.py; reference LBFGSB.scala:39-92); TRON projects
    after each accepted step (OptimizationUtils.projectCoefficientsToSubspace).
    """

    optimizer_type: OptimizerType = OptimizerType.LBFGS
    tolerance: float = 1e-7
    max_iterations: int = 100
    num_corrections: int = 10
    l1_weight: float = 0.0
    box_constraints: Optional[Tuple[Array, Array]] = None
    max_line_search_iterations: int = 25
    # TRON-specific
    max_improvement_failures: int = 5
    max_cg_iterations: int = 20

    def normalized_type(self) -> OptimizerType:
        t = OptimizerType(self.optimizer_type)
        if t == OptimizerType.LBFGS and self.l1_weight > 0.0:
            return OptimizerType.OWLQN
        return t


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SolverResult:
    """Final solver state plus fixed-size per-iteration history
    (the functional OptimizationStatesTracker, Optimizer.scala /
    OptimizationStatesTracker.scala:32-121)."""

    coefficients: Array
    loss: Array
    gradient: Array
    iterations: Array  # i32 scalar
    reason: Array  # i32 scalar, ConvergenceReason code
    loss_history: Array  # f[max_iter + 1], NaN-padded
    grad_norm_history: Array  # f[max_iter + 1], NaN-padded

    @property
    def converged(self) -> Array:
        return self.reason != ConvergenceReason.NOT_CONVERGED


def project_box(w: Array, box: Optional[Tuple[Array, Array]]) -> Array:
    """Clamp coefficients into [lower, upper] (OptimizationUtils.scala:34-66)."""
    if box is None:
        return w
    lower, upper = box
    return jnp.clip(w, lower, upper)


def check_convergence(
    it: Array,
    max_iterations: int,
    loss: Array,
    prev_loss: Array,
    grad_norm: Array,
    loss_abs_tol: Array,
    grad_abs_tol: Array,
    objective_not_improving: Array,
    diverged: Optional[Array] = None,
) -> Array:
    """Reason code in the reference's precedence order (Optimizer.scala:126-139).

    ``diverged`` (per-lane bool) takes precedence over every other reason:
    a non-finite loss/gradient fails the tolerance comparisons silently (NaN
    compares are all False), so without the explicit flag a diverged lane
    would fall through to OBJECTIVE_NOT_IMPROVING or MAX_ITERATIONS.
    """
    reason = jnp.where(
        grad_norm <= grad_abs_tol, ConvergenceReason.GRADIENT_CONVERGED, 0
    )
    reason = jnp.where(
        jnp.abs(loss - prev_loss) <= loss_abs_tol,
        ConvergenceReason.FUNCTION_VALUES_CONVERGED,
        reason,
    )
    reason = jnp.where(
        objective_not_improving, ConvergenceReason.OBJECTIVE_NOT_IMPROVING, reason
    )
    reason = jnp.where(it >= max_iterations, ConvergenceReason.MAX_ITERATIONS, reason)
    if diverged is not None:
        reason = jnp.where(
            diverged, ConvergenceReason.NUMERICAL_DIVERGENCE, reason
        )
    return reason.astype(jnp.int32)


def finite_state(f: Array, g: Array) -> Array:
    """Per-lane "this (loss, gradient) pair is numerically sound": scalar for
    1-D gradients, [E] for entity-minor stacks [d, E] (axis-0 reduction like
    :func:`_norm`)."""
    return jnp.isfinite(f) & jnp.all(jnp.isfinite(g), axis=0)


def as_partial(fn):
    """Wrap a callable as a jax.tree_util.Partial so it can flow through jit
    as a DYNAMIC argument: the jit cache keys on the underlying function
    identity + pytree structure, so fresh objective objects of the same
    structure reuse compiled solvers instead of recompiling (essential: a
    remote-compile environment pays tens of seconds per recompile)."""
    if isinstance(fn, jax.tree_util.Partial):
        return fn
    return jax.tree_util.Partial(fn)


@jax.jit
def _abs_tolerances_impl(value_and_grad, zero_like: Array, tolerance: Array):
    f0, g0 = value_and_grad(jnp.zeros_like(zero_like))
    return jnp.abs(f0) * tolerance, _norm(g0) * tolerance


def abs_tolerances(
    value_and_grad: ValueAndGradFn, zero_like: Array, tolerance: float
) -> Tuple[Array, Array]:
    """Absolute tolerances from the state at zero coefficients
    (Optimizer.scala:65-69 + :171)."""
    return _abs_tolerances_impl(
        as_partial(value_and_grad), zero_like, jnp.asarray(tolerance, zero_like.dtype)
    )


def _norm(v: Array) -> Array:
    # axis-0 reduction: identical to the full norm for 1-D coefficient
    # vectors, and per-problem norms for entity-minor batched stacks [d, E]
    return jnp.sqrt(jnp.sum(v * v, axis=0))


def _vdot(a: Array, b: Array) -> Array:
    """Coefficient-axis dot: scalar for 1-D operands, per-lane [E] for
    entity-minor stacks [d, E]. 1-D keeps ``jnp.dot`` — bit-identical to the
    historical solver path (a fused multiply+reduce associates differently,
    which would break the vmapped path's bucket-shape exactness)."""
    if a.ndim == 1:
        return jnp.dot(a, b)
    return jnp.sum(a * b, axis=0)
