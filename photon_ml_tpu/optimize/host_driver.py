"""Host-driven single-lane solvers for out-of-core objectives.

The device solvers (lbfgs.py / tron.py) run their entire loop inside
``lax.while_loop``, which requires the objective to be traceable — fine when
the batch is HBM-resident, impossible when each evaluation must stage host
row slices through the chip with Python-driven double buffering
(game/fe_streaming.py). These ports move the *driver* loop to the host while
the objective math stays on device, which is exactly the reference's
architecture for the fixed effect: Breeze optimizers iterate on the Spark
driver and every evaluation is a ``treeAggregate`` over disk-persisted
partitions (photon-lib .../optimization/LBFGS.scala:38-154,
DistributedObjectiveFunction + AvroDataReader.scala:165-209).

Parity contract with the device twins, single lane (scalar f, ``[d]`` g):

- same constants (c1=1e-4, c2=0.9; TRON eta/sigma), same bracket updates,
  same correction-pair guard ``s.y > 1e-10 ||y||^2``, same steepest-descent
  fallback, same OWL-QN pseudo-gradient / orthant projection, same L-BFGS-B
  projected gradient, same TRON trust-region schedule and truncated CG with
  boundary crossing;
- same convergence precedence (common.check_convergence) with relative ->
  absolute tolerances from the zero state;
- same numerical-divergence defense: a non-finite trial is never committed
  (the last good iterate survives), its (s, y) pair never enters history, a
  non-finite TRON ratio never resizes the radius, and an already-corrupt
  start freezes at w0 with 0 iterations.

Results are host-materialized ``SolverResult``s (numpy leaves) — directly
compatible with the divergence guard in game/descent and with
``obs.record_solver_metrics``.
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Tuple

import numpy as np

from .. import obs
from .common import ConvergenceReason, OptimizerConfig, OptimizerType, SolverResult

_C1 = 1e-4  # Armijo (sufficient decrease)
_C2 = 0.9  # curvature
_ETA0, _ETA1, _ETA2 = 1e-4, 0.25, 0.75
_SIGMA1, _SIGMA2, _SIGMA3 = 0.25, 0.5, 4.0

# Callable w[np d] -> (float, np[d]); the streamed objective fetches its
# accumulated totals once per evaluation, so these are host-concrete.
HostValueAndGradFn = Callable[[np.ndarray], Tuple[float, np.ndarray]]
HostHvpFn = Callable[[np.ndarray, np.ndarray], np.ndarray]


def _norm(v: np.ndarray) -> float:
    return float(np.sqrt(np.dot(v, v)))


def _finite(f: float, g: np.ndarray) -> bool:
    return bool(np.isfinite(f)) and bool(np.all(np.isfinite(g)))


def host_check_convergence(
    it: int,
    max_iterations: int,
    loss: float,
    prev_loss: float,
    grad_norm: float,
    loss_abs_tol: float,
    grad_abs_tol: float,
    objective_not_improving: bool,
    diverged: bool = False,
) -> int:
    """Host port of common.check_convergence: identical precedence chain
    (later conditions override earlier ones; divergence overrides all)."""
    reason = 0
    if grad_norm <= grad_abs_tol:
        reason = int(ConvergenceReason.GRADIENT_CONVERGED)
    if abs(loss - prev_loss) <= loss_abs_tol:
        reason = int(ConvergenceReason.FUNCTION_VALUES_CONVERGED)
    if objective_not_improving:
        reason = int(ConvergenceReason.OBJECTIVE_NOT_IMPROVING)
    if it >= max_iterations:
        reason = int(ConvergenceReason.MAX_ITERATIONS)
    if diverged:
        reason = int(ConvergenceReason.NUMERICAL_DIVERGENCE)
    return reason


def host_abs_tolerances(
    value_and_grad: HostValueAndGradFn, zero_like: np.ndarray, tolerance: float
) -> Tuple[float, float]:
    """Relative -> absolute tolerances from the zero state (the host twin of
    common.abs_tolerances; costs one extra streamed pass, exactly like the
    device path's extra evaluation)."""
    f0, g0 = value_and_grad(np.zeros_like(zero_like))
    return abs(float(f0)) * tolerance, _norm(np.asarray(g0)) * tolerance


def _pseudo_gradient(w: np.ndarray, g: np.ndarray, l1: float) -> np.ndarray:
    """OWL-QN pseudo-gradient of f(w) + l1*||w||_1 (lbfgs._pseudo_gradient)."""
    gp = g + l1
    gm = g - l1
    pg = np.where(w > 0, gp, np.where(w < 0, gm, 0.0))
    at_zero = np.where(gm > 0, gm, np.where(gp < 0, gp, 0.0))
    return np.where(w == 0, at_zero, pg).astype(g.dtype)


def _two_loop(pairs: List[Tuple[np.ndarray, np.ndarray, float]], g: np.ndarray) -> np.ndarray:
    """Two-loop recursion over the (s, y, rho) history, oldest..newest —
    identical visit order to the device circular buffer (newest-first pass 1,
    oldest-first pass 2, gamma from the newest pair with the yy > 0 guard)."""
    q = g.copy()
    alphas = []
    for s, y, rho in reversed(pairs):
        a = rho * float(np.dot(s, q))
        alphas.append(a)
        q = q - a * y
    if pairs:
        s_n, y_n, _ = pairs[-1]
        yy = float(np.dot(y_n, y_n))
        gamma = float(np.dot(s_n, y_n)) / yy if yy > 0 else 1.0
    else:
        gamma = 1.0
    r = gamma * q
    for (s, y, rho), a in zip(pairs, reversed(alphas)):
        b = rho * float(np.dot(y, r))
        r = r + (a - b) * s
    return r.astype(g.dtype)


def _line_search(
    value_and_grad: HostValueAndGradFn,
    w: np.ndarray,
    f: float,
    direction: np.ndarray,
    dg: float,
    l1: float,
    orthant: Optional[np.ndarray],
    max_iters: int,
    box: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    g_plain: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, float, np.ndarray, bool]:
    """Weak-Wolfe bisection/expansion line search (lbfgs._line_search, one
    lane): OWL-QN projects trials onto the orthant and checks Armijo only;
    L-BFGS-B projects onto the box and measures Armijo on the actual
    displacement."""
    dtype = w.dtype

    def trial(t: float):
        w_t = (w + t * direction).astype(dtype)
        if orthant is not None:
            w_t = np.where(w_t * orthant < 0, 0.0, w_t).astype(dtype)
        if box is not None:
            w_t = np.clip(w_t, box[0], box[1])
        f_t, g_t = value_and_grad(w_t)
        f_t = float(f_t)
        if l1 > 0.0:
            f_t = f_t + l1 * float(np.sum(np.abs(w_t)))
        return w_t, f_t, np.asarray(g_t)

    t, lo, hi = 1.0, 0.0, math.inf
    w_t, f_t, g_t = trial(t)
    for n in range(max_iters):
        finite = bool(np.isfinite(f_t))
        if box is not None:
            armijo_ok = f_t <= f + _C1 * float(np.dot(g_plain, w_t - w))
        else:
            armijo_ok = f_t <= f + _C1 * t * dg
        if orthant is None and box is None:
            curv_ok = float(np.dot(g_t, direction)) >= _C2 * dg
        else:
            curv_ok = True
        if armijo_ok and curv_ok and finite:
            return w_t, f_t, g_t, True
        if n + 1 >= max_iters:
            break
        if armijo_ok and finite:
            # Armijo held but curvature failed: raise the lower bracket
            lo = t
            t = 2.0 * lo + 1.0 if math.isinf(hi) else 0.5 * (lo + hi)
        else:
            # Armijo failed (or non-finite): bisect downward
            hi = t
            t = 0.5 * (lo + t)
        w_t, f_t, g_t = trial(t)
    return w_t, f_t, g_t, False


def solve_lbfgs_host(
    value_and_grad: HostValueAndGradFn,
    w0: np.ndarray,
    loss_abs_tol: float,
    grad_abs_tol: float,
    max_iterations: int = 100,
    num_corrections: int = 10,
    l1_weight: float = 0.0,
    box_constraints: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    max_line_search_iterations: int = 25,
    initial_eval: Optional[Tuple[float, np.ndarray]] = None,
) -> SolverResult:
    """Host port of lbfgs._solve for one lane; numpy-leaved SolverResult.

    ``initial_eval``: a pre-dispatched raw ``value_and_grad(w0)`` result
    (pipelined tolerance overlap, host_optimize); the L1 term is applied
    here with the same arithmetic as ``full_objective``, so the iterate
    stream is bit-identical to evaluating in place. Only valid without box
    constraints (the initial clip would move the evaluation point)."""
    dtype = w0.dtype
    l1 = float(l1_weight)
    box = None
    if box_constraints is not None:
        box = (
            np.asarray(box_constraints[0], dtype),
            np.asarray(box_constraints[1], dtype),
        )

    def full_objective(w: np.ndarray) -> Tuple[float, np.ndarray]:
        f, g = value_and_grad(w)
        f = float(f)
        if l1 > 0.0:
            f = f + l1 * float(np.sum(np.abs(w)))
        return f, np.asarray(g)

    def effective_grad(w: np.ndarray, g: np.ndarray) -> np.ndarray:
        if l1 > 0.0:
            return _pseudo_gradient(w, g, l1)
        if box is not None:
            return (w - np.clip(w - g, box[0], box[1])).astype(g.dtype)
        return g

    w = np.array(w0, dtype, copy=True)
    if box is not None:
        w = np.clip(w, box[0], box[1])
    if initial_eval is not None and box is None:
        f, g = initial_eval
        f = float(f)
        if l1 > 0.0:
            f = f + l1 * float(np.sum(np.abs(w)))
        g = np.asarray(g)
    else:
        f, g = full_objective(w)

    T = max_iterations + 1
    lh = np.full(T, np.nan, dtype)
    gh = np.full(T, np.nan, dtype)
    lh[0] = f
    gh[0] = _norm(effective_grad(w, g))

    def result(it: int, reason: int) -> SolverResult:
        return SolverResult(
            coefficients=w,
            loss=np.asarray(f, dtype),
            gradient=effective_grad(w, g),
            iterations=np.int32(it),
            reason=np.int32(reason),
            loss_history=lh,
            grad_norm_history=gh,
        )

    if not _finite(f, g):
        # corrupt at start: no good iterate to roll back to — freeze at w0
        return result(0, int(ConvergenceReason.NUMERICAL_DIVERGENCE))

    pairs: List[Tuple[np.ndarray, np.ndarray, float]] = []
    it = 0
    while True:
        pg = effective_grad(w, g)
        direction = -_two_loop(pairs, pg)
        if l1 > 0.0:
            direction = np.where(direction * pg >= 0, 0.0, direction).astype(dtype)
        dg = float(np.dot(direction, pg))
        if dg >= 0:
            # not a descent direction: steepest-descent fallback
            direction = -pg
            dg = -float(np.dot(pg, pg))
        orthant = None
        if l1 > 0.0:
            orthant = np.where(w != 0, np.sign(w), -np.sign(pg)).astype(dtype)

        w_new, f_new, g_new, ls_ok = _line_search(
            value_and_grad, w, f, direction, dg, l1, orthant,
            max_line_search_iterations, box=box, g_plain=g,
        )

        finite_new = _finite(f_new, g_new)
        improved = ls_ok and (f_new < f) and finite_new

        s_vec = w_new - w
        y_vec = g_new - g
        sy = float(np.dot(s_vec, y_vec))
        if improved and sy > 1e-10 * _norm(y_vec) ** 2:
            pairs.append((s_vec, y_vec, 1.0 / sy))
            if len(pairs) > num_corrections:
                pairs.pop(0)

        it += 1
        pg_new = effective_grad(w_new, g_new)
        reason = host_check_convergence(
            it, max_iterations, f_new, f, _norm(pg_new), loss_abs_tol,
            grad_abs_tol, objective_not_improving=not improved,
            diverged=not finite_new,
        )
        if improved:
            w, f, g = w_new, f_new, g_new
        lh[it] = f
        gh[it] = _norm(effective_grad(w, g))
        if reason != 0:
            return result(it, reason)


def _truncated_cg(
    hvp: HostHvpFn,
    w: np.ndarray,
    gradient: np.ndarray,
    delta: float,
    max_cg_iterations: int,
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Host port of tron._truncated_cg: solve H step = -g within the radius,
    with the boundary-crossing back-off. Returns (step, residual, iters)."""
    tol = 0.1 * _norm(gradient)
    step = np.zeros_like(gradient)
    r = -gradient
    d = r.copy()
    rtr = float(np.dot(r, r))
    if _norm(r) <= tol:
        return step, r, 0
    it = 0
    while it < max_cg_iterations:
        hd = np.asarray(hvp(w, d))
        dhd = float(np.dot(d, hd))
        alpha = rtr / (dhd if dhd != 0 else 1.0)
        step_try = step + alpha * d
        if _norm(step_try) > delta:
            # hit the trust-region boundary: back off to the crossing
            std = float(np.dot(step, d))
            sts = float(np.dot(step, step))
            dtd = float(np.dot(d, d))
            dsq = delta * delta
            rad = math.sqrt(max(std * std + dtd * (dsq - sts), 0.0))
            if std >= 0:
                denom = std + rad
                alpha_b = (dsq - sts) / (denom if denom != 0 else 1.0)
            else:
                alpha_b = (rad - std) / (dtd if dtd != 0 else 1.0)
            return step + alpha_b * d, r - alpha_b * hd, it + 1
        step = step_try
        r = r - alpha * hd
        rtr_new = float(np.dot(r, r))
        beta = rtr_new / (rtr if rtr != 0 else 1.0)
        d = r + beta * d
        rtr = rtr_new
        it += 1
        if _norm(r) <= tol:
            break
    return step, r, it


def solve_tron_host(
    value_and_grad: HostValueAndGradFn,
    hvp: HostHvpFn,
    w0: np.ndarray,
    loss_abs_tol: float,
    grad_abs_tol: float,
    max_iterations: int = 15,
    max_cg_iterations: int = 20,
    max_improvement_failures: int = 5,
    box_constraints: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    initial_eval: Optional[Tuple[float, np.ndarray]] = None,
) -> SolverResult:
    """Host port of tron._solve for one lane; numpy-leaved SolverResult.

    ``initial_eval``: pre-dispatched ``value_and_grad(w0)`` (pipelined
    tolerance overlap, host_optimize) — TRON starts from unclipped w0, so
    the substitution is exact."""
    dtype = w0.dtype
    box = None
    if box_constraints is not None:
        box = (
            np.asarray(box_constraints[0], dtype),
            np.asarray(box_constraints[1], dtype),
        )

    w = np.array(w0, dtype, copy=True)
    fg = initial_eval if initial_eval is not None else value_and_grad(w)
    f, g = float(fg[0]), np.asarray(fg[1])

    T = max_iterations + 1
    lh = np.full(T, np.nan, dtype)
    gh = np.full(T, np.nan, dtype)
    lh[0] = f
    gh[0] = _norm(g)

    def result(it: int, reason: int) -> SolverResult:
        return SolverResult(
            coefficients=w,
            loss=np.asarray(f, dtype),
            gradient=g,
            iterations=np.int32(it),
            reason=np.int32(reason),
            loss_history=lh,
            grad_norm_history=gh,
        )

    if not _finite(f, g):
        return result(0, int(ConvergenceReason.NUMERICAL_DIVERGENCE))

    delta = _norm(g)
    it = 0
    failures = 0
    while True:
        step, residual, _ = _truncated_cg(hvp, w, g, delta, max_cg_iterations)
        w_try = w + step
        gs = float(np.dot(g, step))
        predicted = -0.5 * (gs - float(np.dot(step, residual)))
        fg_try = value_and_grad(w_try)
        f_try, g_try = float(fg_try[0]), np.asarray(fg_try[1])
        actual = f - f_try
        step_norm = _norm(step)

        # first-ever trial shrinks the initial bound (TRON.scala:190-193)
        delta0 = min(delta, step_norm) if (it == 0 and failures == 0) else delta

        denom = f_try - f - gs
        if denom <= 0:
            alpha = _SIGMA3
        else:
            alpha = max(_SIGMA1, -0.5 * gs / (denom if denom != 0 else 1.0))

        a, p = actual, predicted
        if a < _ETA0 * p:
            delta_new = min(max(alpha, _SIGMA1) * step_norm, _SIGMA2 * delta0)
        elif a < _ETA1 * p:
            delta_new = max(_SIGMA1 * delta0, min(alpha * step_norm, _SIGMA2 * delta0))
        elif a < _ETA2 * p:
            delta_new = max(_SIGMA1 * delta0, min(alpha * step_norm, _SIGMA3 * delta0))
        else:
            delta_new = max(delta0, min(alpha * step_norm, _SIGMA3 * delta0))

        # a non-finite trial is numerical divergence: never accept it and
        # keep the NaN out of the trust-region radius
        finite_try = _finite(f_try, g_try)
        accepted = (actual > _ETA0 * predicted) and finite_try
        delta = delta_new if finite_try else delta

        prev_f = f
        if accepted:
            w = np.clip(w_try, box[0], box[1]) if box is not None else w_try
            f, g = f_try, g_try
            it += 1
            lh[it] = f
            gh[it] = _norm(g)
        else:
            failures += 1

        too_many = failures >= max_improvement_failures
        reason = host_check_convergence(
            it, max_iterations, f, prev_f, _norm(g), loss_abs_tol,
            grad_abs_tol, objective_not_improving=too_many,
            diverged=not finite_try,
        )
        # a rejected trial alone isn't convergence; only repeated failure
        # (or divergence, which freezes the rolled-back lane) is
        if not (accepted or too_many or not finite_try):
            reason = 0
        if reason != 0:
            return result(it, reason)


def host_optimize(
    value_and_grad: HostValueAndGradFn,
    w0: np.ndarray,
    config: OptimizerConfig,
    hvp: Optional[HostHvpFn] = None,
    value_and_grad_deferred: Optional[Callable] = None,
) -> SolverResult:
    """Host twin of driver.optimize: tolerance conversion from the zero
    state, then dispatch on the normalized optimizer type. Records the same
    per-solver obs metrics as the device drivers (solver labels ``lbfgs`` /
    ``tron``; numpy results are fetch-free to record).

    ``value_and_grad_deferred``: dispatch-only form of ``value_and_grad``
    (returns a fetch closure — StreamedFEObjective.value_and_grad_deferred).
    When provided, the tolerance pass at zeros and the first real evaluation
    at w0 are BOTH dispatched before either is fetched, so the driver's two
    mandatory serial passes overlap on device. Same kernels on the same
    operands → same bits; skipped under box constraints, where the solver's
    initial clip moves the evaluation point."""
    w0 = np.asarray(w0)
    initial_eval = None
    if value_and_grad_deferred is not None and config.box_constraints is None:
        fetch_zero = value_and_grad_deferred(np.zeros_like(w0))
        fetch_w0 = value_and_grad_deferred(w0)
        f0, g0 = fetch_zero()
        loss_tol = abs(float(f0)) * config.tolerance
        grad_tol = _norm(np.asarray(g0)) * config.tolerance
        initial_eval = fetch_w0()
    else:
        loss_tol, grad_tol = host_abs_tolerances(
            value_and_grad, w0, config.tolerance
        )
    kind = config.normalized_type()

    if kind in (OptimizerType.LBFGS, OptimizerType.LBFGSB, OptimizerType.OWLQN):
        result = solve_lbfgs_host(
            value_and_grad,
            w0,
            loss_tol,
            grad_tol,
            max_iterations=config.max_iterations,
            num_corrections=config.num_corrections,
            l1_weight=config.l1_weight if kind == OptimizerType.OWLQN else 0.0,
            box_constraints=config.box_constraints,
            max_line_search_iterations=config.max_line_search_iterations,
            initial_eval=initial_eval,
        )
        obs.record_solver_metrics("lbfgs", result)
        return result
    if kind == OptimizerType.TRON:
        if hvp is None:
            raise ValueError("TRON requires a Hessian-vector-product function")
        result = solve_tron_host(
            value_and_grad,
            hvp,
            w0,
            loss_tol,
            grad_tol,
            max_iterations=config.max_iterations,
            max_cg_iterations=config.max_cg_iterations,
            max_improvement_failures=config.max_improvement_failures,
            box_constraints=config.box_constraints,
            initial_eval=initial_eval,
        )
        obs.record_solver_metrics("tron", result)
        return result
    raise ValueError(f"Unknown optimizer type: {config.optimizer_type!r}")
