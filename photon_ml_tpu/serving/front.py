"""Least-loaded replica front: N ``cli serve`` replicas behind one submit
surface, with health-checked routing and idempotent failover.

The fleet's horizontal axis: every replica serves the same snapshot store
(same JSON-lines protocol, same ``model=`` routing), and the front holds
a pool of persistent connections per replica, routing each request to the
live channel with the fewest requests in flight. The pool size matters
because the JSON-lines protocol answers in order PER CONNECTION — a
replica scores one request per connection at a time — so the front's
concurrency into one replica equals its connection count:
``connections_per_replica`` channels keep the replica's microbatcher fed
enough to actually fill batches (one channel caps every batch at one
row). Scoring is a pure function of
(snapshot, request), so a request is safe to replay: when a replica dies
mid-request — connection reset, EOF, or an injected ``serving.replica``
fault — every request still outstanding on it is **resubmitted verbatim**
(same ``trace_id``, the idempotency key: a fleet-merged trace shows the
same id hopping replicas) to the survivors. The chaos drill this enables:
kill a replica under open-loop load and ZERO requests end without a
response — each one either scores on a survivor or comes back as a typed
shed (``no_replica`` when the whole fleet is down, ``resubmit_budget``
when a request has been through too many dying replicas).

Health: a replica is routable when its connection is up AND (when a
``healthz`` address is given) its ``/healthz`` answers 200 — a replica
answering 503 (mid-refresh flip, or shedding past its overload threshold)
is *drained*: no new requests, in-flight ones finish. A background
maintenance thread polls health and reconnects dead replicas, so a
restarted replica rejoins the rotation without operator action.

Fault sites: ``serving.route`` fires at every routing decision (an
injected error sheds the request, typed ``route``); ``serving.replica``
fires at every replica send (an injected IO error is a replica connection
dying mid-request — the failover drill without killing a process).

Addresses are TCP ``host:port`` only — balancing AF_UNIX replicas is
refused through the support-matrix ledger (``plan.check_fleet_composition``):
an AF_UNIX path names one kernel socket on one host, so there is no fleet
to balance. Front metrics: ``photon_serving_route_total{replica=}``,
``photon_serving_replica_up{replica=}``,
``photon_serving_failover_resubmits_total``, and
``photon_serving_front_sheds_total{reason=}``.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import threading
import urllib.error
import urllib.request
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence

from .. import obs
from ..plan import check_fleet_composition
from ..robust import faults
from .batcher import ShedError
from .engine import ScoreRequest
from .server import MAX_REQUEST_LINE_BYTES, BadRequestError, _count_bad_request

_ROUTE_HELP = "requests routed to a replica by the least-loaded front"
_FRONT_SHED_HELP = (
    "requests the front refused with a typed shed response "
    "(no_replica / route / resubmit_budget / front_closed)"
)


class _Pending:
    """One in-flight request: the serialized line (resent verbatim on
    failover — same trace_id), its Future, and its resubmit count."""

    __slots__ = ("payload", "fut", "model", "trace_id", "resubmits")

    def __init__(self, payload: bytes, fut: Future, model, trace_id: str):
        self.payload = payload
        self.fut = fut
        self.model = model
        self.trace_id = trace_id
        self.resubmits = 0


class _Replica:
    """One replica connection: socket + in-order outstanding queue. The
    JSON-lines protocol answers in request order per connection, so the
    reader matches responses by position. ``gen`` increments on every
    disconnect so a stale reader (or a racing send) can tell its
    connection was replaced."""

    def __init__(self, name: str, host: str, port: int, healthz: Optional[str]):
        self.name = name
        self.host = host
        self.port = port
        self.healthz = healthz
        self.lock = threading.Lock()
        self.sock: Optional[socket.socket] = None
        self.rfile = None
        self.up = False
        self.healthy = True
        self.gen = 0
        self.outstanding: "deque[_Pending]" = deque()


_front_ids = itertools.count(1)


class LeastLoadedFront:
    """Route requests across N scoring replicas, least in-flight first.

    ``replicas`` is a list of TCP ``host:port`` addresses (each a
    ``cli serve --listen`` replica over the same snapshot store);
    ``healthz`` optionally gives each replica's introspection address
    (``host:status_port``, or None) for 503-draining. ``submit`` /
    ``score`` mirror :class:`~photon_ml_tpu.serving.server.ScoringServer`'s
    surface (so ``loadgen.run_open_loop`` drives a fleet unchanged);
    ``submit_doc`` is the raw JSON-document surface the pass-through socket
    handler (``serve_front_socket``) and the failover path share.

    ``connections_per_replica`` opens K independent channels to each
    address (module docstring: the serial-per-connection protocol makes K
    the front's concurrency into one replica). Channels beyond the first
    are named ``host:port#k`` everywhere a replica name surfaces (the
    ``replica=`` metric label, ``replica_states()``); each fails over
    independently, so one torn channel resubmits only its own
    outstanding requests."""

    def __init__(
        self,
        replicas: Sequence[str],
        healthz: Optional[Sequence[Optional[str]]] = None,
        connect_timeout: float = 2.0,
        health_poll_seconds: float = 0.25,
        max_resubmits: int = 5,
        request_timeout: float = 60.0,
        connections_per_replica: int = 1,
    ):
        if not replicas:
            raise ValueError("LeastLoadedFront needs at least one replica")
        check_fleet_composition((), front_replicas=replicas)
        if healthz is not None and len(healthz) != len(replicas):
            raise ValueError("healthz must parallel replicas (None entries ok)")
        if int(connections_per_replica) < 1:
            raise ValueError("connections_per_replica must be >= 1")
        self.connect_timeout = float(connect_timeout)
        self.health_poll_seconds = float(health_poll_seconds)
        self.max_resubmits = int(max_resubmits)
        self.request_timeout = float(request_timeout)
        self._id = f"fr{os.getpid():x}-{next(_front_ids)}"
        self._req_seq = itertools.count(1)
        self._closed = threading.Event()
        self._replicas: List[_Replica] = []
        for i, addr in enumerate(replicas):
            host, _, port = str(addr).rpartition(":")
            hz = healthz[i] if healthz is not None else None
            for k in range(int(connections_per_replica)):
                name = str(addr) if k == 0 else f"{addr}#{k}"
                self._replicas.append(_Replica(name, host, int(port), hz))
        self._reader_threads: List[threading.Thread] = []
        for r in self._replicas:
            self._connect(r)
        self._maintainer = threading.Thread(
            target=self._maintain, name="photon-serving-front", daemon=True
        )
        self._maintainer.start()

    # -- connections ----------------------------------------------------------

    def _set_up_gauge(self, r: _Replica, value: int) -> None:
        obs.current_run().registry.gauge(
            "photon_serving_replica_up",
            "replica liveness as seen by the front (1 routable, 0 down)",
        ).labels(replica=r.name).set(value)

    def _connect(self, r: _Replica) -> bool:
        """(Re)open one replica connection and start its reader. Failures
        leave the replica down — the maintenance thread retries."""
        try:
            sock = socket.create_connection(
                (r.host, r.port), timeout=self.connect_timeout
            )
        except OSError:
            self._set_up_gauge(r, 0)
            return False
        try:
            sock.settimeout(None)
            with r.lock:
                r.sock = sock
                r.rfile = sock.makefile("rb")
                r.up = True
                gen = r.gen
        except BaseException:
            sock.close()  # a setup error must not leak the fd
            raise
        self._set_up_gauge(r, 1)
        t = threading.Thread(
            target=self._read_loop,
            args=(r, r.rfile, gen),
            name=f"photon-serving-front-read-{r.name}",
            daemon=True,
        )
        self._reader_threads.append(t)
        t.start()
        return True

    def _fail_replica(self, r: _Replica, gen: int) -> List[_Pending]:
        """Tear one replica connection down (idempotent per ``gen``) and
        return the requests that were outstanding on it — the caller owns
        their failover."""
        with r.lock:
            if r.gen != gen:
                return []  # a newer connection already replaced this one
            r.gen += 1
            r.up = False
            victims = list(r.outstanding)
            r.outstanding.clear()
            sock, r.sock, r.rfile = r.sock, None, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        self._set_up_gauge(r, 0)
        return victims

    def _read_loop(self, r: _Replica, rfile, gen: int) -> None:
        """Per-connection reader: match responses to outstanding requests
        in order; on EOF/reset, fail the replica and resubmit its
        outstanding requests to the survivors (same trace_id — scoring is
        idempotent, so a request the dead replica *did* score is simply
        scored again)."""
        try:
            while True:
                line = rfile.readline()
                if not line:
                    break
                try:
                    doc = json.loads(line)
                except ValueError:
                    break  # torn mid-line write: the connection is gone
                with r.lock:
                    if r.gen != gen:
                        return
                    pending = r.outstanding.popleft() if r.outstanding else None
                if pending is not None:
                    pending.fut.set_result(doc)
        except (OSError, ValueError):
            pass
        for pending in self._fail_replica(r, gen):
            self._resubmit(pending)

    def _maintain(self) -> None:
        """Reconnect dead replicas + poll /healthz until closed."""
        while not self._closed.wait(self.health_poll_seconds):
            for r in self._replicas:
                if self._closed.is_set():
                    return
                with r.lock:
                    up = r.up
                if not up:
                    self._connect(r)
                if r.healthz is not None:
                    self._poll_healthz(r)

    def _poll_healthz(self, r: _Replica) -> None:
        """A 200 makes the replica routable; 503 (mid-refresh flip or
        overloaded) or an unreachable endpoint drains it — no new
        requests, in-flight ones finish."""
        try:
            with urllib.request.urlopen(
                f"http://{r.healthz}/healthz", timeout=self.connect_timeout
            ):
                healthy = True
        except urllib.error.URLError:
            healthy = False
        except OSError:
            healthy = False
        if healthy != r.healthy:
            r.healthy = healthy
            self._set_up_gauge(r, 1 if (healthy and r.up) else 0)

    # -- routing --------------------------------------------------------------

    def _pick(self, exclude) -> Optional[_Replica]:
        best, best_load = None, None
        for r in self._replicas:
            if r.name in exclude:
                continue
            with r.lock:
                if not r.up or not r.healthy or r.sock is None:
                    continue
                load = len(r.outstanding)
            if best is None or load < best_load:
                best, best_load = r, load
        return best

    def _try_send(self, r: _Replica, pending: _Pending) -> bool:
        ok = True
        with r.lock:
            if not r.up or not r.healthy or r.sock is None:
                return False
            gen = r.gen
            try:
                # the replica-I/O chaos site: an injected io error here is
                # a replica connection dying at send time — the failover
                # drill without killing a process
                faults.check("serving.replica")
                r.outstanding.append(pending)
                r.sock.sendall(pending.payload)
            except OSError:
                ok = False
                if r.outstanding and r.outstanding[-1] is pending:
                    r.outstanding.pop()
        if not ok:
            for victim in self._fail_replica(r, gen):
                self._resubmit(victim)
            return False
        obs.current_run().registry.counter(
            "photon_serving_route_total", _ROUTE_HELP
        ).labels(replica=r.name).inc()
        return True

    def _shed(self, pending: _Pending, reason: str) -> None:
        """A typed refusal WITH a response: the front's no-silent-loss
        contract — every dispatched request resolves, even with the whole
        replica fleet down."""
        obs.current_run().registry.counter(
            "photon_serving_front_sheds_total", _FRONT_SHED_HELP
        ).labels(reason=reason).inc()
        doc = {
            "error": f"front shed ({reason})",
            "error_type": "shed",
            "reason": reason,
            "trace_id": pending.trace_id,
        }
        if pending.model is not None:
            doc["model"] = pending.model
        pending.fut.set_result(doc)

    def _dispatch(self, pending: _Pending) -> None:
        try:
            # the routing chaos site: an injected error at the decision
            # point sheds the request (typed), never drops it
            faults.check("serving.route")
        except OSError:
            self._shed(pending, "route")
            return
        tried: set = set()
        while True:
            if self._closed.is_set():
                self._shed(pending, "front_closed")
                return
            r = self._pick(tried)
            if r is None:
                self._shed(pending, "no_replica")
                return
            if self._try_send(r, pending):
                return
            tried.add(r.name)

    def _resubmit(self, pending: _Pending) -> None:
        if self._closed.is_set():
            self._shed(pending, "front_closed")
            return
        pending.resubmits += 1
        if pending.resubmits > self.max_resubmits:
            self._shed(pending, "resubmit_budget")
            return
        obs.current_run().registry.counter(
            "photon_serving_failover_resubmits_total",
            "in-flight requests resubmitted (same trace_id) after their "
            "replica died mid-request",
        ).inc()
        self._dispatch(pending)

    # -- client surface -------------------------------------------------------

    def submit_doc(self, doc: dict) -> Future:
        """Route one raw JSON request document; the Future resolves to the
        replica's (or the front's own shed) response document. A missing
        ``trace_id`` is assigned here so failover resubmits carry the same
        id end to end."""
        doc = dict(doc)
        if doc.get("trace_id") is None:
            doc["trace_id"] = f"{self._id}.{next(self._req_seq)}"
        fut: Future = Future()
        pending = _Pending(
            (json.dumps(doc) + "\n").encode(),
            fut,
            doc.get("model"),
            str(doc["trace_id"]),
        )
        self._dispatch(pending)
        return fut

    def submit(
        self, request: ScoreRequest, deadline_s: Optional[float] = None
    ) -> Future:
        """ScoringServer-shaped submit: the Future resolves to the float
        score, or raises the typed error the response document carried
        (ShedError / BadRequestError / RuntimeError) — so the open-loop
        harness drives a replica fleet exactly like a single server."""
        doc: Dict[str, object] = {
            "features": {
                s: [list(iv[0]), list(iv[1])]
                for s, iv in request.features.items()
            },
            "ids": dict(request.ids),
            "offset": float(request.offset),
        }
        if request.model is not None:
            doc["model"] = request.model
        if deadline_s is not None:
            doc["deadline_ms"] = float(deadline_s) * 1e3
        out: Future = Future()
        inner = self.submit_doc(doc)

        def _done(f: Future) -> None:
            exc = f.exception()
            if exc is not None:
                out.set_exception(exc)
                return
            d = f.result()
            if "score" in d:
                out.set_result(float(d["score"]))
            elif d.get("error_type") == "shed":
                out.set_exception(
                    ShedError(d.get("reason", "unknown"), d.get("error", "shed"))
                )
            elif d.get("error_type") == "bad_request":
                out.set_exception(
                    BadRequestError(
                        d.get("kind", "unknown"), d.get("error", "bad request")
                    )
                )
            else:
                out.set_exception(RuntimeError(d.get("error", "server error")))

        inner.add_done_callback(_done)
        return out

    def score(
        self,
        request: ScoreRequest,
        timeout: Optional[float] = None,
        deadline_s: Optional[float] = None,
    ) -> float:
        """Blocking single-request score through the fleet."""
        return self.submit(request, deadline_s=deadline_s).result(
            timeout=self.request_timeout if timeout is None else timeout
        )

    def replica_states(self) -> Dict[str, dict]:
        """Live routing view per replica (tests + statusz)."""
        out = {}
        for r in self._replicas:
            with r.lock:
                out[r.name] = {
                    "up": r.up,
                    "healthy": r.healthy,
                    "in_flight": len(r.outstanding),
                }
        return out

    def close(self) -> None:
        self._closed.set()
        self._maintainer.join(timeout=5.0)
        for r in self._replicas:
            with r.lock:
                gen = r.gen
            for pending in self._fail_replica(r, gen):
                self._shed(pending, "front_closed")
        for t in self._reader_threads:
            t.join(timeout=5.0)


# -- the front's own socket surface ------------------------------------------


def _front_conn_handler(
    front: LeastLoadedFront, conn: socket.socket, conns, conns_lock
) -> None:
    """Pass-through JSON-lines handler: clients speak the exact replica
    protocol to the front; documents forward verbatim (plus an assigned
    trace_id when the client sent none) and replica responses — model echo,
    shed reasons, bad_request kinds — relay back untouched. Requests on one
    connection forward one at a time, preserving the protocol's in-order
    response guarantee."""
    try:
        with conn, conn.makefile("rwb") as f:

            def respond(doc: dict) -> bool:
                try:
                    f.write((json.dumps(doc) + "\n").encode())
                    f.flush()
                    return True
                except (OSError, ValueError):
                    return False

            while True:
                try:
                    line = f.readline(MAX_REQUEST_LINE_BYTES + 1)
                except (OSError, ValueError):
                    break
                if not line:
                    break
                if len(line) > MAX_REQUEST_LINE_BYTES:
                    _count_bad_request("oversized")
                    respond(
                        {
                            "error": (
                                "request line exceeds "
                                f"{MAX_REQUEST_LINE_BYTES} bytes"
                            ),
                            "error_type": "bad_request",
                            "kind": "oversized",
                        }
                    )
                    break
                if not line.endswith(b"\n"):
                    _count_bad_request("disconnect")
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as exc:
                    _count_bad_request("not_json")
                    if not respond(
                        {
                            "error": f"request is not valid JSON: {exc}",
                            "error_type": "bad_request",
                            "kind": "not_json",
                        }
                    ):
                        break
                    continue
                if not isinstance(msg, dict):
                    _count_bad_request("bad_fields")
                    if not respond(
                        {
                            "error": "request must be a JSON object",
                            "error_type": "bad_request",
                            "kind": "bad_fields",
                        }
                    ):
                        break
                    continue
                try:
                    doc = front.submit_doc(msg).result(
                        timeout=front.request_timeout
                    )
                except Exception as exc:
                    obs.swallowed_error("serving.front")
                    doc = {
                        "error": str(exc),
                        "error_type": "error",
                        "trace_id": msg.get("trace_id"),
                    }
                if not respond(doc):
                    break
    except OSError:
        pass  # makefile close flushes into a torn-down socket
    finally:
        with conns_lock:
            conns.discard(conn)


def serve_front_socket(
    front: LeastLoadedFront,
    path: Optional[str] = None,
    stop_event: Optional[threading.Event] = None,
    listen=None,
    on_bound=None,
) -> None:
    """Serve the front over its own AF_UNIX/TCP listener (the replica
    protocol, passed through): ``cli serve --front`` composes this with
    N ``--listen`` replicas to make the fleet one address."""
    from .server import serve_socket

    serve_socket(
        front,
        path=path,
        stop_event=stop_event,
        listen=listen,
        on_bound=on_bound,
        handler=_front_conn_handler,
    )
