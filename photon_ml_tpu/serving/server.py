"""The resident scoring service: store + engine + microbatcher + refresh,
composed behind one `submit`/`score` surface.

The server keeps exactly one live ``ScoreEngine``; the batcher captures that
reference once per microbatch, and a ``RefreshWatcher`` flip replaces it with
a single attribute assignment — the GIL makes the swap atomic, the per-batch
capture makes it *clean*: every batch scores entirely on one snapshot.

Overload protection is the batcher's deadline-budget admission control
(``serving.batcher``): requests carry a latency budget
(``default_deadline_ms`` server-wide, or per request), the pending queue is
bounded, and refusals are typed ``ShedError`` responses counted in
``photon_serving_shed_total{reason=}`` — past the saturation knee the server
sheds excess load instead of letting the queue collapse everyone's p99.

For processes that can't link the package, ``serve_socket`` exposes the same
surface over an AF_UNIX socket (``path=``) or a TCP listener
(``listen="host:port"``) speaking JSON lines through one shared
connection-handler::

    -> {"features": {"shard": [[idx...], [val...]]}, "ids": {...},
        "offset": 0.0, "deadline_ms": 50}
    <- {"score": 1.25, "trace_id": "..."}
     | {"error": "...", "error_type": "shed", "reason": "deadline",
        "trace_id": "..."}
     | {"error": "...", "error_type": "bad_request", "kind": "not_json",
        "trace_id": "..."}
     | {"error": "...", "error_type": "error", "trace_id": "..."}

one connection per client, one request per line, responses in order.
Every response carries a ``trace_id`` — success, shed and bad_request
alike — assigned per connection at accept time (or echoed back when the
client sent its own ``"trace_id"`` field); the same id threads through the
batcher's per-stage spans (``serving.admit``/``serving.batch``/
``serving.score``, parented under the request's ``serving.request`` span),
so one slow response is greppable end to end across the trace timeline.
Malformed input never kills the connection silently: oversized lines,
non-JSON, and bad fields each get a typed error (and a
``photon_serving_bad_request_total{kind=}`` count); mid-line disconnects are
counted and closed cleanly. On ``stop_event`` every open connection is shut
down deterministically and its handler thread joined — no daemon thread
outlives the listener holding an open socket.
"""

from __future__ import annotations

import itertools
import json
import numbers
import os
import socket
import threading
from typing import Optional, Tuple, Union

import jax.numpy as jnp

from .. import obs
from .batcher import MicroBatcher, RequestTrace, ShedError
from .engine import ScoreEngine, ScoreRequest
from .refresh import RefreshWatcher, open_current
from .store import ModelStore

# One JSON-lines request must fit one line; past this the framing cannot be
# trusted, so the response is a typed refusal and the connection closes.
MAX_REQUEST_LINE_BYTES = 1 << 20


class ScoringServer:
    """Resident scorer over a published serving root (or a fixed store/engine).

    With ``serving_root`` the server opens the CURRENT snapshot and watches
    for newly published ones, flipping without dropping requests; with a
    bare ``store``/``engine`` it serves that model until closed."""

    def __init__(
        self,
        store: Optional[ModelStore] = None,
        engine: Optional[ScoreEngine] = None,
        serving_root: Optional[str] = None,
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
        max_pending: int = 1024,
        default_deadline_ms: Optional[float] = None,
        overload_shed_threshold: Optional[float] = None,
        poll_seconds: float = 0.2,
        dtype=jnp.float32,
        status_port: Optional[int] = None,
        slow_request_ms: Optional[float] = None,
    ):
        if sum(x is not None for x in (store, engine, serving_root)) != 1:
            raise ValueError("pass exactly one of store / engine / serving_root")
        self.dtype = dtype
        self.snapshot_name: Optional[str] = None
        self.default_deadline_s: Optional[float] = (
            None if default_deadline_ms is None else float(default_deadline_ms) / 1e3
        )
        self._lock = threading.Lock()
        self._watcher: Optional[RefreshWatcher] = None
        self._status_server = None
        if serving_root is not None:
            name, store = open_current(serving_root)
            self._install(name, store)
            self._watcher = RefreshWatcher(
                serving_root, self._install, poll_seconds=poll_seconds, live=name
            )
        elif store is not None:
            self._install(None, store)
        else:
            self._engine = engine
        self._engine.warm()
        self._batcher = MicroBatcher(
            self._current_engine,
            max_batch=max_batch,
            max_latency_ms=max_latency_ms,
            max_pending=max_pending,
            slow_request_ms=slow_request_ms,
        )
        if overload_shed_threshold is not None:
            # /healthz compares the scrape-delta shed rate against this
            # (obs/http.py): past it the replica answers 503 "overloaded"
            # so a load balancer backs off while scoring itself continues
            obs.current_run().status.update(
                overload_shed_threshold=float(overload_shed_threshold)
            )
        if status_port is not None:
            # live scrape surface (metrics otherwise only flush to files at
            # close): /metrics text exposition, /healthz, /statusz with
            # request QPS + latency quantiles. Bound to the run current at
            # construction — the one the batcher records into.
            self._status_server = obs.IntrospectionServer(
                obs.current_run(), port=status_port
            )
            # advertise the live snapshot on /statusz
            obs.current_run().status.update(
                serving_snapshot=self.snapshot_name
            )

    @property
    def status_port(self) -> Optional[int]:
        """Bound introspection port (useful with ``status_port=0``)."""
        return None if self._status_server is None else self._status_server.port

    # -- refresh flip ---------------------------------------------------------

    def _install(self, name: Optional[str], store: ModelStore) -> None:
        """Build the engine for a freshly opened store, then flip the live
        reference in one assignment (warm first: the flip must not stall
        in-flight traffic on a compile)."""
        live = getattr(self, "_batcher", None) is not None
        if live:
            # /healthz answers 503 for exactly the mid-publish window, so a
            # load balancer drains this replica while the flip is in flight
            # (scoring itself keeps working — the old engine serves until
            # the one-assignment swap below)
            obs.current_run().status.update(refresh_in_progress=True)
        try:
            engine = ScoreEngine.from_store(store, dtype=self.dtype)
            if live:
                engine.warm()
            with self._lock:
                self._engine = engine
                self.snapshot_name = name
        finally:
            if live:
                obs.current_run().status.update(refresh_in_progress=False)
        if getattr(self, "_status_server", None) is not None:
            obs.current_run().status.update(serving_snapshot=name)

    def _current_engine(self) -> ScoreEngine:
        with self._lock:
            return self._engine

    def poke_refresh(self) -> None:
        """Force an immediate CURRENT check (tests; avoids poll sleeps)."""
        if self._watcher is not None:
            self._watcher.poke()

    # -- scoring surface ------------------------------------------------------

    def submit(
        self,
        request: ScoreRequest,
        deadline_s: Optional[float] = None,
        trace: Optional[RequestTrace] = None,
    ):
        """Enqueue one request; returns a Future resolving to its score.
        ``deadline_s`` overrides the server's ``default_deadline_ms`` budget
        for this request (None = use the server default; the admission
        controller may raise :class:`ShedError` immediately). ``trace``
        threads a request-scoped trace context (trace_id + root span)
        through the batcher's per-stage spans."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        return self._batcher.submit(request, deadline_s=deadline_s, trace=trace)

    def score(
        self,
        request: ScoreRequest,
        timeout: float = 30.0,
        deadline_s: Optional[float] = None,
        trace: Optional[RequestTrace] = None,
    ) -> float:
        """Blocking single-request score (sheds surface as ShedError)."""
        return self.submit(request, deadline_s=deadline_s, trace=trace).result(
            timeout=timeout
        )

    def queue_stats(self) -> dict:
        """Live admission-queue stats (pending depth + drain estimate)."""
        return self._batcher.queue_stats()

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
        if self._status_server is not None:
            self._status_server.stop()
        self._batcher.close()


# -- the socket front --------------------------------------------------------


class BadRequestError(ValueError):
    """A socket request the server refuses to parse; ``kind`` is the
    ``photon_serving_bad_request_total`` label."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


def _count_bad_request(kind: str) -> None:
    obs.current_run().registry.counter(
        "photon_serving_bad_request_total",
        "malformed socket requests refused with a typed error",
    ).labels(kind=kind).inc()


def _parse_score_request(msg) -> Tuple[ScoreRequest, Optional[float]]:
    """Validate one decoded JSON request; raises BadRequestError('bad_fields')
    on anything the engine should never see. Returns (request, deadline_s)."""
    if not isinstance(msg, dict):
        raise BadRequestError(
            "bad_fields", f"request must be a JSON object, got {type(msg).__name__}"
        )
    if "features" not in msg:
        raise BadRequestError("bad_fields", "missing required field 'features'")
    features = msg["features"]
    if not isinstance(features, dict):
        raise BadRequestError(
            "bad_fields",
            f"'features' must map shard -> [[idx...], [val...]], "
            f"got {type(features).__name__}",
        )
    parsed = {}
    for shard, iv in features.items():
        if (
            not isinstance(iv, (list, tuple))
            or len(iv) != 2
            or not all(isinstance(x, (list, tuple)) for x in iv)
            or len(iv[0]) != len(iv[1])
        ):
            raise BadRequestError(
                "bad_fields",
                f"features[{shard!r}] must be two equal-length lists "
                "[[idx...], [val...]]",
            )
        idx, val = iv
        if not all(isinstance(i, int) and not isinstance(i, bool) and i >= 0 for i in idx):
            raise BadRequestError(
                "bad_fields", f"features[{shard!r}] indices must be ints >= 0"
            )
        if not all(
            isinstance(v, numbers.Real) and not isinstance(v, bool) for v in val
        ):
            raise BadRequestError(
                "bad_fields", f"features[{shard!r}] values must be numbers"
            )
        parsed[shard] = (tuple(int(i) for i in idx), tuple(float(v) for v in val))
    ids = msg.get("ids", {})
    if not isinstance(ids, dict):
        raise BadRequestError("bad_fields", "'ids' must be a JSON object")
    offset = msg.get("offset", 0.0)
    if not isinstance(offset, numbers.Real) or isinstance(offset, bool):
        raise BadRequestError("bad_fields", "'offset' must be a number")
    deadline_ms = msg.get("deadline_ms")
    deadline_s: Optional[float] = None
    if deadline_ms is not None:
        if not isinstance(deadline_ms, numbers.Real) or isinstance(deadline_ms, bool):
            raise BadRequestError("bad_fields", "'deadline_ms' must be a number")
        if float(deadline_ms) <= 0:
            raise BadRequestError("bad_fields", "'deadline_ms' must be > 0")
        deadline_s = float(deadline_ms) / 1e3
    return ScoreRequest(features=parsed, ids=ids, offset=float(offset)), deadline_s


# connection sequence for trace_id assignment: ids are unique per process
# (pid prefix) and per accepted connection, so a fleet-merged trace stream
# never collides request ids across replicas
_conn_ids = itertools.count(1)


def _handle_conn(server: ScoringServer, conn: socket.socket, conns, conns_lock) -> None:
    """One JSON-lines connection: the shared handler behind both the AF_UNIX
    and the TCP listener. Registered in ``conns`` so the listener can shut
    the connection down deterministically at stop time. Every request gets
    a ``trace_id`` (``<pid>-<conn>.<seq>``, or the client's own) echoed on
    every response shape."""
    conn_id = f"{os.getpid():x}-{next(_conn_ids)}"
    req_seq = itertools.count(1)
    try:
        with conn, conn.makefile("rwb") as f:

            def respond(doc: dict) -> bool:
                try:
                    f.write((json.dumps(doc) + "\n").encode())
                    f.flush()
                    return True
                except (OSError, ValueError):
                    return False  # peer (or the stop path) tore the socket down

            while True:
                try:
                    line = f.readline(MAX_REQUEST_LINE_BYTES + 1)
                except (OSError, ValueError):
                    break  # shutdown() from the stop path, or peer reset
                if not line:
                    break  # clean EOF
                trace_id = f"{conn_id}.{next(req_seq)}"
                if len(line) > MAX_REQUEST_LINE_BYTES:
                    # framing is unrecoverable past the cap: typed refusal,
                    # then a deterministic close
                    _count_bad_request("oversized")
                    respond(
                        {
                            "error": (
                                "request line exceeds "
                                f"{MAX_REQUEST_LINE_BYTES} bytes"
                            ),
                            "error_type": "bad_request",
                            "kind": "oversized",
                            "trace_id": trace_id,
                        }
                    )
                    break
                if not line.endswith(b"\n"):
                    # mid-line disconnect: nothing to respond to, close clean
                    _count_bad_request("disconnect")
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as exc:
                    _count_bad_request("not_json")
                    if not respond(
                        {
                            "error": f"request is not valid JSON: {exc}",
                            "error_type": "bad_request",
                            "kind": "not_json",
                            "trace_id": trace_id,
                        }
                    ):
                        break
                    continue
                if isinstance(msg, dict) and msg.get("trace_id") is not None:
                    # client-supplied correlation id: echoed and threaded
                    # through the stage spans in place of the assigned one
                    trace_id = str(msg["trace_id"])
                with obs.span("serving.request", trace_id=trace_id) as root:
                    try:
                        req, deadline_s = _parse_score_request(msg)
                    except BadRequestError as exc:
                        _count_bad_request(exc.kind)
                        root.attrs["outcome"] = "bad_request"
                        out = {
                            "error": str(exc),
                            "error_type": "bad_request",
                            "kind": exc.kind,
                            "trace_id": trace_id,
                        }
                    else:
                        trace = RequestTrace(trace_id=trace_id, parent=root)
                        try:
                            out = {
                                "score": server.score(
                                    req, deadline_s=deadline_s, trace=trace
                                ),
                                "trace_id": trace_id,
                            }
                            root.attrs["outcome"] = "ok"
                        except ShedError as exc:
                            # admission refusal: a typed response, never a
                            # dropped connection — the client can back off
                            # and retry
                            root.attrs["outcome"] = "shed"
                            out = {
                                "error": str(exc),
                                "error_type": "shed",
                                "reason": exc.reason,
                                "trace_id": trace_id,
                            }
                        except Exception as exc:
                            obs.swallowed_error("serving.socket")
                            root.attrs["outcome"] = "error"
                            out = {
                                "error": str(exc),
                                "error_type": "error",
                                "trace_id": trace_id,
                            }
                if not respond(out):
                    break
    finally:
        with conns_lock:
            conns.discard(conn)


def _parse_listen(listen: Union[str, Tuple[str, int]]) -> Tuple[str, int]:
    if isinstance(listen, (tuple, list)) and len(listen) == 2:
        return str(listen[0]), int(listen[1])
    host, sep, port = str(listen).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"--listen address must be host:port, got {listen!r}"
        )
    return host, int(port)


def serve_socket(
    server: ScoringServer,
    path: Optional[str] = None,
    stop_event: Optional[threading.Event] = None,
    listen: Optional[Union[str, Tuple[str, int]]] = None,
    on_bound=None,
) -> None:
    """Serve ``server`` over exactly one of an AF_UNIX socket at ``path`` or
    a TCP listener at ``listen`` ("host:port" or (host, port); port 0 binds
    ephemeral) until ``stop_event`` is set (runs forever without one). One
    thread per connection through the shared JSON-lines handler;
    ``on_bound`` (if given) is called once with the bound address — the
    socket path, or the (host, port) tuple with the resolved port.

    Shutdown is deterministic: when ``stop_event`` fires, every open
    connection is shut down (interrupting blocked reads) and every handler
    thread joined before this function returns — no daemon thread survives
    holding an open socket."""
    if (path is None) == (listen is None):
        raise ValueError(
            "serve_socket needs exactly one of path (AF_UNIX) / listen (TCP "
            "host:port)"
        )
    stop = stop_event or threading.Event()
    conns: set = set()
    conns_lock = threading.Lock()
    threads = []
    if path is not None:
        if os.path.exists(path):
            os.unlink(path)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.bind(path)
        except BaseException:
            sock.close()  # a bind error must not leak the fd
            raise
        bound: object = path
    else:
        host, port = _parse_listen(listen)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
            bound = sock.getsockname()[:2]
        except BaseException:
            sock.close()  # a bind error must not leak the fd
            raise
    try:
        with sock:
            sock.listen()
            sock.settimeout(0.2)
            if on_bound is not None:
                on_bound(bound)
            while not stop.is_set():
                try:
                    conn, _ = sock.accept()
                except socket.timeout:
                    continue
                with conns_lock:
                    conns.add(conn)
                t = threading.Thread(
                    target=_handle_conn,
                    args=(server, conn, conns, conns_lock),
                    daemon=True,
                )
                threads.append(t)
                t.start()
                if len(threads) > 64:
                    threads = [x for x in threads if x.is_alive()]
    finally:
        with conns_lock:
            live = list(conns)
        for c in live:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already closed by its handler
        for t in threads:
            t.join(timeout=5.0)
        if path is not None and os.path.exists(path):
            os.unlink(path)
