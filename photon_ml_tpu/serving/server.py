"""The resident scoring service: stores + engines + per-model microbatchers
+ refresh, composed behind one `submit`/`score` surface.

The server holds a :class:`~photon_ml_tpu.serving.fleet.ModelSet` — one or
many named resident models, each behind its own bulkhead (see
``serving.fleet``). Requests route by name (``model=`` on the protocol, or
the server's default model); each model keeps exactly one live
``ScoreEngine``: its batcher captures that reference once per microbatch,
and its own ``RefreshWatcher`` flip replaces it with a single attribute
assignment — the GIL makes the swap atomic, the per-batch capture makes it
*clean*: every batch scores entirely on one snapshot, and flips stagger
per model.

Overload protection is the batcher's deadline-budget admission control
(``serving.batcher``): requests carry a latency budget
(``default_deadline_ms`` server-wide, or per request), the pending queue is
bounded, and refusals are typed ``ShedError`` responses counted in
``photon_serving_shed_total{reason=}`` — past the saturation knee the server
sheds excess load instead of letting the queue collapse everyone's p99.

For processes that can't link the package, ``serve_socket`` exposes the same
surface over an AF_UNIX socket (``path=``) or a TCP listener
(``listen="host:port"``) speaking JSON lines through one shared
connection-handler::

    -> {"features": {"shard": [[idx...], [val...]]}, "ids": {...},
        "offset": 0.0, "deadline_ms": 50, "model": "jobs-us"}
    <- {"score": 1.25, "model": "jobs-us", "trace_id": "..."}
     | {"error": "...", "error_type": "shed", "reason": "deadline",
        "model": "jobs-us", "trace_id": "..."}
     | {"error": "...", "error_type": "bad_request", "kind": "not_json",
        "model": "default", "trace_id": "..."}
     | {"error": "...", "error_type": "error", "model": "jobs-us",
        "trace_id": "..."}

one connection per client, one request per line, responses in order.
``model`` is optional on requests (the server's default model otherwise)
and echoed — resolved — on every response shape, so a fleet client can
always attribute a response: the model the request scored (or shed)
against, the requested name verbatim on an ``unknown_model`` refusal, and
the default model's name when the request was too malformed to name one.
A request naming a model the fleet does not hold (or one still warming) is
answered with a typed ``bad_request`` kind=``unknown_model`` — counted,
never silently scored against the default.
Every response carries a ``trace_id`` — success, shed and bad_request
alike — assigned per connection at accept time (or echoed back when the
client sent its own ``"trace_id"`` field); the same id threads through the
batcher's per-stage spans (``serving.admit``/``serving.batch``/
``serving.score``, parented under the request's ``serving.request`` span),
so one slow response is greppable end to end across the trace timeline.
Malformed input never kills the connection silently: oversized lines,
non-JSON, and bad fields each get a typed error (and a
``photon_serving_bad_request_total{kind=}`` count); mid-line disconnects are
counted and closed cleanly. On ``stop_event`` every open connection is shut
down deterministically and its handler thread joined — no daemon thread
outlives the listener holding an open socket.
"""

from __future__ import annotations

import itertools
import json
import numbers
import os
import socket
import threading
from typing import Optional, Tuple, Union

import jax.numpy as jnp

from .. import obs
from .batcher import RequestTrace, ShedError
from .engine import ScoreEngine, ScoreRequest
from .fleet import ModelSet, UnknownModelError, discover_fleet
from .store import ModelStore

# One JSON-lines request must fit one line; past this the framing cannot be
# trusted, so the response is a typed refusal and the connection closes.
MAX_REQUEST_LINE_BYTES = 1 << 20


class ScoringServer:
    """Resident scorer over published serving roots (or fixed stores/engines).

    With ``serving_root`` the server opens the CURRENT snapshot and watches
    for newly published ones, flipping without dropping requests; with a
    bare ``store``/``engine`` it serves that model until closed. Those
    single-model spellings serve one model named ``default``. The fleet
    spellings hold N models, each behind its own bulkhead and refresh
    watcher (``serving.fleet``): ``models`` maps name -> source (serving
    root path, store dir path, ``ModelStore``, or ``ScoreEngine``);
    ``fleet_root`` discovers one model per subdirectory. Requests route by
    ``model`` (``--models`` name), defaulting to ``default_model`` (the
    first model otherwise)."""

    def __init__(
        self,
        store: Optional[ModelStore] = None,
        engine: Optional[ScoreEngine] = None,
        serving_root: Optional[str] = None,
        models=None,
        fleet_root: Optional[str] = None,
        default_model: Optional[str] = None,
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
        max_pending: int = 1024,
        default_deadline_ms: Optional[float] = None,
        overload_shed_threshold: Optional[float] = None,
        poll_seconds: float = 0.2,
        dtype=jnp.float32,
        status_port: Optional[int] = None,
        slow_request_ms: Optional[float] = None,
        per_model=None,
        warm_async: bool = False,
    ):
        sources = (store, engine, serving_root, models, fleet_root)
        if sum(x is not None for x in sources) != 1:
            raise ValueError(
                "pass exactly one of store / engine / serving_root / "
                "models / fleet_root"
            )
        self.dtype = dtype
        self.default_deadline_s: Optional[float] = (
            None if default_deadline_ms is None else float(default_deadline_ms) / 1e3
        )
        self._status_server = None
        if fleet_root is not None:
            models = discover_fleet(fleet_root)
        if models is None:
            single = store if store is not None else engine
            models = {"default": serving_root if single is None else single}
        self._models = ModelSet(
            models,
            default_model=default_model,
            max_batch=max_batch,
            max_latency_ms=max_latency_ms,
            max_pending=max_pending,
            slow_request_ms=slow_request_ms,
            per_model=per_model,
            poll_seconds=poll_seconds,
            dtype=dtype,
            warm_async=warm_async,
        )
        if overload_shed_threshold is not None:
            # /healthz compares the scrape-delta shed rate against this
            # (obs/http.py): past it the replica answers 503 "overloaded"
            # so a load balancer backs off while scoring itself continues
            obs.current_run().status.update(
                overload_shed_threshold=float(overload_shed_threshold)
            )
        if status_port is not None:
            # live scrape surface (metrics otherwise only flush to files at
            # close): /metrics text exposition, /healthz, /statusz with
            # request QPS + latency quantiles. Bound to the run current at
            # construction — the one the batcher records into.
            self._status_server = obs.IntrospectionServer(
                obs.current_run(), port=status_port
            )
            # advertise the live snapshot on /statusz
            obs.current_run().status.update(
                serving_snapshot=self.snapshot_name
            )

    @property
    def status_port(self) -> Optional[int]:
        """Bound introspection port (useful with ``status_port=0``)."""
        return None if self._status_server is None else self._status_server.port

    # -- fleet surface --------------------------------------------------------

    @property
    def snapshot_name(self) -> Optional[str]:
        """The default model's live snapshot (single-model compatibility)."""
        return self._models.snapshot_names[self._models.default_model]

    @property
    def snapshot_names(self) -> dict:
        """Live snapshot per resident model."""
        return self._models.snapshot_names

    @property
    def model_names(self) -> list:
        return self._models.names

    @property
    def default_model_name(self) -> str:
        return self._models.default_model

    def resolve_model(self, model: Optional[str]) -> str:
        """Resolved model name for a requested one (None -> default);
        raises :class:`~photon_ml_tpu.serving.fleet.UnknownModelError` for
        names this fleet does not hold or has not finished warming."""
        return self._models.resolve(model)

    def poke_refresh(self, model: Optional[str] = None) -> None:
        """Force an immediate CURRENT check on one model's watcher, or all
        of them (tests; avoids poll sleeps)."""
        self._models.poke_refresh(model)

    # -- scoring surface ------------------------------------------------------

    def submit(
        self,
        request: ScoreRequest,
        deadline_s: Optional[float] = None,
        trace: Optional[RequestTrace] = None,
        model: Optional[str] = None,
    ):
        """Enqueue one request; returns a Future resolving to its score.
        ``deadline_s`` overrides the server's ``default_deadline_ms`` budget
        for this request (None = use the server default; the admission
        controller may raise :class:`ShedError` immediately). ``model``
        (explicit arg, else ``request.model``) routes to that model's
        bulkhead. ``trace`` threads a request-scoped trace context
        (trace_id + root span) through the batcher's per-stage spans."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        return self._models.submit(
            request, deadline_s=deadline_s, trace=trace, model=model
        )

    def score(
        self,
        request: ScoreRequest,
        timeout: float = 30.0,
        deadline_s: Optional[float] = None,
        trace: Optional[RequestTrace] = None,
        model: Optional[str] = None,
    ) -> float:
        """Blocking single-request score (sheds surface as ShedError)."""
        return self.submit(
            request, deadline_s=deadline_s, trace=trace, model=model
        ).result(timeout=timeout)

    def queue_stats(self, model: Optional[str] = None) -> dict:
        """Live admission-queue stats (pending depth + drain estimate):
        one model's by name, or the fleet aggregate on a multi-model set."""
        return self._models.queue_stats(model)

    def close(self) -> None:
        if self._status_server is not None:
            self._status_server.stop()
        self._models.close()


# -- the socket front --------------------------------------------------------


class BadRequestError(ValueError):
    """A socket request the server refuses to parse; ``kind`` is the
    ``photon_serving_bad_request_total`` label."""

    def __init__(self, kind: str, message: str):
        super().__init__(message)
        self.kind = kind


def _count_bad_request(kind: str) -> None:
    obs.current_run().registry.counter(
        "photon_serving_bad_request_total",
        "malformed socket requests refused with a typed error",
    ).labels(kind=kind).inc()


def _parse_score_request(msg) -> Tuple[ScoreRequest, Optional[float]]:
    """Validate one decoded JSON request; raises BadRequestError('bad_fields')
    on anything the engine should never see. Returns (request, deadline_s)."""
    if not isinstance(msg, dict):
        raise BadRequestError(
            "bad_fields", f"request must be a JSON object, got {type(msg).__name__}"
        )
    if "features" not in msg:
        raise BadRequestError("bad_fields", "missing required field 'features'")
    features = msg["features"]
    if not isinstance(features, dict):
        raise BadRequestError(
            "bad_fields",
            f"'features' must map shard -> [[idx...], [val...]], "
            f"got {type(features).__name__}",
        )
    parsed = {}
    for shard, iv in features.items():
        if (
            not isinstance(iv, (list, tuple))
            or len(iv) != 2
            or not all(isinstance(x, (list, tuple)) for x in iv)
            or len(iv[0]) != len(iv[1])
        ):
            raise BadRequestError(
                "bad_fields",
                f"features[{shard!r}] must be two equal-length lists "
                "[[idx...], [val...]]",
            )
        idx, val = iv
        if not all(isinstance(i, int) and not isinstance(i, bool) and i >= 0 for i in idx):
            raise BadRequestError(
                "bad_fields", f"features[{shard!r}] indices must be ints >= 0"
            )
        if not all(
            isinstance(v, numbers.Real) and not isinstance(v, bool) for v in val
        ):
            raise BadRequestError(
                "bad_fields", f"features[{shard!r}] values must be numbers"
            )
        parsed[shard] = (tuple(int(i) for i in idx), tuple(float(v) for v in val))
    ids = msg.get("ids", {})
    if not isinstance(ids, dict):
        raise BadRequestError("bad_fields", "'ids' must be a JSON object")
    offset = msg.get("offset", 0.0)
    if not isinstance(offset, numbers.Real) or isinstance(offset, bool):
        raise BadRequestError("bad_fields", "'offset' must be a number")
    model = msg.get("model")
    if model is not None and not isinstance(model, str):
        raise BadRequestError(
            "bad_fields", "'model' must be a string (a resident model name)"
        )
    deadline_ms = msg.get("deadline_ms")
    deadline_s: Optional[float] = None
    if deadline_ms is not None:
        if not isinstance(deadline_ms, numbers.Real) or isinstance(deadline_ms, bool):
            raise BadRequestError("bad_fields", "'deadline_ms' must be a number")
        if float(deadline_ms) <= 0:
            raise BadRequestError("bad_fields", "'deadline_ms' must be > 0")
        deadline_s = float(deadline_ms) / 1e3
    return (
        ScoreRequest(features=parsed, ids=ids, offset=float(offset), model=model),
        deadline_s,
    )


# connection sequence for trace_id assignment: ids are unique per process
# (pid prefix) and per accepted connection, so a fleet-merged trace stream
# never collides request ids across replicas
_conn_ids = itertools.count(1)


def _requested_model(msg, server: ScoringServer) -> str:
    """Best-effort model echo for refused requests: the name the request
    asked for when it managed to say one, else the default model (the one
    it would have scored against)."""
    if isinstance(msg, dict) and isinstance(msg.get("model"), str):
        return msg["model"]
    return server.default_model_name


def _handle_conn(server: ScoringServer, conn: socket.socket, conns, conns_lock) -> None:
    """One JSON-lines connection: the shared handler behind both the AF_UNIX
    and the TCP listener. Registered in ``conns`` so the listener can shut
    the connection down deterministically at stop time. Every request gets
    a ``trace_id`` (``<pid>-<conn>.<seq>``, or the client's own) echoed on
    every response shape, and every response echoes the resolved ``model``."""
    conn_id = f"{os.getpid():x}-{next(_conn_ids)}"
    req_seq = itertools.count(1)
    try:
        with conn, conn.makefile("rwb") as f:

            def respond(doc: dict) -> bool:
                try:
                    f.write((json.dumps(doc) + "\n").encode())
                    f.flush()
                    return True
                except (OSError, ValueError):
                    return False  # peer (or the stop path) tore the socket down

            while True:
                try:
                    line = f.readline(MAX_REQUEST_LINE_BYTES + 1)
                except (OSError, ValueError):
                    break  # shutdown() from the stop path, or peer reset
                if not line:
                    break  # clean EOF
                trace_id = f"{conn_id}.{next(req_seq)}"
                if len(line) > MAX_REQUEST_LINE_BYTES:
                    # framing is unrecoverable past the cap: typed refusal,
                    # then a deterministic close
                    _count_bad_request("oversized")
                    respond(
                        {
                            "error": (
                                "request line exceeds "
                                f"{MAX_REQUEST_LINE_BYTES} bytes"
                            ),
                            "error_type": "bad_request",
                            "kind": "oversized",
                            "model": server.default_model_name,
                            "trace_id": trace_id,
                        }
                    )
                    break
                if not line.endswith(b"\n"):
                    # mid-line disconnect: nothing to respond to, close clean
                    _count_bad_request("disconnect")
                    break
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError as exc:
                    _count_bad_request("not_json")
                    if not respond(
                        {
                            "error": f"request is not valid JSON: {exc}",
                            "error_type": "bad_request",
                            "kind": "not_json",
                            "model": server.default_model_name,
                            "trace_id": trace_id,
                        }
                    ):
                        break
                    continue
                if isinstance(msg, dict) and msg.get("trace_id") is not None:
                    # client-supplied correlation id: echoed and threaded
                    # through the stage spans in place of the assigned one
                    trace_id = str(msg["trace_id"])
                with obs.span("serving.request", trace_id=trace_id) as root:
                    try:
                        req, deadline_s = _parse_score_request(msg)
                        # resolve BEFORE queueing: an unknown (or still
                        # warming) model is a typed refusal, never scored
                        # against the default and never owed a queue slot
                        resolved = server.resolve_model(req.model)
                    except BadRequestError as exc:
                        _count_bad_request(exc.kind)
                        root.attrs["outcome"] = "bad_request"
                        out = {
                            "error": str(exc),
                            "error_type": "bad_request",
                            "kind": exc.kind,
                            "model": _requested_model(msg, server),
                            "trace_id": trace_id,
                        }
                    except UnknownModelError as exc:
                        _count_bad_request(exc.kind)
                        root.attrs["outcome"] = "bad_request"
                        out = {
                            "error": str(exc),
                            "error_type": "bad_request",
                            "kind": exc.kind,
                            "model": _requested_model(msg, server),
                            "trace_id": trace_id,
                        }
                    else:
                        root.attrs["model"] = resolved
                        trace = RequestTrace(trace_id=trace_id, parent=root)
                        try:
                            out = {
                                "score": server.score(
                                    req, deadline_s=deadline_s, trace=trace
                                ),
                                "model": resolved,
                                "trace_id": trace_id,
                            }
                            root.attrs["outcome"] = "ok"
                        except ShedError as exc:
                            # admission refusal: a typed response, never a
                            # dropped connection — the client can back off
                            # and retry
                            root.attrs["outcome"] = "shed"
                            out = {
                                "error": str(exc),
                                "error_type": "shed",
                                "reason": exc.reason,
                                "model": resolved,
                                "trace_id": trace_id,
                            }
                        except Exception as exc:
                            obs.swallowed_error("serving.socket")
                            root.attrs["outcome"] = "error"
                            out = {
                                "error": str(exc),
                                "error_type": "error",
                                "model": resolved,
                                "trace_id": trace_id,
                            }
                if not respond(out):
                    break
    except OSError:
        pass  # makefile close flushes into a torn-down socket (replica kill)
    finally:
        with conns_lock:
            conns.discard(conn)


def _parse_listen(listen: Union[str, Tuple[str, int]]) -> Tuple[str, int]:
    if isinstance(listen, (tuple, list)) and len(listen) == 2:
        return str(listen[0]), int(listen[1])
    host, sep, port = str(listen).rpartition(":")
    if not sep or not host:
        raise ValueError(
            f"--listen address must be host:port, got {listen!r}"
        )
    return host, int(port)


def serve_socket(
    server,
    path: Optional[str] = None,
    stop_event: Optional[threading.Event] = None,
    listen: Optional[Union[str, Tuple[str, int]]] = None,
    on_bound=None,
    handler=None,
) -> None:
    """Serve ``server`` over exactly one of an AF_UNIX socket at ``path`` or
    a TCP listener at ``listen`` ("host:port" or (host, port); port 0 binds
    ephemeral) until ``stop_event`` is set (runs forever without one). One
    thread per connection through the shared JSON-lines handler —
    ``handler`` swaps it out (same ``(server, conn, conns, conns_lock)``
    signature; the replica front's pass-through handler reuses this whole
    accept/shutdown loop over its own routing surface). ``on_bound`` (if
    given) is called once with the bound address — the socket path, or the
    (host, port) tuple with the resolved port.

    Shutdown is deterministic: when ``stop_event`` fires, every open
    connection is shut down (interrupting blocked reads) and every handler
    thread joined before this function returns — no daemon thread survives
    holding an open socket."""
    if (path is None) == (listen is None):
        raise ValueError(
            "serve_socket needs exactly one of path (AF_UNIX) / listen (TCP "
            "host:port)"
        )
    stop = stop_event or threading.Event()
    handler = handler or _handle_conn
    conns: set = set()
    conns_lock = threading.Lock()
    threads = []
    if path is not None:
        if os.path.exists(path):
            os.unlink(path)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.bind(path)
        except BaseException:
            sock.close()  # a bind error must not leak the fd
            raise
        bound: object = path
    else:
        host, port = _parse_listen(listen)
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind((host, port))
            bound = sock.getsockname()[:2]
        except BaseException:
            sock.close()  # a bind error must not leak the fd
            raise
    try:
        with sock:
            sock.listen()
            sock.settimeout(0.2)
            if on_bound is not None:
                on_bound(bound)
            while not stop.is_set():
                try:
                    conn, _ = sock.accept()
                except socket.timeout:
                    continue
                with conns_lock:
                    conns.add(conn)
                t = threading.Thread(
                    target=handler,
                    args=(server, conn, conns, conns_lock),
                    daemon=True,
                )
                threads.append(t)
                t.start()
                if len(threads) > 64:
                    threads = [x for x in threads if x.is_alive()]
    finally:
        with conns_lock:
            live = list(conns)
        for c in live:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass  # already closed by its handler
        for t in threads:
            t.join(timeout=5.0)
        if path is not None and os.path.exists(path):
            os.unlink(path)
