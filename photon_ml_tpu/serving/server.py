"""The resident scoring service: store + engine + microbatcher + refresh,
composed behind one `submit`/`score` surface.

The server keeps exactly one live ``ScoreEngine``; the batcher captures that
reference once per microbatch, and a ``RefreshWatcher`` flip replaces it with
a single attribute assignment — the GIL makes the swap atomic, the per-batch
capture makes it *clean*: every batch scores entirely on one snapshot.

For processes that can't link the package, ``serve_socket`` exposes the same
surface over an AF_UNIX socket speaking JSON lines::

    -> {"features": {"shard": [[idx...], [val...]]}, "ids": {...}, "offset": 0.0}
    <- {"score": 1.25}   |   {"error": "..."}

one connection per client, one request per line, responses in order.
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Optional

import jax.numpy as jnp

from .. import obs
from .batcher import MicroBatcher
from .engine import ScoreEngine, ScoreRequest
from .refresh import RefreshWatcher, open_current
from .store import ModelStore


class ScoringServer:
    """Resident scorer over a published serving root (or a fixed store/engine).

    With ``serving_root`` the server opens the CURRENT snapshot and watches
    for newly published ones, flipping without dropping requests; with a
    bare ``store``/``engine`` it serves that model until closed."""

    def __init__(
        self,
        store: Optional[ModelStore] = None,
        engine: Optional[ScoreEngine] = None,
        serving_root: Optional[str] = None,
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
        poll_seconds: float = 0.2,
        dtype=jnp.float32,
        status_port: Optional[int] = None,
    ):
        if sum(x is not None for x in (store, engine, serving_root)) != 1:
            raise ValueError("pass exactly one of store / engine / serving_root")
        self.dtype = dtype
        self.snapshot_name: Optional[str] = None
        self._lock = threading.Lock()
        self._watcher: Optional[RefreshWatcher] = None
        self._status_server = None
        if serving_root is not None:
            name, store = open_current(serving_root)
            self._install(name, store)
            self._watcher = RefreshWatcher(
                serving_root, self._install, poll_seconds=poll_seconds, live=name
            )
        elif store is not None:
            self._install(None, store)
        else:
            self._engine = engine
        self._engine.warm()
        self._batcher = MicroBatcher(
            self._current_engine, max_batch=max_batch, max_latency_ms=max_latency_ms
        )
        if status_port is not None:
            # live scrape surface (metrics otherwise only flush to files at
            # close): /metrics text exposition, /healthz, /statusz with
            # request QPS + latency quantiles. Bound to the run current at
            # construction — the one the batcher records into.
            self._status_server = obs.IntrospectionServer(
                obs.current_run(), port=status_port
            )
            # advertise the live snapshot on /statusz
            obs.current_run().status.update(
                serving_snapshot=self.snapshot_name
            )

    @property
    def status_port(self) -> Optional[int]:
        """Bound introspection port (useful with ``status_port=0``)."""
        return None if self._status_server is None else self._status_server.port

    # -- refresh flip ---------------------------------------------------------

    def _install(self, name: Optional[str], store: ModelStore) -> None:
        """Build the engine for a freshly opened store, then flip the live
        reference in one assignment (warm first: the flip must not stall
        in-flight traffic on a compile)."""
        live = getattr(self, "_batcher", None) is not None
        if live:
            # /healthz answers 503 for exactly the mid-publish window, so a
            # load balancer drains this replica while the flip is in flight
            # (scoring itself keeps working — the old engine serves until
            # the one-assignment swap below)
            obs.current_run().status.update(refresh_in_progress=True)
        try:
            engine = ScoreEngine.from_store(store, dtype=self.dtype)
            if live:
                engine.warm()
            with self._lock:
                self._engine = engine
                self.snapshot_name = name
        finally:
            if live:
                obs.current_run().status.update(refresh_in_progress=False)
        if getattr(self, "_status_server", None) is not None:
            obs.current_run().status.update(serving_snapshot=name)

    def _current_engine(self) -> ScoreEngine:
        with self._lock:
            return self._engine

    def poke_refresh(self) -> None:
        """Force an immediate CURRENT check (tests; avoids poll sleeps)."""
        if self._watcher is not None:
            self._watcher.poke()

    # -- scoring surface ------------------------------------------------------

    def submit(self, request: ScoreRequest):
        """Enqueue one request; returns a Future resolving to its score."""
        return self._batcher.submit(request)

    def score(self, request: ScoreRequest, timeout: float = 30.0) -> float:
        """Blocking single-request score."""
        return self._batcher.submit(request).result(timeout=timeout)

    def close(self) -> None:
        if self._watcher is not None:
            self._watcher.stop()
        if self._status_server is not None:
            self._status_server.stop()
        self._batcher.close()


def _handle_conn(server: ScoringServer, conn: socket.socket) -> None:
    with conn, conn.makefile("rwb") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
                req = ScoreRequest(
                    features={
                        shard: (tuple(iv[0]), tuple(iv[1]))
                        for shard, iv in msg.get("features", {}).items()
                    },
                    ids=msg.get("ids", {}),
                    offset=float(msg.get("offset", 0.0)),
                )
                out = {"score": server.score(req)}
            except Exception as exc:
                obs.swallowed_error("serving.socket")
                out = {"error": str(exc)}
            f.write((json.dumps(out) + "\n").encode())
            f.flush()


def serve_socket(
    server: ScoringServer,
    path: str,
    stop_event: Optional[threading.Event] = None,
) -> None:
    """Serve ``server`` over an AF_UNIX socket at ``path`` until
    ``stop_event`` is set (runs forever without one). One thread per
    connection; requests within a connection are answered in order."""
    if os.path.exists(path):
        os.unlink(path)
    stop = stop_event or threading.Event()
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as sock:
        sock.bind(path)
        sock.listen()
        sock.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            threading.Thread(
                target=_handle_conn, args=(server, conn), daemon=True
            ).start()
    if os.path.exists(path):
        os.unlink(path)
