"""Open-loop load generation for the resident scorer.

A closed-loop client (send, wait, send) can never measure overload: each
client caps its own in-flight work at 1, so offered load collapses to served
load and queueing delay hides inside the think time — the *coordinated
omission* artifact. This module drives the server the way production
traffic does: arrivals are a seeded Poisson process at a target offered
QPS, sent on schedule whether or not earlier requests have returned, and
every latency is measured from the request's **intended** send time — if
the dispatcher (or the server's queue) falls behind, the backlog shows up
in the numbers instead of silently stretching the arrival schedule.

The pure-math core is separated from the wall clock so the accounting
itself is unit-testable:

- :func:`poisson_intended_times` — the seeded arrival schedule;
- :func:`simulate_fifo_open_loop` / :func:`simulate_fifo_closed_loop` —
  the same FIFO server measured both ways, proving where closed-loop
  measurement hides queueing delay (pinned in ``tests/test_overload.py``);
- :func:`run_open_loop` — drive a real ``submit`` callable (a
  ``ScoringServer`` / ``MicroBatcher``) at one offered QPS;
- :func:`find_knee` — locate the saturation knee in a sweep: the highest
  offered load the server still serves (served >= ``served_fraction`` x
  offered).

``bench.py --config serving-openloop`` sweeps offered load through this
module and reports the knee + past-knee behavior through the
direction-aware ``--diff`` gate.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import wait as futures_wait
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .batcher import ShedError


def _now() -> float:
    # photon: ignore[R7] — the load generator's one clock read: intended-
    # send-time arithmetic and cross-thread completion stamps, not a
    # measured section a span could bracket
    return time.perf_counter()


# -- pure math ---------------------------------------------------------------


def poisson_intended_times(
    offered_qps: float, duration_s: float, seed: int = 0
) -> np.ndarray:
    """Intended send offsets (seconds from epoch start) of a Poisson arrival
    process at ``offered_qps`` over ``duration_s`` — exponential
    inter-arrivals, seeded, so a given (qps, duration, seed) always yields
    the same schedule."""
    if offered_qps <= 0:
        raise ValueError(f"offered_qps must be > 0: {offered_qps}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0: {duration_s}")
    rng = np.random.default_rng(seed)
    # draw in chunks until the schedule passes duration_s
    out: List[np.ndarray] = []
    t = 0.0
    chunk = max(16, int(offered_qps * duration_s * 1.2))
    while t <= duration_s:
        gaps = rng.exponential(1.0 / offered_qps, size=chunk)
        times = t + np.cumsum(gaps)
        out.append(times)
        t = float(times[-1])
    times = np.concatenate(out)
    return times[times <= duration_s]


def simulate_fifo_open_loop(
    intended: Sequence[float], service_s: Sequence[float]
) -> List[float]:
    """Latencies through a single FIFO server, measured from each request's
    INTENDED send time: request k begins when both it has arrived and the
    server is free, so a stall's backlog lands on every request scheduled
    during it. This is the accounting :func:`run_open_loop` implements
    against a real server."""
    free_at = 0.0
    out: List[float] = []
    for a, s in zip(intended, service_s):
        begin = max(float(a), free_at)
        free_at = begin + float(s)
        out.append(free_at - float(a))
    return out


def simulate_fifo_closed_loop(service_s: Sequence[float]) -> List[float]:
    """What a closed-loop client measures on the same server: it sends the
    next request only after the previous response, so the server is always
    free at send time and the measured latency is exactly the service time.
    A 1-second stall appears in ONE sample instead of delaying every
    request scheduled during it — coordinated omission."""
    return [float(s) for s in service_s]


# -- one open-loop step against a real server --------------------------------


@dataclasses.dataclass
class OpenLoopResult:
    """One offered-QPS step. Latency quantiles are over *admitted completed*
    requests, measured from intended send time; ``sent`` counts every
    dispatch attempt, so ``sent == completed + shed_admission +
    shed_expired + errors`` (no request unaccounted for)."""

    offered_qps: float
    duration_s: float
    sent: int
    completed: int
    shed_admission: Dict[str, int]
    shed_expired: int
    errors: int
    served_qps: float
    achieved_offered_qps: float
    latency_mean_s: float
    latency_p50_s: float
    latency_p99_s: float

    @property
    def shed_total(self) -> int:
        return sum(self.shed_admission.values()) + self.shed_expired

    @property
    def served_fraction(self) -> float:
        return self.completed / max(self.sent, 1)


def run_open_loop(
    submit: Callable[..., object],
    requests: Sequence[object],
    offered_qps: float,
    duration_s: float,
    seed: int = 0,
    deadline_s: Optional[float] = None,
    drain_timeout_s: float = 30.0,
) -> OpenLoopResult:
    """Drive ``submit(request[, deadline_s])`` at ``offered_qps`` Poisson
    arrivals for ``duration_s``; requests cycle through ``requests``.

    The dispatcher sends on the intended schedule even when it is running
    late (late dispatch is *measured* as latency, never dropped from the
    schedule), admission refusals (:class:`ShedError` from ``submit``) are
    counted, and in-queue expiries / engine errors are collected from the
    returned futures. Returns after every dispatched request has a
    response or ``drain_timeout_s`` passes."""
    times = poisson_intended_times(offered_qps, duration_s, seed=seed)
    lock = threading.Lock()
    latencies: List[float] = []
    shed_admission: Dict[str, int] = {}
    shed_expired = 0
    errors = 0
    futures = []

    def _complete(fut, intended_at: float) -> None:
        nonlocal shed_expired, errors
        done = _now()
        exc = fut.exception()
        with lock:
            if exc is None:
                latencies.append(done - intended_at)
            elif isinstance(exc, ShedError):
                shed_expired += 1
            else:
                errors += 1

    t_start = _now()
    for k, offset in enumerate(times):
        intended = t_start + float(offset)
        while True:
            delta = intended - _now()
            if delta <= 0:
                break
            time.sleep(min(delta, 0.001))
        req = requests[k % len(requests)]
        try:
            fut = submit(req) if deadline_s is None else submit(req, deadline_s)
        except ShedError as exc:
            with lock:
                shed_admission[exc.reason] = shed_admission.get(exc.reason, 0) + 1
            continue
        futures.append(fut)
        fut.add_done_callback(lambda f, t=intended: _complete(f, t))
    futures_wait(futures, timeout=drain_timeout_s)
    t_end = _now()

    with lock:
        lats = np.asarray(latencies, dtype=np.float64)
        shed_adm = dict(shed_admission)
        n_expired, n_errors = shed_expired, errors
    wall = max(t_end - t_start, 1e-9)
    return OpenLoopResult(
        offered_qps=float(offered_qps),
        duration_s=float(duration_s),
        sent=len(times),
        completed=int(lats.size),
        shed_admission=shed_adm,
        shed_expired=n_expired,
        errors=n_errors,
        served_qps=float(lats.size / wall),
        achieved_offered_qps=float(len(times) / wall),
        latency_mean_s=float(lats.mean()) if lats.size else 0.0,
        latency_p50_s=float(np.percentile(lats, 50)) if lats.size else 0.0,
        latency_p99_s=float(np.percentile(lats, 99)) if lats.size else 0.0,
    )


# -- mixed multi-stream load (the bulkhead isolation drill) ------------------


def run_mixed_open_loop(
    submit: Callable[..., object],
    streams: Dict[str, dict],
    duration_s: float,
    seed: int = 0,
    drain_timeout_s: float = 30.0,
) -> Dict[str, OpenLoopResult]:
    """Drive several open-loop streams *concurrently* against one ``submit``
    — the multi-model isolation drill: a storm stream hammering one model
    must not move a victim stream's latency, because each model sits behind
    its own bulkhead (see ``serving.fleet``).

    ``streams`` maps a stream name to ``{"requests": [...], "offered_qps":
    q}`` (optional ``"deadline_s"``); each stream's requests should already
    carry the routing they need (e.g. ``ScoreRequest.model``). Each stream
    gets its own dispatcher thread and a seed derived from its (sorted)
    position, so the per-stream accounting invariant — ``sent == completed
    + shed + errors`` — holds independently per stream."""
    results: Dict[str, OpenLoopResult] = {}
    failures: Dict[str, BaseException] = {}

    def _run(name: str, spec: dict, stream_seed: int) -> None:
        try:
            results[name] = run_open_loop(
                submit,
                spec["requests"],
                spec["offered_qps"],
                duration_s,
                seed=stream_seed,
                deadline_s=spec.get("deadline_s"),
                drain_timeout_s=drain_timeout_s,
            )
        except BaseException as exc:  # photon: ignore[R4] — parked, re-raised by the caller after join
            failures[name] = exc

    threads = [
        threading.Thread(
            target=_run,
            args=(name, streams[name], seed + i),
            name=f"photon-loadgen-{name}",
        )
        for i, name in enumerate(sorted(streams))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if failures:
        name, exc = sorted(failures.items())[0]
        raise RuntimeError(f"mixed load stream {name!r} failed: {exc!r}") from exc
    return results


# -- sweep + knee ------------------------------------------------------------


def sweep_open_loop(
    submit: Callable[..., object],
    requests: Sequence[object],
    qps_steps: Sequence[float],
    duration_s: float,
    seed: int = 0,
    deadline_s: Optional[float] = None,
) -> List[OpenLoopResult]:
    """One :func:`run_open_loop` step per offered QPS, ascending, each with
    a distinct derived seed so schedules are independent."""
    return [
        run_open_loop(
            submit,
            requests,
            qps,
            duration_s,
            seed=seed + i,
            deadline_s=deadline_s,
        )
        for i, qps in enumerate(sorted(qps_steps))
    ]


def find_knee(
    steps: Sequence[OpenLoopResult], served_fraction: float = 0.9
) -> Optional[OpenLoopResult]:
    """The saturation knee of a sweep: the highest offered-QPS step whose
    served throughput still tracks offered load (served_qps >=
    ``served_fraction`` x offered_qps). Returns None when even the lightest
    step is past saturation."""
    knee = None
    for s in sorted(steps, key=lambda s: s.offered_qps):
        if s.served_qps >= served_fraction * s.offered_qps:
            knee = s
    return knee
