"""The one GLMix score assembly: compiled fixed+random-effect kernels shared
by batch scoring (``cli.score`` / ``GameTransformer.transform``) and the
resident request path (``serving.server``), so batch/resident parity is
structural rather than asserted.

Scoring semantics are the reference's (GameTransformer.scala:39-318): total
score = offsets + sum of per-coordinate margins, fixed effects as a dot
against one coefficient vector, random effects as a per-entity sparse dot
with unseen entities contributing 0 (the cold-start fallback — the request
path counts those in ``photon_serving_cold_start_total{coordinate=}``).

Kernel warmth: the jitted kernels take the coefficient tables as
*arguments*, not closures, so a refreshed snapshot with the same table
shapes re-uses the already-compiled executables (no recompile mid-flip),
and the persistent compile cache (``utils/compile_cache``) carries them
across server restarts. The resident path pads every request batch to a
small ladder of (rows, feature-width) shapes, so no request shape can
trigger a fresh compile once the ladder is warm.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..analysis.runtime import logged_fetch
from ..models.game import score_entity_ell

# Padding ladders for the resident request path. Rows round up to the next
# rung (bigger batches chunk at the top rung); the per-shard ELL feature
# width rounds up likewise. Small ladders keep the warm-kernel set small:
# at most len(LADDER_ROWS) * len(LADDER_WIDTH) compiled shapes per shard.
LADDER_ROWS: Tuple[int, ...] = (1, 8, 64, 256, 1024, 4096, 16384)
LADDER_WIDTH: Tuple[int, ...] = (4, 16, 64, 256, 512)


def _ladder_rows(n: int) -> int:
    for rung in LADDER_ROWS:
        if n <= rung:
            return rung
    return LADDER_ROWS[-1]


def _ladder_width(f: int) -> int:
    for rung in LADDER_WIDTH:
        if f <= rung:
            return rung
    raise ValueError(
        f"request feature width {f} exceeds the serving engine's padded "
        f"feature-width ladder (max {LADDER_WIDTH[-1]} features per row per "
        "shard); score such rows through the batch path (cli.score)"
    )


@jax.jit
def _fe_score_ell(weights, feat_idx, feat_val):
    """Fixed-effect margin for ELL-layout rows: one gather + masked-free dot
    (idx=0/val=0 padding contributes exact zeros)."""
    return jnp.sum(feat_val * jnp.take(weights, feat_idx, axis=0), axis=1)


@dataclasses.dataclass(frozen=True)
class ScoreRequest:
    """One scoring request: per-shard sparse features (already through the
    feature index map) plus the entity id per random-effect type.

    ``model`` routes the request in a multi-model fleet (``serving.fleet``):
    the name of the resident model to score against, or None for the
    server's default model. The engine itself ignores it — routing happens
    one layer up, in the per-model bulkhead lookup."""

    features: Mapping[str, Tuple[Sequence[int], Sequence[float]]]
    ids: Mapping[str, object] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    model: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class _FixedCoord:
    name: str
    feature_shard: str
    weights: object  # device f[d]


@dataclasses.dataclass(frozen=True)
class _RandomCoord:
    name: str
    feature_shard: str
    random_effect_type: str
    coef_indices: object  # device i32[E, S]
    coef_values: object  # device f[E, S]
    rows_for: object  # callable ids -> np.int64[n], -1 unseen


class ScoreEngine:
    """Compiled score assembly over one model's coordinate tables."""

    def __init__(self, coords: List[object], task: str, dtype=jnp.float32):
        self._coords = coords
        self.task = task
        self.dtype = dtype

    # -- construction --------------------------------------------------------

    @classmethod
    def from_model(cls, game_model, dtype=jnp.float32) -> "ScoreEngine":
        """Engine over an in-memory GameModel (the batch-scoring entry)."""
        from ..models.game import FixedEffectModel, RandomEffectModel

        coords: List[object] = []
        for name, sub in game_model.models.items():
            if isinstance(sub, FixedEffectModel):
                coords.append(
                    _FixedCoord(
                        name=name,
                        feature_shard=sub.feature_shard,
                        weights=sub.model.coefficients.means,
                    )
                )
            elif isinstance(sub, RandomEffectModel):
                coords.append(
                    _RandomCoord(
                        name=name,
                        feature_shard=sub.feature_shard,
                        random_effect_type=sub.random_effect_type,
                        coef_indices=sub.coef_indices,
                        coef_values=sub.coef_values,
                        rows_for=sub.rows_for,
                    )
                )
            else:
                raise TypeError(f"unknown model type for {name}: {type(sub)}")
        return cls(coords, game_model.task, dtype=dtype)

    @classmethod
    def from_store(cls, store, dtype=jnp.float32) -> "ScoreEngine":
        """Engine over an opened mmap ModelStore (the resident entry). The
        coefficient tables are staged to the device once here; entity-row
        lookups stay on the store's zero-heap mmap index."""
        from .store import FixedStoreCoord, RandomStoreCoord

        coords: List[object] = []
        for c in store.coords:
            if isinstance(c, FixedStoreCoord):
                coords.append(
                    _FixedCoord(
                        name=c.name,
                        feature_shard=c.feature_shard,
                        weights=jnp.asarray(np.asarray(c.weights)),
                    )
                )
            elif isinstance(c, RandomStoreCoord):
                coords.append(
                    _RandomCoord(
                        name=c.name,
                        feature_shard=c.feature_shard,
                        random_effect_type=c.random_effect_type,
                        coef_indices=jnp.asarray(np.asarray(c.coef_indices)),
                        coef_values=jnp.asarray(np.asarray(c.coef_values)),
                        rows_for=c.rows_for,
                    )
                )
            else:
                raise TypeError(f"unknown store coordinate type: {type(c)}")
        return cls(coords, store.task, dtype=dtype)

    # -- introspection -------------------------------------------------------

    @property
    def random_effect_types(self) -> List[str]:
        return [
            c.random_effect_type
            for c in self._coords
            if isinstance(c, _RandomCoord)
        ]

    @property
    def feature_shards(self) -> List[str]:
        seen: Dict[str, None] = {}
        for c in self._coords:
            seen.setdefault(c.feature_shard, None)
        return list(seen)

    # -- the shared assembly -------------------------------------------------

    def score_ell(
        self,
        offsets: np.ndarray,
        shard_ell: Mapping[str, Tuple[np.ndarray, np.ndarray]],
        entity_rows: Mapping[str, np.ndarray],
    ) -> np.ndarray:
        """Sum per-coordinate margins over rows already in ELL layout.

        ``shard_ell`` maps feature shard -> (idx i32[n, F], val f[n, F]) with
        idx=0/val=0 padding; ``entity_rows`` maps random-effect coordinate
        name -> i32[n] entity rows (-1 = unseen -> contributes 0). Scores
        accumulate in float64 on the host, one counted fetch per coordinate.
        """
        total = np.array(offsets, dtype=np.float64)
        for c in self._coords:
            idx, val = shard_ell[c.feature_shard]
            fidx = jnp.asarray(idx)
            fval = jnp.asarray(val, self.dtype)
            if isinstance(c, _FixedCoord):
                margin = _fe_score_ell(c.weights, fidx, fval)
            else:
                margin = score_entity_ell(
                    c.coef_indices,
                    c.coef_values,
                    jnp.asarray(entity_rows[c.name]),
                    fidx,
                    fval,
                )
            total += np.array(
                logged_fetch(f"serving.score.{c.name}", margin), dtype=np.float64
            )
        return total

    # -- batch path (cli.score / GameTransformer) ----------------------------

    def score_dataset(self, raw) -> np.ndarray:
        """Score a RawDataset: the batch-mode entry (GameScoringDriver role).
        Shapes follow the dataset (one compile per dataset shape — batch jobs
        are one-shot); the kernels are the same ones the request path keeps
        warm."""
        from ..game.data import _rows_to_ell

        shard_ell: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for shard in self.feature_shards:
            rows, cols, vals = raw.shard_coo[shard]
            shard_ell[shard] = _rows_to_ell(rows, cols, vals, raw.n_rows)
        entity_rows: Dict[str, np.ndarray] = {}
        for c in self._coords:
            if isinstance(c, _RandomCoord):
                ids = raw.id_tags[c.random_effect_type]
                entity_rows[c.name] = c.rows_for(ids).astype(np.int32)
        return self.score_ell(raw.offsets, shard_ell, entity_rows)

    # -- resident request path ----------------------------------------------

    def score_requests(
        self, requests: Sequence[ScoreRequest], count_cold: bool = True
    ) -> np.ndarray:
        """Score a microbatch of requests through the warm ladder-padded
        kernels; unseen entities fall back to the fixed effect and count in
        ``photon_serving_cold_start_total{coordinate=}`` (``count_cold=False``
        for synthetic warmup traffic that must not pollute the metric)."""
        n = len(requests)
        if n == 0:
            return np.zeros(0, dtype=np.float64)
        top = LADDER_ROWS[-1]
        if n > top:
            return np.concatenate(
                [
                    self.score_requests(requests[i : i + top], count_cold)
                    for i in range(0, n, top)
                ]
            )
        pad_n = _ladder_rows(n)

        shard_ell: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
        for shard in self.feature_shards:
            feats = [r.features.get(shard, ((), ())) for r in requests]
            width = _ladder_width(max((len(f[0]) for f in feats), default=1))
            idx = np.zeros((pad_n, width), dtype=np.int32)
            val = np.zeros((pad_n, width), dtype=np.float64)
            for i, (fi, fv) in enumerate(feats):
                k = len(fi)
                if k > width:  # defense in depth; _ladder_width refused above
                    raise ValueError(
                        f"request feature width {k} exceeds the serving "
                        "engine's padded feature-width ladder"
                    )
                idx[i, :k] = fi
                val[i, :k] = fv
            shard_ell[shard] = (idx, val)

        entity_rows: Dict[str, np.ndarray] = {}
        cold = obs.current_run().registry.counter(
            "photon_serving_cold_start_total",
            "requests scored fixed-effect-only because the entity was unseen",
        )
        for c in self._coords:
            if not isinstance(c, _RandomCoord):
                continue
            ids = [r.ids.get(c.random_effect_type) for r in requests]
            rows = c.rows_for(ids)
            n_cold = int(np.count_nonzero(rows < 0))
            if n_cold and count_cold:
                cold.labels(coordinate=c.name).inc(n_cold)
            erow = np.full(pad_n, -1, dtype=np.int32)
            erow[:n] = rows.astype(np.int32)
            entity_rows[c.name] = erow

        offsets = np.zeros(pad_n, dtype=np.float64)
        offsets[:n] = [r.offset for r in requests]
        return self.score_ell(offsets, shard_ell, entity_rows)[:n]

    def warm(self) -> None:
        """Compile the ladder's smallest shapes ahead of traffic (the rest
        fill in from the persistent compile cache or on first use)."""
        req = ScoreRequest(
            features={s: ((0,), (0.0,)) for s in self.feature_shards}
        )
        self.score_requests([req], count_cold=False)
