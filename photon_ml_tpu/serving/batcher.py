"""Request microbatching + deadline-budget admission control for the
resident scorer.

Concurrent callers submit single requests; one worker thread drains them
into batches under a max-latency / max-batch policy (the serving analogue of
Spark's partition batching): the first request in a batch waits at most
``max_latency_ms``, and a batch closes early at ``max_batch`` rows. Each
batch is scored by ONE engine reference captured at drain time — the
atomicity unit of a zero-downtime model flip: a refresh swaps the engine
*between* batches, so no batch can mix coefficients from two snapshots.

Past the saturation knee an unbounded queue converts overload into unbounded
tail latency for *everyone*; this batcher refuses instead of queueing:

- the pending queue is bounded (``max_pending``); a submit against a full
  queue is shed with reason ``queue_full``;
- each request may carry a deadline budget. Admission estimates the queue's
  drain time from a live service-rate EWMA (batch wall / batch rows, updated
  after every scored batch) and sheds immediately — reason ``deadline`` —
  when the request could not be scored inside its budget anyway;
- requests whose deadline expires *while queued* (the estimate is an
  estimate) are shed at drain time with reason ``expired``, before the
  engine ever sees them — never scored late, never silently dropped.

Every shed is a typed :class:`ShedError` (callers and the socket front can
tell refusal from failure) and a counted refusal in
``photon_serving_shed_total{model=,reason=}``; offered load lands in
``photon_serving_offered_total{model=}`` whether admitted or not, so
offered-vs-served-vs-shed rates are all derivable from one scrape.

A batcher is also the per-model **bulkhead** of the multi-model fleet
(``serving.fleet``): each resident model owns one batcher — its own worker
thread, pending bound, deadline budget, and service-rate EWMA — and every
serving metric this module records carries the batcher's ``model=`` label,
so a delay storm on one model sheds (and counts) against that model alone.
The chaos site follows the same keying: ``serving.score`` fires for every
batch on every model, and the dynamic per-model spelling
``serving.score.<model>`` lets a ``PHOTON_FAULTS`` storm target exactly one
model's batches (the isolation drill in ``tests/test_serving_fleet.py``).

Every completed request lands in the obs layer:
``photon_serving_request_latency_seconds`` (histogram, enqueue->result),
``photon_serving_batch_size`` (histogram), ``photon_serving_requests_total``
and ``photon_serving_request_errors_total`` (counters), plus live
``photon_serving_queue_depth`` / ``photon_serving_drain_estimate_seconds``
gauges for the admission queue. The Prometheus exposition renders
p50/p95/p99 gauges for every histogram family.
"""

from __future__ import annotations

import dataclasses
import logging
import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

from .. import obs
from ..robust import faults
from .engine import ScoreEngine, ScoreRequest

logger = logging.getLogger("photon_ml_tpu")

# Serving latencies are sub-millisecond to tens of ms — the seconds-scale
# DEFAULT_BUCKETS would put every observation in the first bucket and make
# the quantile estimates useless.
SERVING_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 1.0, 5.0,
)

_SHED_HELP = "requests refused by admission control instead of queued to death"
_OFFERED_HELP = "requests offered to the batcher (admitted + shed)"


@dataclasses.dataclass
class RequestTrace:
    """Per-request trace context threaded through the batcher: the request's
    ``trace_id`` (assigned at socket accept, echoed on every response) and
    the root span the per-stage spans (``serving.admit`` /
    ``serving.batch`` / ``serving.score``) parent under. Free when no sink
    is listening — stage spans are only built for traced requests on an
    active run."""

    trace_id: str
    parent: Optional[obs.Span] = None


def _stage_span(
    trace: Optional[RequestTrace],
    name: str,
    start_perf: float,
    end_perf: float,
    **attrs,
) -> None:
    """Emit one per-stage span for a traced request; no-op untraced/passive."""
    if trace is None or not obs.active():
        return
    obs.record_span(
        name,
        start_perf,
        end_perf,
        parent=trace.parent,
        trace_id=trace.trace_id,
        **attrs,
    )


class ShedError(RuntimeError):
    """A request refused by admission control (reason: ``queue_full`` — the
    bounded pending queue was full; ``deadline`` — the drain-time estimate
    said the deadline budget could not be met; ``expired`` — the deadline
    passed while the request waited in the queue). A shed is a *refusal
    with a response*, distinct from an engine failure."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class MicroBatcher:
    """Queue + worker thread turning concurrent requests into engine calls,
    fronted by deadline-budget admission control (see module docstring)."""

    def __init__(
        self,
        engine_fn: Callable[[], ScoreEngine],
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
        max_pending: int = 1024,
        ewma_alpha: float = 0.2,
        slow_request_ms: Optional[float] = None,
        model: str = "default",
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self._engine_fn = engine_fn
        # bulkhead identity: the model= label on every metric below, and the
        # per-model chaos-site suffix (serving.score.<model>)
        self.model = str(model)
        self._model_site = f"serving.score.{self.model}"
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_ms) / 1e3
        self.max_pending = int(max_pending)
        self._ewma_alpha = float(ewma_alpha)
        # slow-request threshold (enqueue->scored); None disables the log
        # line + photon_serving_slow_requests_total counting
        self.slow_request_s = (
            None if slow_request_ms is None else float(slow_request_ms) / 1e3
        )
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._closed = threading.Event()
        # one lock guards the admission state: pending count + service EWMA
        self._lock = threading.Lock()
        self._pending = 0
        self._ewma_per_req: Optional[float] = None
        self._worker = threading.Thread(
            target=self._run,
            name=f"photon-serving-batcher-{self.model}",
            daemon=True,
        )
        self._worker.start()

    # -- admission state ------------------------------------------------------

    def queue_stats(self) -> dict:
        """Live admission-queue view: pending requests, the service-rate
        EWMA (seconds per request), and the drain-time estimate a request
        admitted right now would wait behind."""
        with self._lock:
            pending, ewma = self._pending, self._ewma_per_req
        return {
            "pending": pending,
            "ewma_service_seconds": ewma,
            "drain_estimate_seconds": pending * ewma if ewma else 0.0,
        }

    def _publish_queue_gauges(self, reg) -> None:
        stats = self.queue_stats()
        reg.gauge(
            "photon_serving_queue_depth", "admission queue: pending requests"
        ).labels(model=self.model).set(stats["pending"])
        reg.gauge(
            "photon_serving_drain_estimate_seconds",
            "admission queue: estimated drain time from the service-rate EWMA",
        ).labels(model=self.model).set(stats["drain_estimate_seconds"])

    def _dec_pending(self, n: int) -> None:
        with self._lock:
            self._pending -= n

    # -- client side ---------------------------------------------------------

    def submit(
        self,
        request: ScoreRequest,
        deadline_s: Optional[float] = None,
        trace: Optional[RequestTrace] = None,
    ) -> Future:
        """Enqueue one request; the Future resolves to its float64 score.

        ``deadline_s`` is the request's latency budget in seconds from now.
        A request that the admission controller predicts cannot meet its
        budget (or that meets a full queue) raises :class:`ShedError`
        immediately; one whose deadline expires while queued gets the same
        error through its Future. ``trace`` (socket front) threads the
        request's trace_id through every stage: the admission decision,
        the queue wait, and the scored batch each land as a span parented
        under the request."""
        if self._closed.is_set():
            raise RuntimeError("MicroBatcher is closed")
        # photon: ignore[R7] — cross-thread enqueue stamp: the matching read
        # happens on the worker thread, so a span cannot bracket it
        now = time.perf_counter()
        deadline = None if deadline_s is None else now + float(deadline_s)
        reason = msg = None
        with self._lock:
            if self._pending >= self.max_pending:
                reason, msg = "queue_full", (
                    f"admission queue full ({self._pending} pending >= "
                    f"max_pending={self.max_pending})"
                )
            elif deadline is not None:
                # the new request drains behind everything pending plus its
                # own service time; no EWMA yet (cold server) admits
                drain = (self._pending + 1) * (self._ewma_per_req or 0.0)
                if now + drain > deadline:
                    reason, msg = "deadline", (
                        f"cannot meet deadline budget {deadline_s * 1e3:.1f}ms: "
                        f"estimated drain {drain * 1e3:.1f}ms behind "
                        f"{self._pending} pending requests"
                    )
            if reason is None:
                self._pending += 1
        reg = obs.current_run().registry
        reg.counter("photon_serving_offered_total", _OFFERED_HELP).labels(
            model=self.model
        ).inc()
        # photon: ignore[R7] — closes the admission-stage interval opened by
        # the enqueue stamp; lands on the span timeline via record_span (the
        # decision spans the lock, so no context manager can bracket it)
        admitted = time.perf_counter()
        _stage_span(
            trace, "serving.admit", now, admitted,
            outcome=reason or "admitted",
        )
        if reason is not None:
            reg.counter("photon_serving_shed_total", _SHED_HELP).labels(
                model=self.model, reason=reason
            ).inc()
            self._publish_queue_gauges(reg)
            raise ShedError(reason, msg)
        fut: Future = Future()
        self._q.put((request, now, deadline, fut, trace))
        self._publish_queue_gauges(reg)
        return fut

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        self._worker.join(timeout=timeout)

    # -- worker side ---------------------------------------------------------

    def _drain_batch(self) -> List[tuple]:
        """Block for a first request, then fill until max_batch or the first
        request's latency budget is spent."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        deadline = first[1] + self.max_latency_s
        while len(batch) < self.max_batch:
            # photon: ignore[R7] — deadline arithmetic against the enqueue
            # stamp, not a measured section
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        while not (self._closed.is_set() and self._q.empty()):
            batch = self._drain_batch()
            if not batch:
                continue
            reg = obs.current_run().registry
            # deadline check at the last moment before scoring: requests that
            # expired while queued are shed — a counted, typed response,
            # never a silent drop and never a wasted engine slot
            # photon: ignore[R7] — expiry check against the enqueue stamps
            now = time.perf_counter()
            live, expired = [], []
            for item in batch:
                _, t0, deadline, _, _ = item
                (expired if deadline is not None and now > deadline else live).append(item)
            if expired:
                reg.counter("photon_serving_shed_total", _SHED_HELP).labels(
                    model=self.model, reason="expired"
                ).inc(len(expired))
                for _, t0, _, fut, trace in expired:
                    _stage_span(
                        trace, "serving.batch", t0, now, outcome="expired"
                    )
                    fut.set_exception(
                        ShedError(
                            "expired",
                            f"deadline expired after {(now - t0) * 1e3:.1f}ms in queue",
                        )
                    )
                self._dec_pending(len(expired))
            if not live:
                self._publish_queue_gauges(reg)
                continue
            # ONE engine per batch: the flip atomicity unit (see module doc)
            engine = self._engine_fn()
            try:
                # the slow-engine chaos site: PHOTON_FAULTS
                # serving.score:delay50:... stalls here (exactly what a
                # degraded accelerator does), serving.score:io:... raises
                # into the counted error path below. The second, per-model
                # spelling keys a storm to ONE bulkhead: a
                # serving.score.<model>:delay spec stalls only that model's
                # batches — every other model's worker sails past it
                faults.check("serving.score")
                faults.check(self._model_site)
                # photon: ignore[R7] — service-rate sample for the admission
                # EWMA; paired read below, crosses the engine call
                t_score = time.perf_counter()
                scores = engine.score_requests([b[0] for b in live])
            except Exception as exc:
                # the error propagates to every caller through its Future —
                # counted, not swallowed
                errors = reg.counter(
                    "photon_serving_request_errors_total",
                    "requests failed inside the score engine",
                ).labels(model=self.model)
                errors.inc(len(live))
                for _, t0, _, fut, trace in live:
                    _stage_span(
                        trace, "serving.batch", t0, now, outcome="error"
                    )
                    fut.set_exception(exc)
                self._dec_pending(len(live))
                self._publish_queue_gauges(reg)
                continue
            # photon: ignore[R7] — closes the cross-thread latency interval
            # opened at submit(); feeds the latency histogram directly
            done = time.perf_counter()
            per_req = (done - t_score) / len(live)
            with self._lock:
                self._ewma_per_req = (
                    per_req
                    if self._ewma_per_req is None
                    else self._ewma_alpha * per_req
                    + (1.0 - self._ewma_alpha) * self._ewma_per_req
                )
            lat = reg.histogram(
                "photon_serving_request_latency_seconds",
                "request latency, enqueue to scored",
                buckets=SERVING_LATENCY_BUCKETS,
            ).labels(model=self.model)
            n_slow = 0
            for i, (_, t0, _, fut, trace) in enumerate(live):
                fut.set_result(float(scores[i]))
                total_s = done - t0
                lat.observe(total_s)
                # per-stage spans for traced requests: queue wait + batch
                # formation (enqueue -> engine start), then the scored batch
                _stage_span(
                    trace, "serving.batch", t0, t_score, outcome="scored"
                )
                _stage_span(
                    trace, "serving.score", t_score, done, batch_size=len(live)
                )
                if (
                    self.slow_request_s is not None
                    and total_s > self.slow_request_s
                ):
                    n_slow += 1
                    logger.warning(
                        "slow request%s: %.1fms total "
                        "(queue+batch %.1fms, score %.1fms, batch=%d)",
                        f" trace_id={trace.trace_id}" if trace else "",
                        total_s * 1e3,
                        (t_score - t0) * 1e3,
                        (done - t_score) * 1e3,
                        len(live),
                    )
            if n_slow:
                reg.counter(
                    "photon_serving_slow_requests_total",
                    "completed requests slower than the slow-request threshold",
                ).labels(model=self.model).inc(n_slow)
            self._dec_pending(len(live))
            reg.counter(
                "photon_serving_requests_total", "requests scored"
            ).labels(model=self.model).inc(len(live))
            reg.histogram(
                "photon_serving_batch_size",
                "rows per scored microbatch",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
            ).labels(model=self.model).observe(len(live))
            self._publish_queue_gauges(reg)
