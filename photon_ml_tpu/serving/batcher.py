"""Request microbatching for the resident scorer.

Concurrent callers submit single requests; one worker thread drains them
into batches under a max-latency / max-batch policy (the serving analogue of
Spark's partition batching): the first request in a batch waits at most
``max_latency_ms``, and a batch closes early at ``max_batch`` rows. Each
batch is scored by ONE engine reference captured at drain time — the
atomicity unit of a zero-downtime model flip: a refresh swaps the engine
*between* batches, so no batch can mix coefficients from two snapshots.

Every completed request lands in the obs layer:
``photon_serving_request_latency_seconds`` (histogram, enqueue->result),
``photon_serving_batch_size`` (histogram), ``photon_serving_requests_total``
and ``photon_serving_request_errors_total`` (counters). The Prometheus
exposition renders p50/p95/p99 gauges for every histogram family.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

from .. import obs
from .engine import ScoreEngine, ScoreRequest

# Serving latencies are sub-millisecond to tens of ms — the seconds-scale
# DEFAULT_BUCKETS would put every observation in the first bucket and make
# the quantile estimates useless.
SERVING_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 1.0, 5.0,
)


class MicroBatcher:
    """Queue + worker thread turning concurrent requests into engine calls."""

    def __init__(
        self,
        engine_fn: Callable[[], ScoreEngine],
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._engine_fn = engine_fn
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_ms) / 1e3
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._closed = threading.Event()
        self._worker = threading.Thread(
            target=self._run, name="photon-serving-batcher", daemon=True
        )
        self._worker.start()

    # -- client side ---------------------------------------------------------

    def submit(self, request: ScoreRequest) -> Future:
        """Enqueue one request; the Future resolves to its float64 score."""
        if self._closed.is_set():
            raise RuntimeError("MicroBatcher is closed")
        fut: Future = Future()
        # photon: ignore[R7] — cross-thread enqueue stamp: the matching read
        # happens on the worker thread, so a span cannot bracket it
        self._q.put((request, time.perf_counter(), fut))
        return fut

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        self._worker.join(timeout=timeout)

    # -- worker side ---------------------------------------------------------

    def _drain_batch(self) -> List[tuple]:
        """Block for a first request, then fill until max_batch or the first
        request's latency budget is spent."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        deadline = first[1] + self.max_latency_s
        while len(batch) < self.max_batch:
            # photon: ignore[R7] — deadline arithmetic against the enqueue
            # stamp, not a measured section
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        while not (self._closed.is_set() and self._q.empty()):
            batch = self._drain_batch()
            if not batch:
                continue
            # ONE engine per batch: the flip atomicity unit (see module doc)
            engine = self._engine_fn()
            reg = obs.current_run().registry
            try:
                scores = engine.score_requests([b[0] for b in batch])
            except Exception as exc:
                # the error propagates to every caller through its Future —
                # counted, not swallowed
                errors = reg.counter(
                    "photon_serving_request_errors_total",
                    "requests failed inside the score engine",
                )
                errors.inc(len(batch))
                for _, _, fut in batch:
                    fut.set_exception(exc)
                continue
            # photon: ignore[R7] — closes the cross-thread latency interval
            # opened at submit(); feeds the latency histogram directly
            done = time.perf_counter()
            lat = reg.histogram(
                "photon_serving_request_latency_seconds",
                "request latency, enqueue to scored",
                buckets=SERVING_LATENCY_BUCKETS,
            )
            for i, (_, t0, fut) in enumerate(batch):
                fut.set_result(float(scores[i]))
                lat.observe(done - t0)
            reg.counter(
                "photon_serving_requests_total", "requests scored"
            ).inc(len(batch))
            reg.histogram(
                "photon_serving_batch_size",
                "rows per scored microbatch",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
            ).observe(len(batch))
