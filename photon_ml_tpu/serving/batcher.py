"""Request microbatching + deadline-budget admission control for the
resident scorer.

Concurrent callers submit single requests; one worker thread drains them
into batches under a max-latency / max-batch policy (the serving analogue of
Spark's partition batching): the first request in a batch waits at most
``max_latency_ms``, and a batch closes early at ``max_batch`` rows. Each
batch is scored by ONE engine reference captured at drain time — the
atomicity unit of a zero-downtime model flip: a refresh swaps the engine
*between* batches, so no batch can mix coefficients from two snapshots.

Past the saturation knee an unbounded queue converts overload into unbounded
tail latency for *everyone*; this batcher refuses instead of queueing:

- the pending queue is bounded (``max_pending``); a submit against a full
  queue is shed with reason ``queue_full``;
- each request may carry a deadline budget. Admission estimates the queue's
  drain time from a live service-rate EWMA (batch wall / batch rows, updated
  after every scored batch) and sheds immediately — reason ``deadline`` —
  when the request could not be scored inside its budget anyway;
- requests whose deadline expires *while queued* (the estimate is an
  estimate) are shed at drain time with reason ``expired``, before the
  engine ever sees them — never scored late, never silently dropped.

Every shed is a typed :class:`ShedError` (callers and the socket front can
tell refusal from failure) and a counted refusal in
``photon_serving_shed_total{reason=}``; offered load lands in
``photon_serving_offered_total`` whether admitted or not, so
offered-vs-served-vs-shed rates are all derivable from one scrape.

Every completed request lands in the obs layer:
``photon_serving_request_latency_seconds`` (histogram, enqueue->result),
``photon_serving_batch_size`` (histogram), ``photon_serving_requests_total``
and ``photon_serving_request_errors_total`` (counters), plus live
``photon_serving_queue_depth`` / ``photon_serving_drain_estimate_seconds``
gauges for the admission queue. The Prometheus exposition renders
p50/p95/p99 gauges for every histogram family.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Tuple

from .. import obs
from ..robust import faults
from .engine import ScoreEngine, ScoreRequest

# Serving latencies are sub-millisecond to tens of ms — the seconds-scale
# DEFAULT_BUCKETS would put every observation in the first bucket and make
# the quantile estimates useless.
SERVING_LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 1.0, 5.0,
)

_SHED_HELP = "requests refused by admission control instead of queued to death"
_OFFERED_HELP = "requests offered to the batcher (admitted + shed)"


class ShedError(RuntimeError):
    """A request refused by admission control (reason: ``queue_full`` — the
    bounded pending queue was full; ``deadline`` — the drain-time estimate
    said the deadline budget could not be met; ``expired`` — the deadline
    passed while the request waited in the queue). A shed is a *refusal
    with a response*, distinct from an engine failure."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class MicroBatcher:
    """Queue + worker thread turning concurrent requests into engine calls,
    fronted by deadline-budget admission control (see module docstring)."""

    def __init__(
        self,
        engine_fn: Callable[[], ScoreEngine],
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
        max_pending: int = 1024,
        ewma_alpha: float = 0.2,
    ):
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        if max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        self._engine_fn = engine_fn
        self.max_batch = int(max_batch)
        self.max_latency_s = float(max_latency_ms) / 1e3
        self.max_pending = int(max_pending)
        self._ewma_alpha = float(ewma_alpha)
        self._q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._closed = threading.Event()
        # one lock guards the admission state: pending count + service EWMA
        self._lock = threading.Lock()
        self._pending = 0
        self._ewma_per_req: Optional[float] = None
        self._worker = threading.Thread(
            target=self._run, name="photon-serving-batcher", daemon=True
        )
        self._worker.start()

    # -- admission state ------------------------------------------------------

    def queue_stats(self) -> dict:
        """Live admission-queue view: pending requests, the service-rate
        EWMA (seconds per request), and the drain-time estimate a request
        admitted right now would wait behind."""
        with self._lock:
            pending, ewma = self._pending, self._ewma_per_req
        return {
            "pending": pending,
            "ewma_service_seconds": ewma,
            "drain_estimate_seconds": pending * ewma if ewma else 0.0,
        }

    def _publish_queue_gauges(self, reg) -> None:
        stats = self.queue_stats()
        reg.gauge(
            "photon_serving_queue_depth", "admission queue: pending requests"
        ).set(stats["pending"])
        reg.gauge(
            "photon_serving_drain_estimate_seconds",
            "admission queue: estimated drain time from the service-rate EWMA",
        ).set(stats["drain_estimate_seconds"])

    def _dec_pending(self, n: int) -> None:
        with self._lock:
            self._pending -= n

    # -- client side ---------------------------------------------------------

    def submit(self, request: ScoreRequest, deadline_s: Optional[float] = None) -> Future:
        """Enqueue one request; the Future resolves to its float64 score.

        ``deadline_s`` is the request's latency budget in seconds from now.
        A request that the admission controller predicts cannot meet its
        budget (or that meets a full queue) raises :class:`ShedError`
        immediately; one whose deadline expires while queued gets the same
        error through its Future."""
        if self._closed.is_set():
            raise RuntimeError("MicroBatcher is closed")
        # photon: ignore[R7] — cross-thread enqueue stamp: the matching read
        # happens on the worker thread, so a span cannot bracket it
        now = time.perf_counter()
        deadline = None if deadline_s is None else now + float(deadline_s)
        reason = msg = None
        with self._lock:
            if self._pending >= self.max_pending:
                reason, msg = "queue_full", (
                    f"admission queue full ({self._pending} pending >= "
                    f"max_pending={self.max_pending})"
                )
            elif deadline is not None:
                # the new request drains behind everything pending plus its
                # own service time; no EWMA yet (cold server) admits
                drain = (self._pending + 1) * (self._ewma_per_req or 0.0)
                if now + drain > deadline:
                    reason, msg = "deadline", (
                        f"cannot meet deadline budget {deadline_s * 1e3:.1f}ms: "
                        f"estimated drain {drain * 1e3:.1f}ms behind "
                        f"{self._pending} pending requests"
                    )
            if reason is None:
                self._pending += 1
        reg = obs.current_run().registry
        reg.counter("photon_serving_offered_total", _OFFERED_HELP).inc()
        if reason is not None:
            reg.counter("photon_serving_shed_total", _SHED_HELP).labels(
                reason=reason
            ).inc()
            self._publish_queue_gauges(reg)
            raise ShedError(reason, msg)
        fut: Future = Future()
        self._q.put((request, now, deadline, fut))
        self._publish_queue_gauges(reg)
        return fut

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        self._worker.join(timeout=timeout)

    # -- worker side ---------------------------------------------------------

    def _drain_batch(self) -> List[tuple]:
        """Block for a first request, then fill until max_batch or the first
        request's latency budget is spent."""
        try:
            first = self._q.get(timeout=0.05)
        except queue.Empty:
            return []
        batch = [first]
        deadline = first[1] + self.max_latency_s
        while len(batch) < self.max_batch:
            # photon: ignore[R7] — deadline arithmetic against the enqueue
            # stamp, not a measured section
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _run(self) -> None:
        while not (self._closed.is_set() and self._q.empty()):
            batch = self._drain_batch()
            if not batch:
                continue
            reg = obs.current_run().registry
            # deadline check at the last moment before scoring: requests that
            # expired while queued are shed — a counted, typed response,
            # never a silent drop and never a wasted engine slot
            # photon: ignore[R7] — expiry check against the enqueue stamps
            now = time.perf_counter()
            live, expired = [], []
            for item in batch:
                _, t0, deadline, _ = item
                (expired if deadline is not None and now > deadline else live).append(item)
            if expired:
                reg.counter("photon_serving_shed_total", _SHED_HELP).labels(
                    reason="expired"
                ).inc(len(expired))
                for _, t0, _, fut in expired:
                    fut.set_exception(
                        ShedError(
                            "expired",
                            f"deadline expired after {(now - t0) * 1e3:.1f}ms in queue",
                        )
                    )
                self._dec_pending(len(expired))
            if not live:
                self._publish_queue_gauges(reg)
                continue
            # ONE engine per batch: the flip atomicity unit (see module doc)
            engine = self._engine_fn()
            try:
                # the slow-engine chaos site: PHOTON_FAULTS
                # serving.score:delay50:... stalls here (exactly what a
                # degraded accelerator does), serving.score:io:... raises
                # into the counted error path below
                faults.check("serving.score")
                # photon: ignore[R7] — service-rate sample for the admission
                # EWMA; paired read below, crosses the engine call
                t_score = time.perf_counter()
                scores = engine.score_requests([b[0] for b in live])
            except Exception as exc:
                # the error propagates to every caller through its Future —
                # counted, not swallowed
                errors = reg.counter(
                    "photon_serving_request_errors_total",
                    "requests failed inside the score engine",
                )
                errors.inc(len(live))
                for _, _, _, fut in live:
                    fut.set_exception(exc)
                self._dec_pending(len(live))
                self._publish_queue_gauges(reg)
                continue
            # photon: ignore[R7] — closes the cross-thread latency interval
            # opened at submit(); feeds the latency histogram directly
            done = time.perf_counter()
            per_req = (done - t_score) / len(live)
            with self._lock:
                self._ewma_per_req = (
                    per_req
                    if self._ewma_per_req is None
                    else self._ewma_alpha * per_req
                    + (1.0 - self._ewma_alpha) * self._ewma_per_req
                )
            lat = reg.histogram(
                "photon_serving_request_latency_seconds",
                "request latency, enqueue to scored",
                buckets=SERVING_LATENCY_BUCKETS,
            )
            for i, (_, t0, _, fut) in enumerate(live):
                fut.set_result(float(scores[i]))
                lat.observe(done - t0)
            self._dec_pending(len(live))
            reg.counter(
                "photon_serving_requests_total", "requests scored"
            ).inc(len(live))
            reg.histogram(
                "photon_serving_batch_size",
                "rows per scored microbatch",
                buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
            ).observe(len(live))
            self._publish_queue_gauges(reg)
