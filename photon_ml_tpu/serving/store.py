"""Mmap-backed GAME model store for the resident scoring service.

The training-side persistence format (``io/model_io.py``) is the reference's
Avro layout: human-portable, but opening it means parsing every
``BayesianLinearModelAvro`` record — minutes and gigabytes of host heap at
production entity counts. The serving store is the *deployment* format: the
same model flattened once (at publish time) into raw binary coefficient
tables plus a key-sorted ``MmapIndexMap`` per random effect, so a server
start is **open-not-parse** — a handful of ``mmap`` calls whose host RSS is
independent of entity count (pages fault in through the OS page cache, the
PalDB role the reference gives its off-heap stores).

Layout of one store (= one published snapshot)::

    store_dir/
      store-meta.json            # written LAST: its presence certifies the store
      fe-<coord>.bin             # f[d] raw fixed-effect coefficient vector
      re-<coord>-indices.bin     # i32[E, S] per-entity sorted support (-1 pad)
      re-<coord>-values.bin      # f[E, S]  per-entity coefficients
      re-<coord>-entities.bin    # MmapIndexMap: entity id -> row in [E, S]

All files land atomically (``robust.atomic``) and the meta goes last, so a
crashed publish never leaves a store a server would half-open.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from ..analysis.runtime import logged_fetch
from ..io.index_map import MmapIndexMap
from ..robust.atomic import atomic_write, atomic_write_json
from ..robust.retry import io_call

STORE_META = "store-meta.json"
STORE_VERSION = 1


def _fe_path(store_dir: str, name: str) -> str:
    return os.path.join(store_dir, f"fe-{name}.bin")


def _re_path(store_dir: str, name: str, part: str) -> str:
    return os.path.join(store_dir, f"re-{name}-{part}.bin")


def build_store(
    model_dir: str,
    index_maps: Mapping[str, object],
    store_dir: str,
    task: Optional[str] = None,
) -> str:
    """One-time publish-side flatten: parse the Avro GAME model layout and
    write the mmap store. Startup cost moves here, off the serving path."""
    from ..io.model_io import load_game_model

    model = load_game_model(model_dir, index_maps, task=task)
    return build_store_from_model(model, store_dir)


def build_store_from_model(game_model, store_dir: str) -> str:
    """Write ``game_model`` as an mmap store under ``store_dir``."""
    from ..models.game import FixedEffectModel, RandomEffectModel

    os.makedirs(store_dir, exist_ok=True)
    coords: List[dict] = []
    for name, sub in game_model.models.items():
        if isinstance(sub, FixedEffectModel):
            w = np.ascontiguousarray(
                logged_fetch("serving.store_build", sub.model.coefficients.means)
            )
            io_call(_write_raw, _fe_path(store_dir, name), w, site="io.serving_store")
            coords.append(
                {
                    "name": name,
                    "kind": "fixed",
                    "shard": sub.feature_shard,
                    "dim": int(w.shape[0]),
                    "dtype": str(w.dtype),
                }
            )
        elif isinstance(sub, RandomEffectModel):
            idx = np.ascontiguousarray(
                logged_fetch("serving.store_build", sub.coef_indices), dtype=np.int32
            )
            val = np.ascontiguousarray(
                logged_fetch("serving.store_build", sub.coef_values)
            )
            io_call(
                _write_raw, _re_path(store_dir, name, "indices"), idx,
                site="io.serving_store",
            )
            io_call(
                _write_raw, _re_path(store_dir, name, "values"), val,
                site="io.serving_store",
            )
            MmapIndexMap.write(
                ((str(e), row) for row, e in enumerate(sub.entity_ids)),
                _re_path(store_dir, name, "entities"),
            )
            coords.append(
                {
                    "name": name,
                    "kind": "random",
                    "shard": sub.feature_shard,
                    "re_type": sub.random_effect_type,
                    "entities": int(idx.shape[0]),
                    "support": int(idx.shape[1]),
                    "dtype": str(val.dtype),
                }
            )
        else:
            raise TypeError(f"unknown sub-model type for {name}: {type(sub)}")
    # meta last: a store without it is an aborted publish, not a torn model
    io_call(
        atomic_write_json,
        os.path.join(store_dir, STORE_META),
        {"version": STORE_VERSION, "task": game_model.task, "coordinates": coords},
        indent=2,
        site="io.serving_store",
    )
    return store_dir


def _write_raw(path: str, arr: np.ndarray) -> None:
    with atomic_write(path, "wb") as f:
        f.write(arr.tobytes())


@dataclasses.dataclass(frozen=True)
class FixedStoreCoord:
    """One fixed-effect coordinate: a dense mmap'd coefficient vector."""

    name: str
    feature_shard: str
    weights: np.ndarray  # memmap f[d]


@dataclasses.dataclass(frozen=True)
class RandomStoreCoord:
    """One random-effect coordinate: mmap'd [E, S] coefficient tables plus a
    zero-heap entity-id -> row index (binary search over the mapped blob)."""

    name: str
    feature_shard: str
    random_effect_type: str
    coef_indices: np.ndarray  # memmap i32[E, S]
    coef_values: np.ndarray  # memmap f[E, S]
    entities: MmapIndexMap

    def rows_for(self, entity_ids: Sequence) -> np.ndarray:
        """Row per entity id, -1 for unseen (the cold-start signal)."""
        out = np.empty(len(entity_ids), dtype=np.int64)
        for i, e in enumerate(entity_ids):
            out[i] = -1 if e is None else self.entities.get_index(str(e))
        return out


class ModelStore:
    """An opened snapshot: coordinate tables as mmap views, in the model's
    coordinate order. Opening is O(#coordinates) syscalls — no parsing."""

    def __init__(self, store_dir: str, task: str, coords: List[object]):
        self.store_dir = store_dir
        self.task = task
        self.coords = coords

    @staticmethod
    def open(store_dir: str) -> "ModelStore":
        def _read_meta():
            with open(os.path.join(store_dir, STORE_META)) as f:
                return json.load(f)

        meta = io_call(_read_meta, site="io.serving_store")
        version = meta.get("version")
        if version != STORE_VERSION:
            raise ValueError(
                f"{store_dir}: unsupported serving store version {version!r} "
                f"(this build reads version {STORE_VERSION}; re-publish the "
                "snapshot with serving.store.build_store)"
            )
        def _open_tables() -> List[object]:
            # mmap establishment is idempotent, so the whole loop retries as
            # one io_call unit: a transient FS error on any artifact backs
            # off and re-opens instead of failing the snapshot outright
            coords: List[object] = []
            for c in meta["coordinates"]:
                dt = np.dtype(c["dtype"])
                if c["kind"] == "fixed":
                    coords.append(
                        FixedStoreCoord(
                            name=c["name"],
                            feature_shard=c["shard"],
                            weights=np.memmap(
                                _fe_path(store_dir, c["name"]), dtype=dt,
                                mode="r", shape=(c["dim"],),
                            ),
                        )
                    )
                else:
                    shape = (c["entities"], c["support"])
                    coords.append(
                        RandomStoreCoord(
                            name=c["name"],
                            feature_shard=c["shard"],
                            random_effect_type=c["re_type"],
                            coef_indices=np.memmap(
                                _re_path(store_dir, c["name"], "indices"),
                                dtype=np.int32, mode="r", shape=shape,
                            ),
                            coef_values=np.memmap(
                                _re_path(store_dir, c["name"], "values"),
                                dtype=dt, mode="r", shape=shape,
                            ),
                            entities=MmapIndexMap.open(
                                _re_path(store_dir, c["name"], "entities")
                            ),
                        )
                    )
            return coords

        return ModelStore(
            store_dir,
            meta["task"],
            io_call(_open_tables, site="io.serving_store"),
        )


def discover_shards(model_dir: str) -> List[str]:
    """Feature shards a GAME model directory references (from the id-info
    files) — what a server needs to load index maps without a training
    configuration in hand."""
    shards = set()
    for sub, line_of_shard in (("fixed-effect", 0), ("random-effect", 1)):
        base = os.path.join(model_dir, sub)
        if not os.path.isdir(base):
            continue
        for name in sorted(os.listdir(base)):
            info = os.path.join(base, name, "id-info")
            if not os.path.isfile(info):
                continue
            with open(info) as f:
                lines = [ln.strip() for ln in f.readlines()]
            if len(lines) > line_of_shard:
                shards.add(lines[line_of_shard])
    return sorted(shards)
