"""Resident GLMix scoring service (the GameScoringDriver product surface,
re-shaped for a long-lived TPU process).

Pieces, composable or standalone:

- ``store``   — mmap model store: open-not-parse startup, host RSS
  independent of entity count.
- ``engine``  — the one compiled score assembly, shared by batch scoring
  (``cli.score`` / ``GameTransformer``) and the resident request path.
- ``batcher`` — microbatching under a max-latency / max-batch policy.
- ``refresh`` — atomic snapshot publication + zero-downtime flips.
- ``server``  — the composed resident service (+ AF_UNIX JSON-lines front).
"""

from .batcher import SERVING_LATENCY_BUCKETS, MicroBatcher
from .engine import LADDER_ROWS, LADDER_WIDTH, ScoreEngine, ScoreRequest
from .refresh import (
    RefreshWatcher,
    current_snapshot,
    open_current,
    publish_snapshot,
    snapshot_path,
)
from .server import ScoringServer, serve_socket
from .store import (
    ModelStore,
    build_store,
    build_store_from_model,
    discover_shards,
)

__all__ = [
    "SERVING_LATENCY_BUCKETS",
    "MicroBatcher",
    "LADDER_ROWS",
    "LADDER_WIDTH",
    "ScoreEngine",
    "ScoreRequest",
    "RefreshWatcher",
    "current_snapshot",
    "open_current",
    "publish_snapshot",
    "snapshot_path",
    "ScoringServer",
    "serve_socket",
    "ModelStore",
    "build_store",
    "build_store_from_model",
    "discover_shards",
]
