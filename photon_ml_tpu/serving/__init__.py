"""Resident GLMix scoring service (the GameScoringDriver product surface,
re-shaped for a long-lived TPU process).

Pieces, composable or standalone:

- ``store``   — mmap model store: open-not-parse startup, host RSS
  independent of entity count.
- ``engine``  — the one compiled score assembly, shared by batch scoring
  (``cli.score`` / ``GameTransformer``) and the resident request path.
- ``batcher`` — microbatching under a max-latency / max-batch policy, with
  deadline-budget admission control (bounded queue, typed ``ShedError``
  refusals).
- ``refresh`` — atomic snapshot publication + zero-downtime flips.
- ``server``  — the composed resident service (+ AF_UNIX / TCP JSON-lines
  front).
- ``fleet``   — multi-model residency: N named snapshots in one process,
  each behind its own bulkhead (batcher + refresh watcher), routed by the
  request protocol's ``model=`` field.
- ``front``   — the least-loaded replica front: N ``cli serve`` replicas
  behind one address, health-checked via ``/healthz``, with idempotent
  trace_id resubmit when a replica dies mid-request.
- ``loadgen`` — open-loop Poisson load generation measuring latency from
  intended send time (the coordinated-omission-proof harness behind
  ``bench.py --config serving-openloop`` / ``serving-fleet``).
"""

from .batcher import SERVING_LATENCY_BUCKETS, MicroBatcher, ShedError
from .engine import LADDER_ROWS, LADDER_WIDTH, ScoreEngine, ScoreRequest
from .fleet import ModelSet, UnknownModelError, discover_fleet
from .front import LeastLoadedFront, serve_front_socket
from .loadgen import (
    OpenLoopResult,
    find_knee,
    poisson_intended_times,
    run_mixed_open_loop,
    run_open_loop,
    simulate_fifo_closed_loop,
    simulate_fifo_open_loop,
    sweep_open_loop,
)
from .refresh import (
    RefreshWatcher,
    current_snapshot,
    open_current,
    publish_snapshot,
    snapshot_path,
)
from .server import (
    MAX_REQUEST_LINE_BYTES,
    BadRequestError,
    ScoringServer,
    serve_socket,
)
from .store import (
    ModelStore,
    build_store,
    build_store_from_model,
    discover_shards,
)

__all__ = [
    "SERVING_LATENCY_BUCKETS",
    "MicroBatcher",
    "ShedError",
    "LADDER_ROWS",
    "LADDER_WIDTH",
    "ScoreEngine",
    "ScoreRequest",
    "ModelSet",
    "UnknownModelError",
    "discover_fleet",
    "LeastLoadedFront",
    "serve_front_socket",
    "OpenLoopResult",
    "find_knee",
    "poisson_intended_times",
    "run_mixed_open_loop",
    "run_open_loop",
    "simulate_fifo_closed_loop",
    "simulate_fifo_open_loop",
    "sweep_open_loop",
    "RefreshWatcher",
    "current_snapshot",
    "open_current",
    "publish_snapshot",
    "snapshot_path",
    "MAX_REQUEST_LINE_BYTES",
    "BadRequestError",
    "ScoringServer",
    "serve_socket",
    "ModelStore",
    "build_store",
    "build_store_from_model",
    "discover_shards",
]
