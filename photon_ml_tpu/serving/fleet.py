"""Multi-model residency: N named snapshots resident in one process, each
behind its own bulkhead.

The GLMix deployment story is many per-market / per-surface model variants
(the reference trains one GAME model set per market); one resident process
per variant wastes a warm accelerator, but naive co-residency couples their
failure domains. :class:`ModelSet` holds N named models over one store root
and isolates them three ways:

- **per-model bulkheads** — every model owns one ``MicroBatcher``: its own
  worker thread, pending bound, deadline-budget admission, and service-rate
  EWMA. A delay storm on one model stalls that model's worker only; its
  queue fills, its requests shed (typed, counted under its ``model=``
  label), and every other model's batches drain untouched.
- **staggered refresh** — every serving-root model owns one
  ``RefreshWatcher``, so snapshots flip independently: a torn publish on
  one model is swallowed (``serving.refresh``) and retried by *that*
  watcher while the other models keep flipping on their own schedules.
- **shared executables, not shared state** — the jitted score kernels take
  coefficient tables as arguments (``serving.engine``), so same-shape
  models share the warm padding-ladder executables; residency costs one
  mmap store + one device table set per model, zero extra compiles.

Model sources are heterogeneous: a serving root (CURRENT + snapshots/,
watched), a bare store directory or opened ``ModelStore`` (fixed), or a
built ``ScoreEngine``. ``discover_fleet`` maps a fleet root — one
directory with one serving root per model subdirectory — into the
``models=`` mapping ``cli serve --fleet-root`` serves.

Routing is by name: ``resolve(None)`` is the default model; an unknown (or
``warm_async=True`` still-warming) name raises :class:`UnknownModelError`,
which the socket layer answers as a typed ``bad_request``
kind=``unknown_model`` — never silently scored against the default.
Duplicate names are refused up front through the support-matrix ledger
(``plan.check_fleet_composition``).
"""

from __future__ import annotations

import functools
import os
import threading
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from .. import obs
from ..plan import check_fleet_composition
from .batcher import MicroBatcher, RequestTrace
from .engine import ScoreEngine, ScoreRequest
from .refresh import CURRENT_POINTER, RefreshWatcher, open_current
from .store import STORE_META, ModelStore

ModelSource = Union[str, ModelStore, ScoreEngine]


class UnknownModelError(LookupError):
    """A request named a model this fleet does not hold (or holds but has
    not finished warming). The socket layer maps it to a typed
    ``bad_request`` kind=``unknown_model`` response; in-process callers see
    this exception directly. ``model`` is the requested name."""

    kind = "unknown_model"

    def __init__(self, model: Optional[str], message: str):
        super().__init__(message)
        self.model = model


class _ModelEntry:
    """One resident model: source + engine + bulkhead + optional watcher."""

    def __init__(self, name: str):
        self.name = name
        self.serving_root: Optional[str] = None
        self.snapshot_name: Optional[str] = None
        self.engine: Optional[ScoreEngine] = None
        self.batcher: Optional[MicroBatcher] = None
        self.watcher: Optional[RefreshWatcher] = None
        self.ready = threading.Event()


def discover_fleet(fleet_root: str) -> Dict[str, str]:
    """Map a fleet root (one serving root, or bare store dir, per model
    subdirectory) to a sorted ``{model_name: path}`` mapping."""
    models: Dict[str, str] = {}
    for name in sorted(os.listdir(fleet_root)):
        path = os.path.join(fleet_root, name)
        if not os.path.isdir(path):
            continue
        if os.path.exists(os.path.join(path, CURRENT_POINTER)) or os.path.exists(
            os.path.join(path, STORE_META)
        ):
            models[name] = path
    if not models:
        raise FileNotFoundError(
            f"{fleet_root}: no model subdirectories (each model needs a "
            f"serving root with {CURRENT_POINTER}, or a bare store dir)"
        )
    return models


class ModelSet:
    """N named resident models over one store root, one bulkhead each.

    ``models`` maps name -> source (or is a sequence of (name, source)
    pairs — the order-preserving spelling ``--models`` uses, where a
    repeated name is refused through the support-matrix ledger). The first
    name is the default model unless ``default_model`` says otherwise.
    ``per_model`` optionally overrides the shared batcher settings for
    individual models (each bulkhead's admission budget is its own either
    way). ``warm_async=True`` builds + warms engines on background threads;
    until a model's ladder is warm it answers :class:`UnknownModelError`
    (the socket layer's ``unknown_model``) instead of serving cold.
    """

    def __init__(
        self,
        models: Union[Mapping[str, ModelSource], Sequence[Tuple[str, ModelSource]]],
        default_model: Optional[str] = None,
        max_batch: int = 256,
        max_latency_ms: float = 2.0,
        max_pending: int = 1024,
        slow_request_ms: Optional[float] = None,
        per_model: Optional[Mapping[str, Mapping]] = None,
        poll_seconds: float = 0.2,
        dtype=jnp.float32,
        warm_async: bool = False,
    ):
        pairs = (
            list(models.items())
            if isinstance(models, Mapping)
            else [(str(n), s) for n, s in models]
        )
        if not pairs:
            raise ValueError("ModelSet needs at least one model")
        check_fleet_composition([n for n, _ in pairs])
        if default_model is not None and default_model not in {n for n, _ in pairs}:
            raise ValueError(
                f"default model {default_model!r} is not in the fleet: "
                f"{sorted(n for n, _ in pairs)}"
            )
        self.default_model: str = default_model or pairs[0][0]
        self.dtype = dtype
        self.poll_seconds = float(poll_seconds)
        self._batcher_opts = dict(
            max_batch=max_batch,
            max_latency_ms=max_latency_ms,
            max_pending=max_pending,
            slow_request_ms=slow_request_ms,
        )
        # one lock for every entry's engine swap: flips are rare and the
        # critical section is one attribute assignment
        self._lock = threading.Lock()
        self._entries: Dict[str, _ModelEntry] = {}
        self._warm_threads: List[threading.Thread] = []
        try:
            for name, source in pairs:
                entry = _ModelEntry(name)
                opts = dict(self._batcher_opts)
                opts.update((per_model or {}).get(name, {}))
                entry.batcher = MicroBatcher(
                    functools.partial(self._entry_engine, entry),
                    model=name,
                    **opts,
                )
                self._entries[name] = entry
                if warm_async:
                    t = threading.Thread(
                        target=functools.partial(self._open_entry, entry, source),
                        name=f"photon-serving-warm-{name}",
                        daemon=True,
                    )
                    self._warm_threads.append(t)
                    t.start()
                else:
                    self._open_entry(entry, source)
        except BaseException:
            self.close()
            raise

    # -- construction / refresh flips ----------------------------------------

    def _open_entry(self, entry: _ModelEntry, source: ModelSource) -> None:
        """Open one model's source, build + warm its engine, and (for a
        serving root) start its own RefreshWatcher — the staggered-refresh
        unit: each watcher flips its model independently, so a torn publish
        on one model never stalls another's flip."""
        try:
            if isinstance(source, ModelStore):
                self._install(entry, None, source)
            elif not isinstance(source, (str, os.PathLike)):
                # a ready-made engine — duck-typed (anything with
                # score_requests; tests use jax-free fakes), warmed when it
                # knows how
                engine = source
                warm = getattr(engine, "warm", None)
                if warm is not None:
                    warm()
                with self._lock:
                    entry.engine = engine
            else:
                root = str(source)
                if os.path.exists(os.path.join(root, CURRENT_POINTER)):
                    entry.serving_root = root
                    snap, store = open_current(root)
                    self._install(entry, snap, store)
                    entry.watcher = RefreshWatcher(
                        root,
                        functools.partial(self._install, entry),
                        poll_seconds=self.poll_seconds,
                        live=snap,
                        model=entry.name,
                    )
                else:
                    self._install(entry, None, ModelStore.open(root))
        except Exception:
            # a model that failed to open must not take down its siblings
            # (the warm_async path runs on a background thread): it stays
            # not-ready — requests naming it get the typed unknown_model
            # refusal — and the failure is counted, never swallowed silently
            obs.swallowed_error("serving.fleet")
            return
        entry.ready.set()

    def _install(
        self, entry: _ModelEntry, snapshot: Optional[str], store: ModelStore
    ) -> None:
        """Build the engine for a freshly opened store, then flip ``entry``'s
        live reference in one assignment. Warm before the flip: a flip must
        not stall in-flight traffic on a compile (and same-shape models
        share the warm ladder executables, so warming the Nth model of a
        shape compiles nothing). Called at open time and from the entry's
        RefreshWatcher thread on every staggered flip."""
        live = entry.ready.is_set()
        if live:
            # /healthz answers 503 for exactly the mid-publish window, so a
            # load balancer (or the replica front) drains this replica while
            # the flip is in flight — scoring keeps working on the old
            # engine until the one-assignment swap below
            obs.current_run().status.update(refresh_in_progress=True)
        try:
            engine = ScoreEngine.from_store(store, dtype=self.dtype)
            engine.warm()
            with self._lock:
                entry.engine = engine
                entry.snapshot_name = snapshot
        finally:
            if live:
                obs.current_run().status.update(refresh_in_progress=False)
        self._publish_status()

    def _entry_engine(self, entry: _ModelEntry) -> ScoreEngine:
        with self._lock:
            return entry.engine

    def _publish_status(self) -> None:
        # serving_snapshot (singular) keeps the pre-fleet /statusz contract:
        # the default model's live snapshot; serving_snapshots is the
        # per-model breakdown the fleet statusz section renders
        default = self._entries.get(self.default_model)
        obs.current_run().status.update(
            serving_snapshot=None if default is None else default.snapshot_name,
            serving_snapshots={
                n: e.snapshot_name for n, e in self._entries.items()
            },
        )

    # -- routing surface ------------------------------------------------------

    @property
    def names(self) -> List[str]:
        return list(self._entries)

    @property
    def snapshot_names(self) -> Dict[str, Optional[str]]:
        return {n: e.snapshot_name for n, e in self._entries.items()}

    def resolve(self, model: Optional[str]) -> str:
        """The resolved model name for a requested one (None -> default);
        raises :class:`UnknownModelError` for names this fleet does not
        hold or has not finished warming."""
        name = self.default_model if model is None else str(model)
        entry = self._entries.get(name)
        if entry is None:
            raise UnknownModelError(
                model,
                f"unknown model {name!r}: this fleet holds "
                f"{sorted(self._entries)}",
            )
        if not entry.ready.is_set():
            raise UnknownModelError(
                model, f"model {name!r} is still warming; retry shortly"
            )
        return name

    def submit(
        self,
        request: ScoreRequest,
        deadline_s: Optional[float] = None,
        trace: Optional[RequestTrace] = None,
        model: Optional[str] = None,
    ):
        """Route one request to its model's bulkhead; returns the batcher's
        Future. ``model`` (explicit arg, else ``request.model``) picks the
        bulkhead; admission refusals raise the model's own ShedError."""
        name = self.resolve(model if model is not None else request.model)
        return self._entries[name].batcher.submit(
            request, deadline_s=deadline_s, trace=trace
        )

    def warm_wait(self, timeout: Optional[float] = None) -> bool:
        """Block until every model is ready (warm_async construction);
        returns False on timeout."""
        for t in self._warm_threads:
            t.join(timeout=timeout)
        return all(e.ready.is_set() for e in self._entries.values())

    def queue_stats(self, model: Optional[str] = None) -> dict:
        """Live admission-queue view: one model's (by name), or — with
        ``model=None`` on a multi-model set — the fleet aggregate (summed
        pending, max drain estimate: the worst bulkhead gates the fleet)."""
        if model is not None or len(self._entries) == 1:
            name = self.resolve(model)
            return self._entries[name].batcher.queue_stats()
        per = {
            n: e.batcher.queue_stats() for n, e in self._entries.items()
        }
        return {
            "pending": sum(s["pending"] for s in per.values()),
            "ewma_service_seconds": None,
            "drain_estimate_seconds": max(
                s["drain_estimate_seconds"] for s in per.values()
            ),
            "models": per,
        }

    def poke_refresh(self, model: Optional[str] = None) -> None:
        """Force an immediate CURRENT check on one model's watcher (by
        name) or all of them (tests; avoids poll sleeps)."""
        entries = (
            self._entries.values()
            if model is None
            else [self._entries[self.resolve(model)]]
        )
        for e in entries:
            if e.watcher is not None:
                e.watcher.poke()

    def close(self) -> None:
        for t in self._warm_threads:
            t.join(timeout=5.0)
        for e in self._entries.values():
            if e.watcher is not None:
                e.watcher.stop()
            if e.batcher is not None:
                e.batcher.close()
