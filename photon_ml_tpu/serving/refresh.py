"""Zero-downtime model refresh: atomic snapshot publication + a watcher
that flips the live store mid-traffic.

Publication layout (one serving root per deployed model)::

    serving_root/
      CURRENT                # text file: the live snapshot's name
      snapshots/<name>/      # one mmap store each (serving.store layout)

``publish_snapshot`` builds the store in a hidden temp directory, renames it
into ``snapshots/<name>`` (one atomic directory rename), then rewrites
``CURRENT`` through ``robust.atomic`` — the output-committer discipline: a
reader either sees the old pointer or the new one, never a half-built store.

``RefreshWatcher`` polls ``CURRENT``; on a change it opens the new store
*beside* the live one and hands it to the server, which swaps a single
engine reference between microbatches (see ``serving.batcher``) — requests
in flight finish on the old snapshot, the next batch scores on the new one,
and nothing ever blocks. That is the kill-and-keep-serving drill of ROADMAP
item 2, exercised end to end in ``tests/test_serving.py``.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Mapping, Optional

from .. import obs
from ..robust import faults
from ..robust.atomic import atomic_write_text
from ..robust.retry import io_call
from .store import ModelStore, build_store, build_store_from_model

CURRENT_POINTER = "CURRENT"
SNAPSHOT_DIR = "snapshots"


def snapshot_path(serving_root: str, name: str) -> str:
    return os.path.join(serving_root, SNAPSHOT_DIR, name)


def publish_snapshot(
    serving_root: str,
    name: str,
    game_model=None,
    model_dir: Optional[str] = None,
    index_maps: Optional[Mapping[str, object]] = None,
    task: Optional[str] = None,
    replace: bool = False,
) -> str:
    """Build ``name`` from either an in-memory GameModel or an Avro model
    directory, publish it atomically, and point ``CURRENT`` at it.

    ``replace=True`` is the torn-publish repair mode (the retrain chain's
    next cycle): a stale half-built ``.tmp-<name>`` from a crashed publish
    is discarded, and a ``name`` that already finished publishing is reused
    as-is — only ``CURRENT`` is re-pointed. Without it a completed snapshot
    name is refused (snapshots are immutable once published)."""
    if (game_model is None) == (model_dir is None):
        raise ValueError("pass exactly one of game_model / model_dir")
    final = snapshot_path(serving_root, name)
    tmp = os.path.join(serving_root, SNAPSHOT_DIR, f".tmp-{name}")
    if os.path.exists(final):
        if not replace:
            raise FileExistsError(f"snapshot already published: {final}")
        # the store build committed; a retry only needs the pointer flip
        atomic_write_text(
            os.path.join(serving_root, CURRENT_POINTER), name + "\n"
        )
        return final
    if replace and os.path.exists(tmp):
        import shutil

        shutil.rmtree(tmp)  # half-built leftover of a torn publish
    os.makedirs(os.path.dirname(final), exist_ok=True)
    if game_model is not None:
        build_store_from_model(game_model, tmp)
    else:
        build_store(model_dir, index_maps or {}, tmp, task=task)
    os.rename(tmp, final)  # atomic directory publish
    atomic_write_text(os.path.join(serving_root, CURRENT_POINTER), name + "\n")
    return final


def current_snapshot(serving_root: str) -> Optional[str]:
    """The live snapshot's name, or None before the first publish."""
    path = os.path.join(serving_root, CURRENT_POINTER)
    if not os.path.exists(path):
        return None

    def _read():
        with open(path) as f:
            return f.read().strip()

    name = io_call(_read, site="io.serving_store")
    return name or None


def open_current(serving_root: str):
    """(name, ModelStore) for the live snapshot; raises if none published."""
    name = current_snapshot(serving_root)
    if name is None:
        raise FileNotFoundError(
            f"{serving_root}: no published snapshot (no {CURRENT_POINTER})"
        )
    return name, ModelStore.open(snapshot_path(serving_root, name))


class RefreshWatcher:
    """Background poller that loads newly published snapshots and hands them
    to ``on_flip(name, store)``. Counted in ``photon_serving_refresh_total``;
    a failed load leaves the live model serving and is counted via
    ``obs.swallowed_error('serving.refresh')``."""

    def __init__(
        self,
        serving_root: str,
        on_flip: Callable[[str, ModelStore], None],
        poll_seconds: float = 0.2,
        live: Optional[str] = None,
        model: str = "default",
    ):
        self.serving_root = serving_root
        self._on_flip = on_flip
        self.poll_seconds = float(poll_seconds)
        self._live = live
        # fleet identity: each resident model has its OWN watcher (staggered
        # refresh — flips never synchronize across models), so the flip
        # count and span carry the model= label
        self.model = str(model)
        # serializes _check between the poll thread and poke() callers: both
        # run the read-compare-flip of _live, and an unserialized pair could
        # load the same snapshot twice or publish flips out of order
        self._check_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run,
            name=f"photon-serving-refresh-{self.model}",
            daemon=True,
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout=timeout)

    def poke(self) -> None:
        """Check for a new snapshot now (tests; avoids poll-interval sleeps)."""
        self._check()

    def _check(self) -> None:
        with self._check_lock:
            try:
                # the refresh chaos site: PHOTON_FAULTS serving.refresh:delay:...
                # stalls a flip mid-poll, serving.refresh:io:... raises into the
                # swallow-and-retry path below while the live model keeps serving
                faults.check("serving.refresh")
                name = current_snapshot(self.serving_root)
                if name is None or name == self._live:
                    return
                # retry-with-backoff INSIDE the poll (robust.retry, counted
                # via photon_retry_attempts_total{site=}): a transient FS
                # error while opening the snapshot recovers within this poll
                # instead of costing a full poll interval as a one-shot miss
                store = io_call(
                    ModelStore.open,
                    snapshot_path(self.serving_root, name),
                    site="io.serving_store",
                )
            except Exception:
                # a torn/late publish must not take down serving: keep the live
                # model, surface the failure in metrics, retry next poll
                obs.swallowed_error("serving.refresh")
                return
            # the flip lands on the span timeline (and therefore in the
            # flight recorder's ring): a latency anomaly that coincides
            # with a snapshot flip is diagnosable from the postmortem alone
            with obs.span("serving.refresh.flip", snapshot=name, model=self.model):
                self._on_flip(name, store)
            self._live = name
            obs.current_run().registry.counter(
                "photon_serving_refresh_total",
                "model snapshots flipped in without downtime",
            ).labels(model=self.model).inc()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._check()
            self._stop.wait(self.poll_seconds)
