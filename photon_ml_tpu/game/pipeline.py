"""Sweep-level pipelining: async-dispatch depth plumbing and the eval lane.

The CD loop has three independent resource lanes — host staging/H2D, device
solve, and device score/eval — that the serial sweep runs strictly in order
(PR 7's timeline profiler scores it an ``overlap_factor`` of exactly 0).
This module is the coordination layer that lets them overlap without
changing a single accepted bit:

- :func:`pipelined` / :func:`active_depth` / :func:`stage_anchor` carry the
  sweep's pipeline depth and anchor span down to the streaming layers
  (``fe_streaming`` / ``streaming``) through a contextvar, so
  ``descent.run`` does not have to thread a knob through every coordinate
  signature. Depth 1 — the default everywhere — means "exactly the serial
  loop"; the streaming layers only start background staging at depth >= 2.
- :class:`EvalLane` runs validation evaluations on a single daemon worker
  in submit order, bounded by ``capacity`` in-flight snapshots, so
  coordinate k's eval overlaps coordinate k+1's solve. Results are drained
  in FIFO order — the same order the serial loop produced them — which is
  what keeps the best-model comparisons and the evaluation ledger
  bit-identical to depth 1.

Worker-thread spans are parented explicitly on the sweep's anchor span
(contextvar ancestry does not cross threads); that keeps them OUTERMOST
phase spans in ``obs.timeline.phase_attribution`` so the overlap they buy
is the overlap the instrument reports.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
from typing import Callable, List, Optional, Tuple

from .. import obs

_ctx: contextvars.ContextVar = contextvars.ContextVar(
    "photon_pipeline", default=None
)


@contextlib.contextmanager
def pipelined(depth: int, anchor: Optional[obs.Span] = None):
    """Declare a pipelined region of ``depth`` (>= 1); streaming layers
    constructed inside pick the depth up via :func:`active_depth` and parent
    their worker-thread spans on ``anchor`` (normally the sweep span)."""
    if depth < 1:
        raise ValueError(f"pipeline depth must be >= 1: {depth}")
    token = _ctx.set((int(depth), anchor))
    try:
        yield
    finally:
        _ctx.reset(token)


def active_depth() -> int:
    state = _ctx.get()
    return state[0] if state is not None else 1


def stage_anchor() -> Optional[obs.Span]:
    state = _ctx.get()
    return state[1] if state is not None else None


@contextlib.contextmanager
def closing(lane: Optional["EvalLane"]):
    """Close ``lane`` on exit (None is fine) — keeps the sweep's combined
    ``with`` line flat instead of a try/finally around the whole body."""
    try:
        yield lane
    finally:
        if lane is not None:
            lane.close()


class EvalLane:
    """Ordered background evaluation lane for the CD sweep.

    One daemon worker runs ``fn(snapshot)`` per submitted task strictly in
    submit order; :meth:`submit` blocks while ``capacity`` tasks are in
    flight (bounding how many model snapshots stay alive). The consumer
    drains ``(iteration, coordinate, result)`` triples — :meth:`drain_ready`
    without blocking, :meth:`drain_all` before any point that must observe
    the same state as the serial loop (checkpoint boundaries, sweep end).
    A worker exception is parked in order and re-raised at the drain that
    would have returned its result, after which the lane is closed."""

    def __init__(
        self,
        fn: Callable[[dict], object],
        capacity: int,
        anchor: Optional[obs.Span] = None,
        name: str = "photon-eval",
    ):
        if capacity < 1:
            raise ValueError(f"eval lane capacity must be >= 1: {capacity}")
        self._fn = fn
        self._capacity = int(capacity)
        self._anchor = anchor
        self._tasks: collections.deque = collections.deque()
        # (iteration, coordinate, result, error) in submit order
        self._done: collections.deque = collections.deque()
        self._inflight = 0
        self._closed = False
        self._cv = threading.Condition()
        self._thread = threading.Thread(target=self._work, name=name, daemon=True)
        self._thread.start()

    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._tasks and not self._closed:
                    self._cv.wait()
                if self._closed:
                    return
                it, coord, snapshot = self._tasks.popleft()
            try:
                with obs.span(
                    "cd.eval",
                    parent=self._anchor,
                    phase="eval",
                    iteration=it,
                    coordinate=coord,
                ):
                    result, error = self._fn(snapshot), None
            # photon: ignore[R4] — parked, re-raised at the matching drain
            except BaseException as e:
                result, error = None, e
            with self._cv:
                self._done.append((it, coord, result, error))
                self._cv.notify_all()
                if error is not None:
                    self._closed = True
                    return

    def submit(self, iteration: int, coordinate: str, snapshot: dict) -> None:
        """Queue ``fn(snapshot)``; blocks while ``capacity`` results are
        still unconsumed (submitted but not yet drained)."""
        with self._cv:
            while (
                not self._closed
                and self._inflight - len(self._done) >= self._capacity
            ):
                self._cv.wait()
            if self._closed and not self._done:
                raise RuntimeError("EvalLane is closed")
            self._inflight += 1
            self._tasks.append((iteration, coordinate, snapshot))
            self._cv.notify_all()

    def _pop_done(self) -> Tuple[int, str, object]:
        it, coord, result, error = self._done.popleft()
        self._inflight -= 1
        if error is not None:
            raise error
        return it, coord, result

    def drain_ready(self) -> List[Tuple[int, str, object]]:
        """Completed results so far, in submit order; never blocks."""
        out: List[Tuple[int, str, object]] = []
        with self._cv:
            while self._done:
                out.append(self._pop_done())
            self._cv.notify_all()
        return out

    def drain_all(self) -> List[Tuple[int, str, object]]:
        """Block until every submitted task has completed, then return all
        unconsumed results in submit order."""
        out: List[Tuple[int, str, object]] = []
        with self._cv:
            while self._inflight > 0:
                while not self._done:
                    if self._closed and self._inflight > len(self._done):
                        raise RuntimeError("EvalLane worker died")
                    self._cv.wait()
                out.append(self._pop_done())
            self._cv.notify_all()
        return out

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._tasks.clear()
            self._cv.notify_all()
