"""Multi-process random-effect dataset build: entity planning across hosts.

Reference: the reference's cluster-side RE pipeline — entities placed by a
size-aware partitioner that collects (entityId -> count) to the driver
(photon-api .../data/RandomEffectDatasetPartitioner.scala:117-180), followed
by a ``partitionBy`` shuffle of every entity's rows to its owning executor and
per-partition local dataset builds (RandomEffectDataset.scala:255-360).

TPU re-design: the sample axis is already sharded across processes (each host
read its own row range), so the build splits into

1. **Planning metadata exchange** (host, small): each process allgathers its
   local (entity id, count) table (`multihost.allgather_object`); every
   process merges them identically and derives the same `_EntityPlan`
   (size-sorted entity order, block capacity K, weight rescales) — the
   analogue of the reference's driver-side partitioner state.
2. **Device-side shuffle** (bulk, zero host networking): per-row planning
   columns (entity index, splitmix64 reservoir priority) and the row data
   (labels/weights/offsets + ELL features at a globally-agreed width) are
   assembled into globally row-sharded arrays (`multihost.put_global`). The
   active-set selection is ONE multi-key stable device sort
   (``lax.sort(num_keys=3)`` — exactly ``np.lexsort((priority64, entity))``
   via the (hi32, lo32) key split), and the "shuffle" into entity-sharded
   blocks is a device gather: GSPMD lowers the row-sharded -> entity-sharded
   data movement to cross-device collectives over ICI/DCN, which is where the
   reference's Spark shuffle traffic belongs on a TPU pod.
3. **Per-entity subspace projection on device**: each entity's active feature
   column union (LinearSubspaceProjector.scala:37-90) is a vmapped
   sort-and-compact over its gathered ELL columns; block features are
   remapped into subspace slots by a vmapped searchsorted.

Single-process, this degrades to plain device_puts and produces bit-identical
planning to `build_random_effect_dataset` (same `_EntityPlan`, same reservoir
order) — asserted by tests/test_re_build.py's parity tests. Pearson feature
selection included: scores are computed in wide precision and quantized to a
1e-12 grid before ranking, so the ~1e-13 reduction-order differences between
host numpy and XLA collapse onto the same sort key and the stable
column-order tie-break keeps the SAME column on both paths (exact ties are
common for tiny entities, e.g. four columns all scoring sqrt(6)/4). This is
a mitigation with a vanishing — not zero — failure window: a true score
within ~1 ulp of a grid midpoint can still round apart on the two paths.
Tied-column parity therefore NEEDS f64: the 1e-12 grid is below f32
resolution, so the wide scoring path requires jax_enable_x64 and refuses to
run without it (``_require_wide_dtype``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import obs
from ..io.data import RawDataset
from ..parallel import multihost
from ..parallel.mesh import DATA_AXIS
from .data import (
    EntityBlocks,
    RandomEffectDataset,
    _entity_plan,
    _hash64,
    _rows_to_ell,
)


def build_random_effect_dataset_global(
    raw: RawDataset,
    coordinate_id: str,
    feature_shard: str,
    random_effect_type: str,
    mesh,
    active_cap: Optional[int] = None,
    active_lower_bound: int = 1,
    seed: int = 0,
    dtype=jnp.float32,
    pad_entities_to_multiple: int = 1,
    features_to_samples_ratio: Optional[float] = None,
    feature_dtype=None,
    hbm_budget_bytes: Optional[int] = None,
) -> RandomEffectDataset:
    """Build a RandomEffectDataset whose row axis spans ALL processes' rows.

    ``raw`` is this process's local (equal-share padded) row slice; the
    resulting dataset's sample space is the padded GLOBAL row space
    [P * raw.n_rows], row-sharded over the mesh data axis, and the entity
    blocks are entity-sharded over the same axis.

    ``hbm_budget_bytes``: when set and this host's entity shard would exceed
    the budget, the dataset is built STREAMED — each process keeps only ITS
    contiguous block-row range as HOST numpy (``entity_shard_range`` marks
    the range) and training/scoring stream entity slices under the PER-HOST
    budget (game/streaming.py; the execution planner's streamed+sharded
    routing). Caveat: the build itself still stages the full blocks through
    device memory — the budget bounds steady-state training residency, not
    peak build residency.
    """
    if jax.process_count() > 1 and raw.global_row_start is None:
        raise ValueError(
            "multi-process RE build requires raw.global_row_start (this "
            "process's first global row): without it every host would hash "
            "reservoir priorities from row 0 and the active-set selection "
            "silently diverges; set it from multihost.host_row_range"
        )
    np_dtype = np.dtype(jnp.zeros((), dtype).dtype)
    # Pearson selection scores must see pre-cast values (parity with the
    # single-process host build, which selects in f64 and casts after):
    # stage the build in the widest available float, downcast at the end
    build_dtype = (
        np.dtype(jnp.zeros((), jnp.float64).dtype)
        if features_to_samples_ratio is not None
        else np_dtype
    )
    true_local = raw.true_rows if raw.true_rows is not None else raw.n_rows
    g_start = raw.global_row_start or 0
    n_proc = jax.process_count()
    # pad the local row slice exactly like pad_rows_for_mesh pads the
    # fixed-effect batch, so the padded GLOBAL row space (and hence residual
    # score vector positions) is identical across all coordinates
    chunk = max(mesh.shape[DATA_AXIS] // n_proc, 1)
    n_local = ((raw.n_rows + chunk - 1) // chunk) * chunk
    N = n_local * n_proc
    d_shard = raw.shard_dims[feature_shard]
    rows, cols, vals = raw.shard_coo[feature_shard]

    # --- 1. planning metadata exchange (host, small) -------------------------
    ids_arr = np.asarray(raw.id_tags[random_effect_type][:true_local]).astype(str)
    uniq_l, inv_l = np.unique(ids_arr, return_inverse=True)
    counts_l = np.bincount(inv_l, minlength=len(uniq_l)).astype(np.int64)
    nnz_rows = np.bincount(rows, minlength=n_local) if len(rows) else np.zeros(1)
    f_local = max(int(nnz_rows.max()) if n_local else 1, 1)
    tables = multihost.allgather_object((uniq_l, counts_l, f_local))

    all_ids = np.concatenate([t[0] for t in tables])
    all_cnt = np.concatenate([t[1] for t in tables])
    F = max(t[2] for t in tables)
    uniq, inv_m = np.unique(all_ids, return_inverse=True)
    counts = np.zeros(len(uniq), np.int64)
    np.add.at(counts, inv_m, all_cnt)

    plan = _entity_plan(counts, active_lower_bound, active_cap, pad_entities_to_multiple)
    E_real, E, K = plan.E_real, plan.E, plan.K

    # per-host build shape telemetry (host-known numbers; no device fetch)
    reg = obs.current_run().registry
    proc = str(multihost.process_index())
    reg.gauge(
        "photon_re_build_rows", "true (unpadded) local rows per process"
    ).labels(coordinate=coordinate_id, process=proc).set(true_local)
    reg.gauge(
        "photon_re_build_local_entities", "distinct local entities per process"
    ).labels(coordinate=coordinate_id, process=proc).set(len(uniq_l))
    reg.gauge(
        "photon_re_build_global_entities", "kept entities in the merged plan"
    ).labels(coordinate=coordinate_id).set(E_real)

    # --- 2. local per-row planning columns -> global row-sharded arrays ------
    local_block = plan.old_to_block[np.searchsorted(uniq, ids_arr)]
    ent_local = np.full(n_local, -1, np.int32)
    ent_local[:true_local] = local_block
    # reservoir priorities hash the TRUE global row id (parity with the
    # single-process path); active_rows index the PADDED global row space
    pr = _hash64(g_start + np.arange(true_local, dtype=np.int64), seed)
    phi = np.zeros(n_local, np.uint32)
    plo = np.zeros(n_local, np.uint32)
    phi[:true_local] = (pr >> np.uint64(32)).astype(np.uint32)
    plo[:true_local] = (pr & np.uint64(0xFFFFFFFF)).astype(np.uint32)

    def _pad1(a):
        out = np.zeros(n_local, np.float64)
        out[: len(a)] = a
        return out

    wt_local = _pad1(raw.weights)
    safe_block = np.maximum(local_block, 0)
    wt_local[:true_local] *= plan.weight_scale[safe_block]
    lab_local = _pad1(raw.labels)
    off_local = _pad1(raw.offsets)

    ell_idx_l, ell_val_l = _rows_to_ell(rows, cols, vals, n_local, width=F)

    row_spec = P(DATA_AXIS)
    put_row = lambda a: multihost.put_global(a, mesh, row_spec)
    put_ell = lambda a: multihost.put_global(a, mesh, P(DATA_AXIS, None))
    ent_g = put_row(ent_local)
    phi_g = put_row(phi)
    plo_g = put_row(plo)
    lab_g = put_row(lab_local.astype(build_dtype))
    off_g = put_row(off_local.astype(np_dtype))
    wt_g = put_row(wt_local.astype(np_dtype))
    eli_g = put_ell(ell_idx_l)
    elv_g = put_ell(ell_val_l.astype(build_dtype))

    ent_shard = NamedSharding(mesh, P(DATA_AXIS, None))
    ent_shard3 = NamedSharding(mesh, P(DATA_AXIS, None, None))

    # --- 3. device-side active selection (the reservoir, P9) -----------------
    if E_real == 0:
        active_rows = multihost.put_global_from_full(
            np.full((E, K), -1, np.int32), mesh, P(DATA_AXIS, None)
        )
    else:

        def _select(ent, hi, lo):
            n = ent.shape[0]
            idx = jnp.arange(n, dtype=jnp.int32)
            # stable 3-key sort == np.lexsort((priority64, entity)): primary
            # entity, then priority hi32, then lo32, then original position
            s_ent, _, _, s_rows = lax.sort((ent, hi, lo, idx), num_keys=3, is_stable=True)
            starts = jnp.searchsorted(s_ent, jnp.arange(E_real, dtype=s_ent.dtype))
            rank = jnp.arange(n, dtype=jnp.int32) - starts[
                jnp.clip(s_ent, 0, E_real - 1)
            ].astype(jnp.int32)
            active = (s_ent >= 0) & (rank < K)
            te = jnp.where(active, s_ent, E)  # out-of-bounds rows drop
            tk = jnp.where(active, rank, 0)
            return (
                jnp.full((E, K), -1, jnp.int32).at[te, tk].set(s_rows, mode="drop")
            )

        active_rows = jax.jit(_select, out_shardings=ent_shard)(ent_g, phi_g, plo_g)

    # --- 4. device-side shuffle: gather row data into entity blocks ----------
    def _gather(act, lab, off, wt, eli, elv):
        valid = (act >= 0).astype(lab.dtype)
        safe = jnp.maximum(act, 0)
        lb = jnp.take(lab, safe, axis=0) * valid
        ob = jnp.take(off, safe, axis=0) * valid
        wb = jnp.take(wt, safe, axis=0) * valid
        bc = jnp.take(eli, safe, axis=0)  # [E, K, F] global columns
        bv = jnp.take(elv, safe, axis=0) * valid[..., None]
        return lb, ob, wb, bc, bv

    lb, ob, wb, bc, bv = jax.jit(
        _gather,
        out_shardings=(ent_shard, ent_shard, ent_shard, ent_shard3, ent_shard3),
    )(active_rows, lab_g, off_g, wt_g, eli_g, elv_g)

    # --- 5. per-entity subspace projection on device -------------------------
    def _unions(bc, bv):
        keyc = jnp.where(bv != 0, bc, d_shard).reshape(E, K * F)
        sk = jnp.sort(keyc, axis=1)
        prev = jnp.concatenate([jnp.full((E, 1), -1, sk.dtype), sk[:, :-1]], axis=1)
        new = (sk != prev) & (sk < d_shard)
        return sk, new, new.sum(axis=1)

    sk, newm, sizes = jax.jit(
        _unions, out_shardings=(ent_shard, ent_shard, NamedSharding(mesh, P(DATA_AXIS)))
    )(bc, bv)
    sizes_host = np.asarray(multihost.fully_replicate(sizes, mesh)).astype(np.int64)
    S = max(int(sizes_host.max()) if E_real else 1, 1)

    def _project(sk, newm, bc, bv):
        pos = jnp.cumsum(newm, axis=1) - 1
        te = jnp.broadcast_to(jnp.arange(E)[:, None], sk.shape)
        pc = (
            jnp.full((E, S), -1, jnp.int32)
            .at[te, jnp.where(newm, pos, S)]
            .set(sk.astype(jnp.int32), mode="drop")
        )
        pc_search = jnp.where(pc >= 0, pc, d_shard)
        loc = jax.vmap(jnp.searchsorted)(pc_search, bc.reshape(E, K * F))
        loc = loc.reshape(E, K, F)
        nz = bv != 0
        e3 = jnp.broadcast_to(jnp.arange(E)[:, None, None], loc.shape)
        k3 = jnp.broadcast_to(jnp.arange(K)[None, :, None], loc.shape)
        feats = (
            jnp.zeros((E, K, S), bv.dtype)
            .at[e3, k3, jnp.where(nz, loc, S)]
            .set(bv, mode="drop")
        )
        return pc, feats

    pc, feats = jax.jit(_project, out_shardings=(ent_shard, ent_shard3))(
        sk, newm, bc, bv
    )

    if features_to_samples_ratio is not None:
        pc, feats, sizes_host, S = _pearson_select_device(
            mesh, ent_shard, ent_shard3, pc, feats, lb,
            (active_rows >= 0), features_to_samples_ratio, E_real,
        )

    host_pc = np.asarray(multihost.fully_replicate(pc, mesh))

    # --- 6. assemble (downcast wide staging to the block dtype; features and
    # ELL values optionally narrower via feature_dtype) -----------------------
    fdt = feature_dtype or dtype
    fdt_np = np.dtype(jnp.zeros((), fdt).dtype)
    streamed = False
    if hbm_budget_bytes is not None:
        from .streaming import estimate_block_bytes

        # per-HOST budget against this host's entity shard (same estimator
        # as the single-process build, scaled to the local share of E)
        streamed = (
            estimate_block_bytes(-(-E // n_proc), K, int(pc.shape[1]), fdt_np.itemsize)
            > hbm_budget_bytes
        )
    entity_shard_range = None
    if streamed:
        # streamed + sharded: pull THIS host's contiguous block-row range to
        # host numpy; train/score stream it in budget-sized slices
        # (game/streaming.py) and exchange results host-side in process order
        shard_keys = sorted(
            {
                (s.index[0].start or 0, s.index[0].stop)
                for s in active_rows.addressable_shards
            }
        )
        lo = int(shard_keys[0][0])
        hi = int(shard_keys[-1][1]) if shard_keys[-1][1] is not None else E
        entity_shard_range = (lo, hi)
        pull = multihost.host_local_rows
        blocks = EntityBlocks(
            features=pull(feats).astype(fdt_np),
            labels=pull(lb).astype(np_dtype),
            offsets=pull(ob).astype(np_dtype),
            weights=pull(wb).astype(np_dtype),
            proj_cols=pull(pc).astype(np.int32),
            active_rows=pull(active_rows).astype(np.int32),
        )
        # scoring arrays stay LOCAL (this host's padded row slice, plain
        # single-device arrays): the streamed score computes local scores
        # and put_globals them into the global row space
        row_entity_out = jnp.asarray(ent_local)
        ell_idx_out = jnp.asarray(ell_idx_l)
        ell_val_out = jnp.asarray(ell_val_l.astype(fdt_np))
    else:
        if build_dtype != np_dtype or feature_dtype is not None:
            feats = feats.astype(fdt)
            lb = lb.astype(dtype)
            elv_g = elv_g.astype(fdt)
        blocks = EntityBlocks(
            features=feats,
            labels=lb,
            offsets=ob.astype(dtype),
            weights=wb.astype(dtype),
            proj_cols=pc,
            active_rows=active_rows,
        )
        row_entity_out = ent_g
        ell_idx_out = eli_g
        ell_val_out = elv_g
    kept_ids = uniq[plan.kept_entities].astype(str)
    entity_ids = (
        np.concatenate(
            [kept_ids, np.asarray([f"__pad{i}" for i in range(E - E_real)], dtype=object)]
        )
        if E > E_real
        else kept_ids
    )
    entity_counts = np.zeros(E, np.int64)
    entity_counts[:E_real] = np.minimum(counts[plan.kept_entities], K)

    return RandomEffectDataset(
        coordinate_id=coordinate_id,
        feature_shard=feature_shard,
        random_effect_type=random_effect_type,
        entity_ids=entity_ids.astype(object),
        blocks=blocks,
        row_entity=row_entity_out,
        ell_idx=ell_idx_out,
        ell_val=ell_val_out,
        # per-entity passive/active accounting (RandomEffectDataset.scala:
        # 590-599): global rows that belong to a kept entity but were
        # reservoir-dropped from its active block. Derived from the
        # replicated plan arrays — same O(E*K + n) host cost the
        # single-process build pays
        passive_rows=_derive_passive_rows(mesh, ent_local, n_local, active_rows),
        entity_counts=entity_counts,
        entity_subspace_dims=sizes_host,
        host_proj_cols=host_pc,
        streamed=streamed,
        hbm_budget_bytes=hbm_budget_bytes if streamed else None,
        entity_shard_range=entity_shard_range,
        mesh=mesh if streamed else None,
    )


def _derive_passive_rows(mesh, ent_local, n_local, active_rows) -> np.ndarray:
    """PADDED-global row ids that belong to a kept entity but are not in any
    active block (the reference's passive set, RandomEffectDataset.scala:
    590-599).

    ``active_rows`` indexes the padded global row space (local row i on
    process p lives at ``p * n_local + i``), so the local candidates must be
    computed in that same space. Using the TRUE global row start here is
    wrong whenever ``n_rows`` is not divisible by the per-process chunk:
    the pad shifts every later process's rows, active rows get misclassified
    as passive and the returned ids don't address the dataset's row space.

    Scalability: the [n] entity map is NOT replicated — each host tests only
    its own local row slice (host numpy, O(n/p)) against the [E, K] active
    table (replicated once, the same scale as the host_proj_cols table this
    build already replicates), then the per-host PASSIVE candidates — usually
    a small reservoir-dropped subset — are exchanged and concatenated."""
    ar_host = np.asarray(multihost.fully_replicate(active_rows, mesh)).ravel()
    active_ids = np.sort(ar_host[ar_host >= 0].astype(np.int64))
    local_in_entity = (
        multihost.process_index() * n_local
        + np.flatnonzero(np.asarray(ent_local) >= 0)
    ).astype(np.int64)
    pos = np.searchsorted(active_ids, local_in_entity)
    pos = np.minimum(pos, max(len(active_ids) - 1, 0))
    is_active = (
        active_ids[pos] == local_in_entity if len(active_ids) else
        np.zeros(len(local_in_entity), bool)
    )
    local_passive = local_in_entity[~is_active]
    parts = multihost.allgather_object(local_passive)
    return np.sort(np.concatenate(parts)) if parts else local_passive


def _require_wide_dtype():
    """The dtype the device-side Pearson scoring runs in — must be f64.

    The tied-column parity scheme quantizes |score| to a 1e-12 grid
    (``jnp.round(|score|, 12)``) so host/device reduction-order noise
    collapses onto one sort key. f32 resolves ~7 decimal digits, so under
    f32 the rounding is a silent no-op, near-ties rank by raw f32 noise, and
    tied-column selection can diverge from the single-process host build.
    Hence: wide scoring requires jax_enable_x64."""
    wide = jnp.zeros((), jnp.float64).dtype
    if wide != np.dtype(np.float64):
        raise ValueError(
            "features_to_samples_ratio on the multi-process build requires "
            "jax_enable_x64: without f64 the 1e-12 tie-break quantization "
            "(jnp.round(|score|, 12)) is below f32 resolution — a silent "
            "no-op — and tied-column selection can diverge from the "
            "single-process host path. Enable x64 or drop the ratio."
        )
    return wide


def _pearson_select_device(
    mesh, ent_shard, ent_shard3, pc, feats, labels, row_mask, ratio, E_real
):
    """Device-side port of data._pearson_keep_mask + column compaction
    (LocalDataset.filterFeaturesByPearsonCorrelationScore,
    LocalDataset.scala:103-130): keep per entity the ceil(ratio * n_rows)
    columns with the largest |Pearson(feature, label)|, compact kept columns
    to the front, shrink the block subspace dim."""
    E, K, S = feats.shape

    wide = _require_wide_dtype()

    def _keep(feats, labels, row_mask, pc):
        fw = feats.astype(wide)
        lw = labels.astype(wide)
        rm = row_mask.astype(wide)
        eps = jnp.finfo(jnp.float64).eps
        n_e = rm.sum(axis=1)
        n_safe = jnp.maximum(n_e, 1.0)
        mean_y = (lw * rm).sum(axis=1) / n_safe
        dy = (lw - mean_y[:, None]) * rm
        std_y = jnp.sqrt((dy * dy).sum(axis=1))
        mean_x = (fw * rm[:, :, None]).sum(axis=1) / n_safe[:, None]
        dx = (fw - mean_x[:, None, :]) * rm[:, :, None]
        cov = jnp.einsum("eks,ek->es", dx, dy)
        std_x = jnp.sqrt((dx * dx).sum(axis=1))
        score = cov / (std_y[:, None] * std_x + eps)

        const = std_x < jnp.sqrt(n_safe)[:, None] * eps
        cand = const & (jnp.abs(mean_x - 1.0) < 1e-12) & (pc >= 0)
        has = cand.any(axis=1)
        first = jnp.argmax(cand, axis=1)
        first_one = (
            jnp.zeros_like(cand)
            .at[jnp.arange(E), first]
            .set(has)
        )
        score = jnp.where(const, jnp.where(first_one, 1.0, 0.0), score)

        n_active = (pc >= 0).sum(axis=1)
        k_keep = jnp.ceil(ratio * n_e).astype(jnp.int64)
        k_keep = jnp.minimum(k_keep, n_active)
        # quantize to the same 1e-12 grid as the host path: ulp-level
        # reduction-order differences collapse onto one key, so the stable
        # column-order tie-break picks the SAME column on both paths
        absc = jnp.where(pc >= 0, jnp.round(jnp.abs(score), 12), -1.0)
        order = jnp.argsort(-absc, axis=1, stable=True)
        rank = (
            jnp.zeros((E, S), jnp.int64)
            .at[jnp.broadcast_to(jnp.arange(E)[:, None], (E, S)), order]
            .set(jnp.broadcast_to(jnp.arange(S, dtype=jnp.int64), (E, S)))
        )
        keep = (rank < k_keep[:, None]) & (pc >= 0)
        # compact kept columns to the front (stable)
        corder = jnp.argsort(~keep, axis=1, stable=True)
        pc2 = jnp.take_along_axis(jnp.where(keep, pc, -1), corder, axis=1)
        f2 = jnp.take_along_axis(
            jnp.where(keep[:, None, :], feats, 0.0), corder[:, None, :], axis=2
        )
        return pc2, f2, keep.sum(axis=1)

    pc2, f2, sizes = jax.jit(
        _keep,
        out_shardings=(ent_shard, ent_shard3, NamedSharding(mesh, P(DATA_AXIS))),
    )(feats, labels, row_mask, pc)
    sizes_host = np.asarray(multihost.fully_replicate(sizes, mesh)).astype(np.int64)
    S2 = max(int(sizes_host.max()) if E_real else 1, 1)
    return pc2[:, :S2], f2[:, :, :S2], sizes_host, S2
