"""Out-of-core random-effect training: entity-block slices streamed through HBM.

The reference reaches "hundreds of billions of coefficients"
(/root/reference/README.md:56) because Spark spills: RandomEffectDataset RDDs
persist DISK_ONLY and stream through executors
(photon-lib .../algorithm/CoordinateDescent.scala:262,404;
RandomEffectDataset.scala:51-66). The TPU re-design keeps entity blocks in
HOST memory (numpy) and pipelines fixed-size entity slices through the chip:

- the slice size is chosen from an explicit HBM budget (bytes), halved for
  double buffering;
- slice i+1's ``jax.device_put`` is dispatched BEFORE slice i's solve is
  awaited, so the H2D transfer overlaps compute (measured in
  ``bench.py --config billion``: at on-host PCIe the transfer hides entirely
  under the solve);
- per-slice results are fetched to host numpy as soon as the NEXT slice's
  solve is dispatched, so device residency stays bounded by ~2 slices of
  data + solver state regardless of total model size.

Slices respect the size-bucket segmentation (``_size_buckets``), so each
solve call keeps the bucket's (K, S)-rounded shapes and the packed solver's
lane economy. Scoring streams the per-entity coefficient table through the
chip the same way (the model itself is bigger than the budget by
assumption).

Composes with multi-process sharding (the execution planner's
streamed+sharded routing, plan/planner.py): multi-process GLMix shards
entities ACROSS hosts (game/data_mp.py), and when the per-host entity shard
still exceeds ``hbm_budget_bytes`` each host keeps ITS contiguous block-row
range host-resident and streams it through this module under the PER-HOST
budget. Per-host results are exchanged host-side in process order
(coordinate._train_streamed), so streaming scales UP each host's share while
sharding scales OUT across hosts — total coefficient capacity is
P hosts x (host RAM), beyond any single-host resident configuration.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..analysis.runtime import logged_fetch
from ..optimize import SolverResult
from ..utils.futures import PrefetchQueue
from . import pipeline

Array = jax.Array

# proj_cols / active_rows are int32 index planes (io/data.py builds them
# that way); derived here so a future widening to int64 keeps the HBM
# estimates honest instead of silently under-counting
_INDEX_ITEMSIZE = int(np.dtype(np.int32).itemsize)


def estimate_block_bytes(
    E: int, K: int, S: int, feature_itemsize: int = 4, scalar_itemsize: int = 4
) -> int:
    """Device bytes of an in-HBM EntityBlocks of this shape (features +
    labels/offsets/weights + proj_cols/active_rows).

    ``scalar_itemsize`` is the labels/offsets/weights itemsize — 8 for an
    x64-configured dataset; callers derive it from
    ``blocks.labels.dtype.itemsize`` (the old hardcoded 4 under-counted f64
    datasets by up to a third)."""
    return (
        E * K * S * feature_itemsize
        + 3 * E * K * scalar_itemsize
        + E * (S + K) * _INDEX_ITEMSIZE
    )


def entities_per_slice(
    budget_bytes: int,
    K: int,
    S: int,
    feature_itemsize: int = 4,
    multiple: int = 8,
    scalar_itemsize: int = 4,
) -> int:
    """Entities per streamed slice under ``budget_bytes``: double-buffered
    (2 slices resident) plus ~4 [E_s, S] solver-state arrays per entity
    lane (w0/prior/coef/grad; the L-BFGS history is bounded separately by the
    solve itself). Solver state follows the label dtype (``scalar_itemsize``)."""
    state_planes = 4  # w0 / prior-mean / coefficient / gradient per entity
    per_entity = (
        2 * (K * S * feature_itemsize + 3 * K * scalar_itemsize
             + (S + K) * _INDEX_ITEMSIZE)
        + state_planes * S * scalar_itemsize
    )
    e = max(budget_bytes // max(per_entity, 1), multiple)
    return int(e // multiple * multiple)


def solve_streamed(
    blocks_np,  # EntityBlocks holding HOST numpy arrays
    segments,  # [(start, end, K_b, S_b)] from _size_buckets (or one segment)
    residual_scores: Optional[Array],  # device f[n] or None
    w0_np: np.ndarray,  # [E, S] host
    prior_mean_np: np.ndarray,
    prior_prec_np: np.ndarray,
    budget_bytes: int,
    train_fn,  # _train_blocks or _train_blocks_packed
    solver_kwargs: dict,
    pipeline_depth: Optional[int] = None,  # None -> pipeline.active_depth()
) -> SolverResult:
    """Double-buffered streamed solve over all entity slices; returns a
    host-materialized SolverResult in entity order (numpy arrays).

    At ``pipeline_depth`` >= 2 staging moves to a background thread bounded
    by the same byte budget (queued + held slice bytes <= ``budget_bytes``,
    queue-empty admits one — the inline double buffer's worst case). Slice
    geometry, dispatch order, and collect order are unchanged, so the
    outputs are bit-identical to the serial loop."""
    depth = pipeline.active_depth() if pipeline_depth is None else int(pipeline_depth)
    anchor = pipeline.stage_anchor()
    E, K, S = blocks_np.features.shape
    feat_itemsize = blocks_np.features.dtype.itemsize
    # solve dtype follows the dataset's labels (features may be narrower):
    # a f64-configured streamed dataset keeps f64 results, like the in-HBM path
    sdt = np.dtype(blocks_np.labels.dtype)

    # build the flat slice list: buckets split into budget-sized windows
    slices = []
    for start, end, kb, sb in segments:
        step = max(
            min(
                entities_per_slice(
                    budget_bytes, kb, sb, feat_itemsize, scalar_itemsize=sdt.itemsize
                ),
                end - start,
            ),
            8,
        )
        for s0 in range(start, end, step):
            s1 = min(s0 + step, end)
            slices.append((s0, s1, kb, sb))

    staged_stats = {"total_bytes": 0, "max_slice_bytes": 0}
    # (start, end) host wall intervals behind photon_stream_overlap_ratio
    intervals = {"stage": [], "collect": []}

    def stage(sl, parent=None):
        with obs.span(
            "re_stream.stage", parent=parent, phase="stage", slice=sl[0]
        ) as sp:
            s0, s1, kb, sb = sl
            host = (
                blocks_np.features[s0:s1, :kb, :sb],
                blocks_np.labels[s0:s1, :kb],
                blocks_np.offsets[s0:s1, :kb],
                blocks_np.weights[s0:s1, :kb],
                blocks_np.active_rows[s0:s1, :kb],
                w0_np[s0:s1, :sb],
                prior_mean_np[s0:s1, :sb],
                prior_prec_np[s0:s1, :sb],
            )
            nbytes = int(sum(a.nbytes for a in host))
            staged_stats["total_bytes"] += nbytes
            staged_stats["max_slice_bytes"] = max(
                staged_stats["max_slice_bytes"], nbytes
            )
            obs.add_device_put_bytes("streaming.stage", nbytes)
            dev = [jax.device_put(np.ascontiguousarray(a)) for a in host]
        intervals["stage"].append((sp.start_perf, sp.start_perf + sp.duration_s))
        return dev

    def dispatch(staged):
        feats, labels, offsets, weights, active_rows, w0, pm, pp = staged
        if residual_scores is not None:
            res = jnp.take(
                residual_scores, jnp.maximum(active_rows, 0), axis=0
            ) * (active_rows >= 0)
            offsets = offsets + res.astype(offsets.dtype)
        return train_fn(feats, labels, offsets, weights, w0, pm, pp, **solver_kwargs)

    out_coef = np.zeros((E, S), sdt)
    out_grad = np.zeros((E, S), sdt)
    out_loss = np.zeros(E, sdt)
    out_it = np.zeros(E, np.int32)
    out_reason = np.zeros(E, np.int32)
    T = solver_kwargs["max_iterations"] + 1
    out_lh = np.full((E, T), np.nan, sdt)
    out_gh = np.full((E, T), np.nan, sdt)
    empty_result = SolverResult(
        coefficients=out_coef,
        loss=out_loss,
        gradient=out_grad,
        iterations=out_it,
        reason=out_reason,
        loss_history=out_lh,
        grad_norm_history=out_gh,
    )
    if not slices:
        # every segment was empty (e.g. all entities filtered out): nothing
        # to solve — zero coefficients, NOT_CONVERGED reasons, NaN histories
        return empty_result

    def collect(sl, res):
        s0, s1, _, sb = sl
        with obs.span("re_stream.collect", phase="collect", slice=s0) as cp:
            coef, grad, loss, iters, reason, lh, gh = logged_fetch(
                "streaming.collect",
                (
                    res.coefficients, res.gradient, res.loss, res.iterations,
                    res.reason, res.loss_history, res.grad_norm_history,
                ),
            )
        intervals["collect"].append((cp.start_perf, cp.start_perf + cp.duration_s))
        out_coef[s0:s1, :sb] = coef
        out_grad[s0:s1, :sb] = grad
        out_loss[s0:s1] = loss
        out_it[s0:s1] = iters
        out_reason[s0:s1] = reason
        out_lh[s0:s1] = lh
        out_gh[s0:s1] = gh

    def _staged_slice_bytes(e: int, kb: int, sb: int) -> int:
        # what stage() actually transfers: features + labels/offsets/weights
        # + active_rows + the w0/prior-mean/prior-precision planes (proj_cols
        # is not staged — projection happens on the host side)
        return (
            e * kb * sb * feat_itemsize
            + 3 * e * kb * sdt.itemsize
            + e * kb * blocks_np.active_rows.dtype.itemsize
            + 3 * e * sb * sdt.itemsize
        )

    est_max_slice = max(
        _staged_slice_bytes(s1 - s0, kb, sb) for s0, s1, kb, sb in slices
    )

    prefetch = None
    if depth > 1 and len(slices) > 1:
        prefetch = PrefetchQueue(
            lambda i: stage(slices[i], parent=anchor),
            len(slices),
            depth=depth,
            cost=lambda i: _staged_slice_bytes(
                slices[i][1] - slices[i][0], slices[i][2], slices[i][3]
            ),
            budget=budget_bytes,
            name="photon-re-stage",
        )

    def acquire(i):
        if prefetch is None:
            return stage(slices[i])
        idx, staged = prefetch.get()
        if idx != i:
            raise RuntimeError(
                f"re streaming prefetch out of order: staged slice {idx}, "
                f"consumer wants {i}"
            )
        return staged

    try:
        with obs.span(
            "stream.solve", n_slices=len(slices), budget_bytes=int(budget_bytes)
        ):
            staged = acquire(0)
            pending = None  # (slice, dispatched result)
            for i, sl in enumerate(slices):
                res = dispatch(staged)  # async dispatch on the staged slice
                if i + 1 < len(slices):
                    staged = acquire(i + 1)  # H2D overlaps the running solve
                if pending is not None:
                    collect(*pending)  # fetch of slice i-1 syncs AFTER i is queued
                pending = (sl, res)
            collect(*pending)
    finally:
        if prefetch is not None:
            prefetch.close()

    reg = obs.current_run().registry
    # site label distinguishes this (entity-sliced RE) path from the
    # row-sliced fixed-effect path (fe_streaming.py, site="fe.train")
    reg.counter(
        "photon_stream_slices_total", "streamed slices staged through the chip"
    ).labels(site="re.train").inc(len(slices))
    reg.counter(
        "photon_stream_staged_bytes_total", "host bytes staged to device"
    ).labels(site="re.train").inc(staged_stats["total_bytes"])
    reg.gauge(
        "photon_stream_budget_bytes", "configured HBM budget"
    ).labels(site="re.train").set(budget_bytes)
    reg.gauge(
        "photon_stream_estimated_slice_bytes",
        "largest slice footprint by the block-byte estimator",
    ).labels(site="re.train").set(est_max_slice)
    reg.gauge(
        "photon_stream_actual_slice_bytes", "largest slice actually staged"
    ).labels(site="re.train").set(staged_stats["max_slice_bytes"])
    reg.gauge(
        "photon_stream_budget_headroom_bytes",
        "budget minus double-buffered peak (negative = over budget)",
    ).labels(site="re.train").set(budget_bytes - 2 * staged_stats["max_slice_bytes"])
    reg.gauge(
        "photon_stream_overlap_ratio",
        "fraction of staging wall overlapped with in-flight compute",
    ).labels(site="re.train").set(
        obs.overlap_ratio(intervals["stage"], intervals["collect"])
    )
    if prefetch is not None:
        reg.gauge(
            "photon_stream_inflight_peak_bytes",
            "peak staged bytes in flight (queued + held), bounded by the budget",
        ).labels(site="re.train").set(prefetch.peak_inflight)

    return SolverResult(
        coefficients=out_coef,
        loss=out_loss,
        gradient=out_grad,
        iterations=out_it,
        reason=out_reason,
        loss_history=out_lh,
        grad_norm_history=out_gh,
    )


class StreamedScoreCache:
    """One-time host-side regroup of rows by entity slice (plus the x_sub
    densification) reused across score sweeps.

    ``slice_rows[k]`` holds the row indices whose entity falls in slice k,
    padded with the out-of-range sentinel ``n`` up to a power-of-two bucket
    so repeated sweeps reuse O(log n) compiled shapes. ``device_rows`` is the
    total padded row count gathered per sweep — the device work counter the
    flat-wall assertion checks (<= 2n regardless of slice count)."""

    def __init__(self, x_sub, step, slice_rows, device_rows):
        self.x_sub = x_sub  # [n, S] device
        self.step = step
        self.slice_rows = slice_rows  # per-slice device i32[m_k], pad = n
        self.device_rows = device_rows


def _pow2_ceil(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def score_streamed(
    coef_values_np: np.ndarray,  # [E, S] host model table
    proj_cols_np: np.ndarray,  # [E, S] host support layout
    row_entity: Array,  # device i32[n]
    ell_idx: Array,  # device i32[n, F]
    ell_val: Array,  # device f[n, F]
    budget_bytes: int,
    cache: Optional[StreamedScoreCache] = None,
    score_dtype=None,
) -> tuple:
    """Score all rows against a host-resident per-entity coefficient table by
    streaming entity slices of the table through the device.

    Returns (scores [n], cache to reuse across sweeps). The cache holds the
    x_sub densification (row features in entity-subspace layout — row-sized
    [n, S], device-resident by assumption like the ELL arrays) plus a
    one-time host regroup of rows by entity slice.

    Cost shape: rows are regrouped by slice once (stable argsort of
    row_entity on host), so each sweep's slice k touches ONLY its own rows —
    a gather + dot over m_k padded rows with sum(m_k) <= 2n. A sweep is O(n)
    total regardless of slice count (previously each slice did masked O(n)
    work, making sweeps O(n * n_slices))."""
    from ..models.game import ell_support_positions

    E, S = coef_values_np.shape
    n = row_entity.shape[0]
    itemsize = np.dtype(coef_values_np.dtype).itemsize
    # photon: ignore[R3] — the //8*8 below rounds to the 8-entity lane
    # multiple (matches entities_per_slice), not an itemsize
    step = max(int(budget_bytes // max(S * itemsize * 2, 1)) // 8 * 8, 8)
    if score_dtype is None:
        score_dtype = jnp.promote_types(ell_val.dtype, jnp.float32)

    if cache is not None and not isinstance(cache, StreamedScoreCache):
        # pre-regroup callers cached the bare x_sub array
        cache = StreamedScoreCache(cache, -1, None, 0)

    if cache is None or cache.x_sub is None:
        x_sub = jnp.zeros((n, S), ell_val.dtype)
        for s0 in range(0, E, step):
            s1 = min(s0 + step, E)
            pc = jax.device_put(np.ascontiguousarray(proj_cols_np[s0:s1]))
            in_sl = (row_entity >= s0) & (row_entity < s1)
            # reuse the canonical support lookup (models/game.py): rows
            # outside the slice resolve against entity 0's layout but their
            # contribution is masked to zero below
            loc = jnp.where(in_sl, row_entity - s0, 0)
            pos, hit = ell_support_positions(pc, loc, ell_idx)
            contrib = jnp.where(hit & in_sl[:, None], ell_val, 0.0)
            x_sub = x_sub.at[jnp.arange(n)[:, None], pos].add(contrib)
        cache = StreamedScoreCache(x_sub, -1, None, 0)

    if cache.step != step or cache.slice_rows is None:
        # one-time regroup: rows sorted by entity are contiguous by slice;
        # per-slice groups pad to power-of-two buckets (sentinel n) so sweeps
        # reuse O(log n) compiled shapes and total padded work stays <= 2n
        re_np = np.asarray(
            logged_fetch("streaming.score_regroup", row_entity)
        ).astype(np.int64)
        order = np.argsort(re_np, kind="stable")
        edges = np.arange(0, E + step, step)[: (E + step - 1) // step + 1]
        bounds = np.searchsorted(re_np[order], edges)
        slice_rows = []
        device_rows = 0
        for k in range(len(edges) - 1):
            rows = order[bounds[k] : bounds[k + 1]]
            if len(rows) == 0:
                slice_rows.append(None)
                continue
            m = _pow2_ceil(len(rows))
            padded = np.full(m, n, dtype=np.int32)
            padded[: len(rows)] = rows
            slice_rows.append(jax.device_put(padded))
            device_rows += m
        cache = StreamedScoreCache(cache.x_sub, step, slice_rows, device_rows)
        reg = obs.current_run().registry
        reg.gauge(
            "photon_stream_score_device_rows",
            "padded rows gathered per streamed score sweep "
            "(O(n), flat in slice count)",
        ).labels(site="re.score").set(device_rows)

    xsub_wide = cache.x_sub.astype(score_dtype)  # hoisted: cast once per sweep
    scores = jnp.zeros(n, score_dtype)
    n_slices = (E + step - 1) // step
    for k in range(n_slices):
        idx = cache.slice_rows[k]
        if idx is None:
            continue
        s0 = k * step
        e_k = min(s0 + step, E) - s0
        # pad the table slice to `step` entities so every slice shares one
        # compiled shape (the tail would otherwise compile separately)
        w_np = np.zeros((step, S), coef_values_np.dtype)
        w_np[:e_k] = coef_values_np[s0 : s0 + e_k]
        w = jax.device_put(w_np)
        # sentinel rows (idx == n) read entity s0's coefficients against a
        # zero-filled feature row and are dropped by the scatter below
        loc = jnp.take(row_entity, idx, mode="fill", fill_value=s0) - s0
        wr = jnp.take(w, loc, axis=0).astype(score_dtype)  # [m, S]
        xr = jnp.take(xsub_wide, idx, axis=0, mode="fill", fill_value=0)
        part = jnp.sum(wr * xr.astype(score_dtype), axis=1)
        scores = scores.at[idx].add(part, mode="drop")
    reg = obs.current_run().registry
    reg.counter(
        "photon_stream_slices_total", "streamed slices staged through the chip"
    ).labels(site="re.score").inc(n_slices)
    return scores, cache
