"""GAME coordinates: the training/scoring unit of coordinate descent.

Reference: photon-lib .../algorithm/Coordinate.scala:28-84 (trainModel with
optional initial model + residual scores, score), FixedEffectCoordinate.scala
(whole-dataset GLM solve with broadcast model — here: jit over the, possibly
mesh-sharded, global batch), RandomEffectCoordinate.scala:42-375 (per-entity
solves — here: one vmapped masked solver over entity blocks), and the locked
Fixed/RandomEffectModelCoordinate stubs that only score (partial retraining).

Scores returned by coordinates NEVER include base offsets: the coordinate-
descent loop owns residual composition (CoordinateDataScores semantics, P7).
"""

from __future__ import annotations

import dataclasses
import os
import weakref
from functools import partial
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.runtime import logged_fetch
from ..models.coefficients import Coefficients
from ..models.game import FixedEffectModel, RandomEffectModel
from ..models.glm import GeneralizedLinearModel, model_for_task
from ..ops.features import FeatureMatrix, LabeledBatch
from ..ops.glm import GLMObjective
from ..ops.losses import get_loss
from ..ops.normalization import NormalizationContext
from ..optimize import OptimizerType, SolverResult, solve_lbfgs, solve_tron
from ..optimize.common import abs_tolerances
from ..robust import faults
from .data import FixedEffectDataset, RandomEffectDataset
from .problem import GLMOptimizationConfig, GLMProblem
from .sampling import down_sample

Array = jax.Array


class Coordinate:
    """Base coordinate API (Coordinate.scala:28-84)."""

    coordinate_id: str

    @property
    def n_rows(self) -> int:
        raise NotImplementedError

    def train(self, residual_scores: Optional[Array], initial_model):
        """-> (model, SolverResult-or-None). residual_scores f[n] are OTHER
        coordinates' summed scores, added to base offsets for this solve."""
        raise NotImplementedError

    def score(self, model) -> Array:
        """Per-sample scores of this coordinate's model, excluding offsets."""
        raise NotImplementedError


@dataclasses.dataclass
class FixedEffectCoordinate(Coordinate):
    """Whole-dataset GLM solve (FixedEffectCoordinate.scala:33-154)."""

    dataset: FixedEffectDataset
    task: str
    config: GLMOptimizationConfig
    normalization: Optional[NormalizationContext] = None
    down_sampling_seed: int = 0
    # incremental training: regularize toward this model instead of zero
    prior_model: Optional[FixedEffectModel] = None

    def __post_init__(self):
        self.coordinate_id = self.dataset.coordinate_id

    @property
    def n_rows(self) -> int:
        return self.dataset.n_rows

    def train(
        self,
        residual_scores: Optional[Array],
        initial_model: Optional[FixedEffectModel] = None,
    ) -> Tuple[FixedEffectModel, SolverResult]:
        if self.dataset.streamed:
            return self._train_streamed(residual_scores, initial_model)
        batch = self.dataset.batch
        if residual_scores is not None:
            # residual scores live in true sample space; padded batch rows
            # (mesh row multiples) carry zero residual
            n_pad = batch.n_rows - residual_scores.shape[0]
            if n_pad > 0:
                residual_scores = jnp.concatenate(
                    [residual_scores, jnp.zeros((n_pad,), residual_scores.dtype)]
                )
            batch = batch.with_offsets(batch.offsets + residual_scores)
        if self.config.down_sampling_rate < 1.0:
            # runWithSampling (DistributedOptimizationProblem.scala:155-170)
            batch = down_sample(
                batch, self.task, self.config.down_sampling_rate, self.down_sampling_seed
            )
        if faults.active():
            # fault site solver.value_and_grad: corrupt the effective offsets
            # feeding this solve. train() runs eagerly at host level, so the
            # schedule decision never bakes into a compiled function.
            batch = batch.with_offsets(
                faults.corrupt("solver.value_and_grad", batch.offsets)
            )
        problem = GLMProblem(
            task=self.task,
            config=self.config,
            normalization=self.normalization,
            prior=self.prior_model.model.coefficients if self.prior_model else None,
        )
        glm, result = problem.run(
            batch, initial_model=initial_model.model if initial_model else None
        )
        if jax.process_count() > 1:
            # tiled solves leave coefficients model-axis-sharded across
            # processes; replicate so every host can read/save the model
            from ..parallel import multihost

            mesh = getattr(batch.features, "mesh", None)
            if mesh is not None:
                glm = dataclasses.replace(
                    glm,
                    coefficients=multihost.fully_replicate(glm.coefficients, mesh),
                )
                result = multihost.fully_replicate(result, mesh)
        # models live in the shard's TRUE feature space: trim any mesh padding
        d_true = self.dataset.dim
        if glm.coefficients.means.shape[0] > d_true:
            glm = dataclasses.replace(
                glm,
                coefficients=Coefficients(
                    means=glm.coefficients.means[:d_true],
                    variances=None
                    if glm.coefficients.variances is None
                    else glm.coefficients.variances[:d_true],
                ),
            )
        return (
            FixedEffectModel(model=glm, feature_shard=self.dataset.feature_shard),
            result,
        )

    def train_lanes(
        self,
        residual_lanes: Array,  # f[n, L] per-lane residual scores
        l2_lanes: Array,  # f[L] per-lane L2 weights
        w0_lanes: Optional[Array] = None,  # f[d_true, L] warm start
    ) -> Tuple[Array, SolverResult]:
        """Lane-stacked train: L lambda candidates share this batch's data
        residency and one compiled solve (game/lanes.py sweep executor).
        Returns (coefficients f[d_true, L], per-lane SolverResult). The fault
        site mirrors :meth:`train`: flat index 0 of the [n, L] offsets is row
        0 / lane 0, so an injected NaN poisons exactly one lane."""
        if self.dataset.streamed:
            raise ValueError(
                "trial-lanes sweeps require HBM-resident coordinates"
                f" (coordinate {self.coordinate_id} is streamed)"
            )
        if self.config.down_sampling_rate < 1.0:
            raise ValueError(
                "down-sampling is not supported with trial-lanes"
            )
        batch = self.dataset.batch
        L = residual_lanes.shape[1]
        n_pad = batch.n_rows - residual_lanes.shape[0]
        if n_pad > 0:
            residual_lanes = jnp.concatenate(
                [residual_lanes, jnp.zeros((n_pad, L), residual_lanes.dtype)]
            )
        offsets_lanes = batch.offsets[:, None] + residual_lanes
        if faults.active():
            offsets_lanes = faults.corrupt(
                "solver.value_and_grad", offsets_lanes
            )
        if w0_lanes is not None and w0_lanes.shape[0] < batch.dim:
            w0_lanes = jnp.concatenate(
                [
                    w0_lanes,
                    jnp.zeros(
                        (batch.dim - w0_lanes.shape[0], L), w0_lanes.dtype
                    ),
                ]
            )
        problem = GLMProblem(
            task=self.task,
            config=self.config,
            normalization=self.normalization,
            prior=self.prior_model.model.coefficients if self.prior_model else None,
        )
        W, result = problem.run_lanes(
            batch, offsets_lanes, l2_lanes, w0=w0_lanes
        )
        d_true = self.dataset.dim
        if W.shape[0] > d_true:
            W = W[:d_true]
        return W, result

    def score_lanes(self, W: Array) -> Array:
        """Per-sample scores [n, L] of lane-stacked coefficients W[d, L] —
        one fused matmat instead of L matvec dispatches."""
        feats = self.dataset.batch.features
        dtype = self.dataset.batch.labels.dtype
        W = jnp.asarray(W, dtype)
        d_pad = feats.dim - W.shape[0]
        if d_pad > 0:
            W = jnp.concatenate(
                [W, jnp.zeros((d_pad, W.shape[1]), W.dtype)]
            )
        scores = feats.matmat(W)
        n_true = self.dataset.n_rows
        return scores[:n_true] if scores.shape[0] > n_true else scores

    def _train_streamed(
        self,
        residual_scores: Optional[Array],
        initial_model: Optional[FixedEffectModel] = None,
    ) -> Tuple[FixedEffectModel, SolverResult]:
        """Out-of-core FE solve: host-resident rows streamed through the chip
        in double-buffered row slices (game/fe_streaming.py; the reference's
        DISK_ONLY spill + treeAggregate scale path for the fixed effect,
        AvroDataReader.scala:165-209)."""
        ds = self.dataset
        hb = ds.host_batch
        if self.config.down_sampling_rate < 1.0:
            raise ValueError(
                f"coordinate {self.coordinate_id}: down_sampling_rate < 1 is"
                " not supported on the streamed fixed-effect path; raise"
                " hbm.budget.mb so the batch is HBM-resident, or disable"
                " down-sampling"
            )
        if faults.active():
            # same fault site as the resident path: corrupt the host offsets
            # feeding this solve (faults.corrupt copies numpy leaves)
            hb = dataclasses.replace(
                hb, offsets=faults.corrupt("solver.value_and_grad", hb.offsets)
            )
        if (
            residual_scores is not None
            and ds.mesh is not None
            and jax.process_count() > 1
        ):
            # the residual is the global row-sharded [N] vector; this host
            # streams only ITS row slice, so hand the objective the local
            # block (trimmed of the per-host mesh padding rows). A fully
            # replicated residual (e.g. the zeros vector of the first sweep)
            # comes back global from host_local_rows — slice this process's
            # padded block out of it first.
            from ..parallel import multihost

            local = multihost.host_local_rows(residual_scores)
            n_loc_pad = self.n_rows // jax.process_count()
            if local.shape[0] > n_loc_pad:
                start = jax.process_index() * n_loc_pad
                local = local[start : start + n_loc_pad]
            residual_scores = local[: hb.n_rows]
        problem = GLMProblem(
            task=self.task,
            config=self.config,
            normalization=self.normalization,
            prior=self.prior_model.model.coefficients if self.prior_model else None,
        )
        glm, result = problem.run_streamed(
            hb,
            ds.hbm_budget_bytes,
            residual_scores=residual_scores,
            initial_model=initial_model.model if initial_model else None,
        )
        return (
            FixedEffectModel(model=glm, feature_shard=ds.feature_shard),
            result,
        )

    def score(self, model: FixedEffectModel) -> Array:
        if self.dataset.streamed:
            from .fe_streaming import score_streamed_fe

            ds = self.dataset
            hb = ds.host_batch
            dtype = hb.labels.dtype
            means = jnp.asarray(model.model.coefficients.means, dtype)
            d_pad = hb.dim - means.shape[0]
            if d_pad > 0:
                means = jnp.concatenate([means, jnp.zeros((d_pad,), means.dtype)])
            scores = score_streamed_fe(hb, means, ds.hbm_budget_bytes, dtype)
            if ds.mesh is not None and jax.process_count() > 1:
                # local row scores -> global row-sharded vector: pad this
                # host's slice to the per-host mesh chunk (zero-score pad
                # rows, like pad_rows_for_mesh) and put_global
                from jax.sharding import PartitionSpec
                from ..parallel import multihost
                from ..parallel.mesh import DATA_AXIS

                local = np.asarray(
                    logged_fetch("coordinate.fe_stream_score", scores)
                )
                chunk = max(
                    ds.mesh.shape[DATA_AXIS] // jax.process_count(), 1
                )
                n_pad = -(-local.shape[0] // chunk) * chunk
                if n_pad > local.shape[0]:
                    local = np.concatenate(
                        [local, np.zeros(n_pad - local.shape[0], local.dtype)]
                    )
                return multihost.put_global(
                    local, ds.mesh, PartitionSpec(DATA_AXIS)
                )
            return scores
        feats = self.dataset.batch.features
        # compute in the dataset's dtype: a warm-start model loaded under an
        # x64 config is f64 and must not promote the f32 score/residual stream
        dtype = self.dataset.batch.labels.dtype
        means = jnp.asarray(model.model.coefficients.means, dtype)
        d_pad = feats.dim - means.shape[0]
        if d_pad > 0:
            means = jnp.concatenate([means, jnp.zeros((d_pad,), means.dtype)])
        mesh = getattr(feats, "mesh", None)
        if mesh is not None and jax.process_count() > 1:
            # tiled matvec shard_maps over the model axis: reshard the vector
            # on device (no host round trip — the d-sized fetch would cost
            # seconds at huge d)
            from jax.sharding import PartitionSpec
            from ..parallel import multihost
            from ..parallel.sparse import MODEL_AXIS

            means = multihost.reshard(means, mesh, PartitionSpec(MODEL_AXIS))
        scores = feats.matvec(means)
        n_true = self.dataset.n_rows
        return scores[:n_true] if scores.shape[0] > n_true else scores


@dataclasses.dataclass
class RandomEffectCoordinate(Coordinate):
    """Entity-blocked batched solves (RandomEffectCoordinate.scala:42-375).

    The reference joined per-entity datasets with per-entity problems and ran
    thousands of small sequential L-BFGS solves inside each partition (P8).
    Here all entities advance in lockstep through ONE vmapped masked solver —
    each lane converges and freezes independently — and entity blocks shard
    over the mesh on dim 0.
    """

    dataset: RandomEffectDataset
    task: str
    config: GLMOptimizationConfig
    # incremental training: per-entity prior means/precisions
    prior_model: Optional[RandomEffectModel] = None

    def __post_init__(self):
        self.coordinate_id = self.dataset.coordinate_id

    @property
    def n_rows(self) -> int:
        ds = self.dataset
        if ds.entity_shard_range is not None:
            # streamed + sharded: the row arrays hold this host's equal-share
            # slice of the padded global row space
            return ds.row_entity.shape[0] * jax.process_count()
        return ds.row_entity.shape[0]

    def train(
        self,
        residual_scores: Optional[Array],
        initial_model: Optional[RandomEffectModel] = None,
    ) -> Tuple[RandomEffectModel, SolverResult]:
        if self.dataset.streamed:
            return self._train_streamed(residual_scores, initial_model)
        blocks = self.dataset.blocks
        E, K, S = blocks.features.shape
        # solver state stays in the WIDE dtype: features may be stored bf16
        # (feature_dtype), labels/weights/offsets carry the solve precision
        dtype = blocks.labels.dtype

        if residual_scores is not None:
            res_blocks = jnp.take(
                residual_scores, jnp.maximum(blocks.active_rows, 0), axis=0
            ) * (blocks.active_rows >= 0)
            offsets = blocks.offsets + res_blocks.astype(dtype)
        else:
            offsets = blocks.offsets
        if faults.active():
            # same fault site as the fixed-effect path; flat index 0 of the
            # [E, K] offsets is entity 0's first row, so the corruption
            # deterministically poisons exactly one entity lane. (The
            # streamed path carries no injection site — its offsets never
            # materialize whole.)
            offsets = faults.corrupt("solver.value_and_grad", offsets)

        # w0/priors: multi-process passes host numpy (every process holds the
        # full array; jit treats numpy inputs as replicated contributions).
        # Single-process on an ACCELERATOR creates the default zeros/ones ON
        # DEVICE — three host [E, S] uploads per train call (~7 MB at bench
        # shapes) would otherwise ride the host->device link every sweep. On
        # the CPU backend host numpy is kept: the transfer is a memcpy, and
        # device-created inputs to the sharded-blocks pjit tickled an XLA:CPU
        # compiler segfault under long test sessions (observed at
        # test_scale_paths with 8 virtual devices).
        multiproc = jax.process_count() > 1
        if multiproc or jax.default_backend() == "cpu":
            xp, xdt = np, np.dtype(jnp.zeros((), dtype).dtype)
            # explicit logged fetch: warm-start/prior projections may land on
            # device; the CD sweep runs under transfer_guard, which rejects
            # a bare np.asarray on device arrays
            to_host = lambda a: logged_fetch("coordinate.host_state", a)  # noqa: E731
        else:
            xp, xdt = jnp, dtype
            to_host = lambda a: a  # noqa: E731 — single decision point
        if initial_model is not None:
            w0 = to_host(
                _initial_subspace_coefficients(self.dataset, initial_model, dtype)
            )
        else:
            w0 = xp.zeros((E, S), xdt)

        prior_mean = xp.zeros((E, S), xdt)
        prior_prec = xp.ones((E, S), xdt)
        if self.prior_model is not None:
            prior_mean = to_host(
                _project_model_values(
                    self.dataset, self.prior_model, self.prior_model.coef_values, dtype
                )
            )
            if self.prior_model.variances is not None:
                var = _project_model_values(
                    self.dataset, self.prior_model, self.prior_model.variances, dtype
                )
                prior_prec = to_host(1.0 / jnp.maximum(var, 1e-12))

        solver_kwargs = self._solver_kwargs()
        train_fn = self._train_fn()
        segments = _size_buckets(self.dataset, align=_entity_shard_align(blocks))
        if segments is None:
            results = train_fn(
                blocks.features, blocks.labels, offsets, blocks.weights,
                w0, prior_mean, prior_prec, **solver_kwargs,
            )
        else:
            # Size-bucketed solves: entities are sorted by descending row
            # count, so each (K, S)-rounded bucket is a contiguous block-row
            # segment; solving per bucket avoids every small entity paying
            # the padding of the largest (RandomEffectDatasetPartitioner's
            # size-awareness, re-purposed for vmap lane economy).
            parts = []
            for start, end, kb, sb in segments:
                parts.append(
                    train_fn(
                        blocks.features[start:end, :kb, :sb],
                        blocks.labels[start:end, :kb],
                        offsets[start:end, :kb],
                        blocks.weights[start:end, :kb],
                        w0[start:end, :sb],
                        prior_mean[start:end, :sb],
                        prior_prec[start:end, :sb],
                        **solver_kwargs,
                    )
                )
            results = _concat_results(parts, S)
        if jax.process_count() > 1:
            # entity-sharded outputs span processes; replicate so every host
            # can read the model (saving, validation scoring, trackers) — the
            # reference's collect-model-to-driver step
            from ..parallel import multihost

            mesh = blocks.features.sharding.mesh
            results = multihost.fully_replicate(results, mesh)
            coef_indices = jnp.asarray(self.dataset.host_proj_cols)
        else:
            coef_indices = blocks.proj_cols
        w_sub = results.coefficients  # [E, S]
        valid = coef_indices >= 0
        model = RandomEffectModel(
            random_effect_type=self.dataset.random_effect_type,
            feature_shard=self.dataset.feature_shard,
            task=self.task,
            entity_ids=self.dataset.entity_ids,
            coef_indices=coef_indices,
            coef_values=jnp.where(valid, w_sub, 0.0),
        )
        # provenance mark (weakref: must not pin the dataset's device arrays
        # to the model's lifetime): this model's support layout IS this
        # dataset's block layout, so score() can take the cached-positions
        # fast path without fetching/comparing the [E, S] index arrays
        object.__setattr__(model, "_support_layout_of", weakref.ref(self.dataset))
        return model, results

    def _solver_kwargs(self) -> dict:
        """Shared static solver arguments — ONE construction site so the
        in-memory and streamed paths cannot drift."""
        cfg = self.config
        solver_cfg = cfg.solver_config()
        return dict(
            task=self.task,
            l2=cfg.regularization.l2_weight(cfg.reg_weight),
            l1=solver_cfg.l1_weight,
            optimizer_type=OptimizerType(solver_cfg.normalized_type()).value,
            tolerance=solver_cfg.tolerance,
            max_iterations=solver_cfg.max_iterations,
            num_corrections=solver_cfg.num_corrections,
            max_cg_iterations=solver_cfg.max_cg_iterations,
            max_improvement_failures=solver_cfg.max_improvement_failures,
        )

    @staticmethod
    def _train_fn():
        return _train_blocks if _re_solver_mode() == "vmapped" else _train_blocks_packed

    def train_lanes(
        self,
        residual_lanes: Array,  # f[n, L] per-lane residual scores
        l2_lanes: Array,  # f[L] per-lane L2 weights
        w0_lanes: Optional[Array] = None,  # f[E, S, L] warm start
    ) -> Tuple[Array, SolverResult]:
        """Lane-stacked train: every (entity, lambda) pair is one lockstep
        solver lane (game/lanes.py sweep executor). Returns (coef_values
        f[E, S, L] zeroed outside each entity's support, per-lane
        SolverResult with loss/reason [E, L]).

        No size-bucketing here: bucketed stitching pads the trailing axis
        (_concat_results.pad_cols), which on this path is the LANE axis — one
        full-shape solve keeps the layout unambiguous, and the sweep already
        amortizes the padding over L lambdas. The fault site mirrors
        :meth:`train`: flat index 0 of the [E, K, L] offsets is entity 0 /
        row 0 / lane 0."""
        if self.dataset.streamed:
            raise ValueError(
                "trial-lanes sweeps require HBM-resident coordinates"
                f" (coordinate {self.coordinate_id} is streamed)"
            )
        if self.prior_model is not None:
            raise ValueError(
                "regularize-by-prior is not supported with trial-lanes"
            )
        blocks = self.dataset.blocks
        E, K, S = blocks.features.shape
        dtype = blocks.labels.dtype
        L = residual_lanes.shape[1]
        res = jnp.take(
            residual_lanes, jnp.maximum(blocks.active_rows, 0), axis=0
        ) * (blocks.active_rows >= 0)[:, :, None]
        offsets_lanes = blocks.offsets[:, :, None] + res.astype(dtype)
        if faults.active():
            offsets_lanes = faults.corrupt(
                "solver.value_and_grad", offsets_lanes
            )
        # same host-numpy zeros policy as train(): CPU backend keeps w0 on
        # host (device-created pjit inputs tickled an XLA:CPU segfault)
        if jax.process_count() > 1 or jax.default_backend() == "cpu":
            if w0_lanes is None:
                w0 = np.zeros((E, S, L), np.dtype(jnp.zeros((), dtype).dtype))
            else:
                w0 = np.asarray(
                    logged_fetch("coordinate.host_state", w0_lanes)
                )
        else:
            w0 = (
                jnp.zeros((E, S, L), dtype)
                if w0_lanes is None
                else jnp.asarray(w0_lanes, dtype)
            )
        solver_kwargs = self._solver_kwargs()
        if solver_kwargs.pop("l1") > 0.0:
            raise ValueError(
                "trial-lanes sweeps support L2 regularization only (the "
                "OWL-QN l1 weight is compile-time static, not a per-lane "
                "operand)"
            )
        del solver_kwargs["l2"]  # replaced by the dynamic l2_lanes operand
        results = _train_blocks_packed_lanes(
            blocks.features,
            blocks.labels,
            offsets_lanes,
            blocks.weights,
            w0,
            jnp.asarray(l2_lanes, dtype),
            **solver_kwargs,
        )
        valid = blocks.proj_cols >= 0
        W = jnp.where(valid[:, :, None], results.coefficients, 0.0)
        return W, results

    def score_lanes(self, coef_values: Array) -> Array:
        """Per-sample scores [n, L] of lane-stacked per-entity coefficients
        [E, S, L], reusing the densified-subspace cache of the sequential
        scoring hot path (one row gather + fused dot for all L lanes)."""
        from ..models.game import ell_row_subspace, score_entity_rows_dense_lanes

        ds = self.dataset
        row_entity = ds.row_entity
        cache = getattr(ds, "_score_xsub_cache", None)
        if cache is None:
            cache = ell_row_subspace(
                ds.blocks.proj_cols, row_entity, ds.ell_idx, ds.ell_val
            )
            object.__setattr__(ds, "_score_xsub_cache", cache)
        score_dt = jnp.promote_types(ds.ell_val.dtype, ds.blocks.labels.dtype)
        vals = jnp.asarray(coef_values, score_dt)
        return score_entity_rows_dense_lanes(vals, row_entity, cache)

    def _train_streamed(
        self,
        residual_scores: Optional[Array],
        initial_model: Optional[RandomEffectModel] = None,
    ) -> Tuple[RandomEffectModel, SolverResult]:
        """Out-of-core solve: host-resident blocks streamed through the chip
        in double-buffered entity slices (game/streaming.py; the reference's
        DISK_ONLY spill scale path, CoordinateDescent.scala:262,404)."""
        from .streaming import solve_streamed

        ds = self.dataset
        blocks = ds.blocks  # host numpy (streamed+sharded: the local range)
        E, K, S = blocks.features.shape
        sdt = blocks.labels.dtype  # solve dtype (features may be narrower)
        shard = ds.entity_shard_range  # set only when streamed + sharded
        E_g = ds.num_entities  # global entity count (== E when unsharded)

        # warm start / priors are projected in the GLOBAL entity layout
        # (_project_model_values keys off host_proj_cols), then sliced to
        # this host's block-row range for the local solve
        if initial_model is not None:
            w0 = _project_model_values(
                ds, initial_model, initial_model.coef_values, sdt, to_device=False
            )
        else:
            w0 = np.zeros((E_g, S), sdt)
        prior_mean = np.zeros((E_g, S), sdt)
        prior_prec = np.ones((E_g, S), sdt)
        if self.prior_model is not None:
            prior_mean = _project_model_values(
                ds, self.prior_model, self.prior_model.coef_values, sdt,
                to_device=False,
            )
            if self.prior_model.variances is not None:
                var = _project_model_values(
                    ds, self.prior_model, self.prior_model.variances, sdt,
                    to_device=False,
                )
                prior_prec = (1.0 / np.maximum(var, 1e-12)).astype(sdt)

        if shard is not None:
            from ..parallel import multihost

            lo, hi = shard
            w0 = w0[lo:hi]
            prior_mean = prior_mean[lo:hi]
            prior_prec = prior_prec[lo:hi]
            if residual_scores is not None:
                # local active_rows index the PADDED GLOBAL row space, so
                # the solve needs the FULL residual addressable on this
                # host: replicate, fetch, re-place as a plain local array
                residual_scores = jnp.asarray(
                    logged_fetch(
                        "coordinate.stream_residual",
                        multihost.fully_replicate(residual_scores, ds.mesh),
                    )
                )

        solver_kwargs = self._solver_kwargs()
        segments = _size_buckets(ds, entity_range=shard) or [(0, E, K, S)]
        results = solve_streamed(
            blocks,
            segments,
            residual_scores,
            w0,
            prior_mean,
            prior_prec,
            ds.hbm_budget_bytes,
            self._train_fn(),
            solver_kwargs,
        )
        if shard is not None:
            # every host solved ITS contiguous block-row range; process order
            # IS entity order, so a host-side allgather + concat rebuilds the
            # global result table on every host (the reference's
            # collect-model-to-driver step, host-side because the tables are
            # host numpy by streamed design)
            parts = multihost.allgather_object(results)
            results = _concat_results_np(parts)
            coef_indices = np.asarray(ds.host_proj_cols)
        else:
            coef_indices = blocks.proj_cols
        valid = coef_indices >= 0
        model = RandomEffectModel(
            random_effect_type=ds.random_effect_type,
            feature_shard=ds.feature_shard,
            task=self.task,
            entity_ids=ds.entity_ids,
            coef_indices=coef_indices,
            coef_values=np.where(valid, results.coefficients, 0.0),
        )
        object.__setattr__(model, "_support_layout_of", weakref.ref(ds))
        return model, results

    def _support_layout_matches(self, model: RandomEffectModel) -> bool:
        """True when model.coef_indices is this dataset's own block layout
        (the coordinate-descent case). Checks provenance/identity first;
        falls back to a memoized array comparison (bounded FIFO memo holding
        strong refs, so a GC'd array's id cannot alias a stale entry; the
        host proj_cols fetch is cached on the dataset)."""
        ds = self.dataset
        prov = getattr(model, "_support_layout_of", None)
        if prov is not None and prov() is ds:
            return True
        ci = model.coef_indices
        if ci is ds.blocks.proj_cols:
            return True
        memo = getattr(ds, "_layout_match_memo", None)
        if memo is None:
            memo = {}
            object.__setattr__(ds, "_layout_match_memo", memo)
        hit = memo.get(id(ci))
        if hit is not None and hit[0] is ci:
            return hit[1]
        pc_host = getattr(ds, "_host_proj_cols_cache", None)
        if pc_host is None:
            pc_host = ds.host_proj_cols
            if pc_host is None:
                pc_host = logged_fetch(
                    "coordinate.layout_check", ds.blocks.proj_cols
                )
            object.__setattr__(ds, "_host_proj_cols_cache", pc_host)
        ok = tuple(ci.shape) == tuple(np.shape(pc_host)) and np.array_equal(
            logged_fetch("coordinate.layout_check", ci), pc_host
        )
        while len(memo) >= 8:  # bounded: drop oldest entries
            memo.pop(next(iter(memo)))
        memo[id(ci)] = (ci, ok)
        return ok

    def score(self, model: RandomEffectModel) -> Array:
        if self.dataset.streamed:
            from .streaming import score_streamed

            ds = self.dataset
            # identity short-circuit: CD-trained models carry the dataset's
            # own entity_ids array — avoid two O(E) str() list builds per
            # sweep at streamed (big-E) scale
            same_ids = model.entity_ids is ds.entity_ids or list(
                map(str, ds.entity_ids)
            ) == list(map(str, model.entity_ids))
            same_layout = same_ids and self._support_layout_matches(model)
            sdt = np.dtype(ds.blocks.labels.dtype)  # solve/residual dtype
            if same_layout:
                vals = np.asarray(
                    logged_fetch("coordinate.stream_score_model", model.coef_values),
                    sdt,
                )
            else:
                # re-project a differently laid-out model into this dataset's
                # entity/subspace layout on host (no device round trip)
                vals = _project_model_values(
                    ds, model, model.coef_values, sdt, to_device=False
                )
            cache = getattr(ds, "_stream_xsub_cache", None)
            # streamed + sharded: row_entity holds GLOBAL block-row indices,
            # so the coefficient table and support layout must be the GLOBAL
            # ones (blocks.proj_cols covers only this host's range)
            proj = (
                np.asarray(ds.host_proj_cols)
                if ds.entity_shard_range is not None
                else np.asarray(ds.blocks.proj_cols)
            )
            scores, cache = score_streamed(
                vals,
                proj,
                ds.row_entity,
                ds.ell_idx,
                ds.ell_val,
                ds.hbm_budget_bytes,
                cache,
                score_dtype=jnp.promote_types(ds.ell_val.dtype, sdt),
            )
            object.__setattr__(ds, "_stream_xsub_cache", cache)
            if ds.entity_shard_range is not None:
                # local row scores -> global row-sharded vector (each host
                # contributed exactly its padded row slice)
                from jax.sharding import PartitionSpec
                from ..parallel import multihost
                from ..parallel.mesh import DATA_AXIS

                local = np.asarray(
                    logged_fetch("coordinate.stream_score", scores)
                )
                scores = multihost.put_global(
                    local, ds.mesh, PartitionSpec(DATA_AXIS)
                )
            return scores
        row_entity = self.dataset.row_entity
        # The model's entity-row order may differ from this dataset's block
        # order (warm start from a loaded model, locked partial-retrain
        # models): remap dataset block rows -> model rows by entity id.
        # Device-side gather: works when row_entity is sharded across
        # processes (multi-process) as well as single-host.
        ds_ids = list(map(str, self.dataset.entity_ids))
        m_ids = list(map(str, model.entity_ids))
        if ds_ids == m_ids and self._support_layout_matches(model):
            # coordinate-descent hot path: the support LAYOUT is this
            # dataset's own block layout, so the row features are densified
            # into entity-subspace layout once and cached; each sweep's score
            # is then one contiguous row gather + elementwise dot
            # (models/game.py score_entity_rows_dense)
            from ..models.game import ell_row_subspace, score_entity_rows_dense

            cache = getattr(self.dataset, "_score_xsub_cache", None)
            if cache is None:
                cache = ell_row_subspace(
                    model.coef_indices, row_entity,
                    self.dataset.ell_idx, self.dataset.ell_val,
                )
                object.__setattr__(self.dataset, "_score_xsub_cache", cache)
            # scores compute in the WIDE dtype: bf16 feature storage must not
            # truncate the coefficients or the residual stream
            score_dt = jnp.promote_types(
                self.dataset.ell_val.dtype, self.dataset.blocks.labels.dtype
            )
            vals = jnp.asarray(model.coef_values, score_dt)
            return score_entity_rows_dense(vals, row_entity, cache)
        if ds_ids != m_ids:
            block_to_model = model.rows_for(self.dataset.entity_ids).astype(np.int32)
            row_entity = jnp.where(
                row_entity >= 0,
                jnp.take(jnp.asarray(block_to_model), jnp.maximum(row_entity, 0)),
                -1,
            ).astype(jnp.int32)
        ds_dtype = jnp.promote_types(
            self.dataset.ell_val.dtype, self.dataset.blocks.labels.dtype
        )
        if model.coef_values.dtype != ds_dtype:
            model = dataclasses.replace(
                model, coef_values=jnp.asarray(model.coef_values, ds_dtype)
            )
        return model.score_ell_rows(row_entity, self.dataset.ell_idx, self.dataset.ell_val)


def _re_solver_mode() -> str:
    """Random-effect solver selection: 'packed' (default, entity-minor
    lane-packed lockstep solves) or 'vmapped' (the entity-leading vmapped
    path, bit-exact across bucket shapes — the parity/debug escape hatch).
    Unknown values raise instead of silently picking a default."""
    mode = os.environ.get("PHOTON_RE_SOLVER", "packed").strip().lower()
    if mode not in ("packed", "vmapped"):
        raise ValueError(
            f"PHOTON_RE_SOLVER={mode!r}: expected 'packed' or 'vmapped'"
        )
    return mode


def _pow2_ceil(x: np.ndarray) -> np.ndarray:
    """Exact elementwise 2**ceil(log2(max(x, 1))) for int64 inputs < 2^53
    (frexp exponents of exactly-represented ints are bit_lengths)."""
    v = np.maximum(np.asarray(x, dtype=np.int64), 1) - 1
    return np.int64(1) << np.frexp(v.astype(np.float64))[1].astype(np.int64)


def _size_buckets(
    dataset: RandomEffectDataset,
    min_dim: int = 8,
    align: int = 1,
    entity_range: Optional[Tuple[int, int]] = None,
):
    """Contiguous entity segments with power-of-2-rounded (K, S) block shapes.

    Returns [(start, end, K_b, S_b)], or None when per-entity stats are
    unavailable or bucketing cannot shrink anything. Rounding to powers of two
    (floored at ``min_dim``) bounds the number of distinct compiled solver
    shapes at O(log^2) while removing the bulk of the padding FLOPs.

    Fully vectorized (no per-entity Python work — this runs on every train()
    call, potentially over millions of entities). ``align`` snaps segment
    boundaries up to multiples of the per-device entity-chunk size so bucket
    slices of mesh-sharded blocks never split a device shard (counts are
    non-increasing, so the merged head of the next run still fits the larger
    preceding block shape).
    """
    counts = dataset.entity_counts
    svec = dataset.entity_subspace_dims
    if counts is None or svec is None or len(counts) == 0:
        return None
    if entity_range is not None:
        # streamed + sharded: stats are GLOBAL but the blocks hold only this
        # host's [lo, hi) range — bucket the local slice (counts are globally
        # non-increasing, so the slice stays sorted)
        lo, hi = entity_range
        counts = counts[lo:hi]
        svec = svec[lo:hi]
        if len(counts) == 0:
            return None
    E, K, S = dataset.blocks.features.shape

    kb_of = np.minimum(
        np.maximum(_pow2_ceil(np.asarray(counts[:E], dtype=np.int64)), min_dim), K
    )
    bounds = np.flatnonzero(np.diff(kb_of)) + 1  # starts of new equal-K runs
    if align > 1:
        bounds = np.unique(-(-bounds // align) * align)
    bounds = bounds[(bounds > 0) & (bounds < E)]
    starts = np.concatenate([[0], bounds])
    ends = np.concatenate([bounds, [E]])

    sv = np.asarray(svec[:E], dtype=np.int64)
    sb_of = np.minimum(
        np.maximum(_pow2_ceil(np.maximum.reduceat(sv, starts)), min_dim), S
    )
    segments = [
        (
            int(s),
            int(e),
            int(kb_of[s]),  # counts non-increasing => max K of the segment
            int(sb),
        )
        for s, e, sb in zip(starts, ends, sb_of)
    ]
    if len(segments) == 1 and segments[0][2] >= K and segments[0][3] >= S:
        return None
    return segments


def _entity_shard_align(blocks) -> int:
    """Per-device chunk size of mesh-sharded entity blocks (1 = unsharded):
    the boundary multiple that keeps bucket slices shard-aligned."""
    try:
        sh = blocks.features.sharding
        if len(sh.device_set) > 1:
            chunk = sh.shard_shape(blocks.features.shape)[0]
            if chunk < blocks.features.shape[0]:
                return int(chunk)
    except AttributeError:
        # host-numpy blocks (streamed datasets) carry no .sharding: unsharded
        pass
    return 1


def _concat_results(parts, S: int) -> SolverResult:
    """Stitch per-bucket vmapped SolverResults back into entity order,
    zero-padding coefficients/gradients to the global subspace dim."""

    def pad_cols(a):
        if a.shape[-1] == S:
            return a
        return jnp.pad(a, ((0, 0), (0, S - a.shape[-1])))

    return SolverResult(
        coefficients=jnp.concatenate([pad_cols(p.coefficients) for p in parts]),
        loss=jnp.concatenate([p.loss for p in parts]),
        gradient=jnp.concatenate([pad_cols(p.gradient) for p in parts]),
        iterations=jnp.concatenate([p.iterations for p in parts]),
        reason=jnp.concatenate([p.reason for p in parts]),
        loss_history=jnp.concatenate([p.loss_history for p in parts]),
        grad_norm_history=jnp.concatenate([p.grad_norm_history for p in parts]),
    )


def _concat_results_np(parts) -> SolverResult:
    """Stitch per-host streamed SolverResults (host numpy) into the global
    entity order — process order == entity order because the streamed entity
    shard ranges are contiguous and ascending by process."""
    if len(parts) == 1:
        return parts[0]
    return SolverResult(
        **{
            f.name: np.concatenate([np.asarray(getattr(p, f.name)) for p in parts])
            for f in dataclasses.fields(SolverResult)
        }
    )


def _project_model_values(
    dataset: RandomEffectDataset, model: RandomEffectModel, values, dtype,
    to_device: bool = True,
) -> Array:
    """Project per-entity values stored in ``model``'s (entity, support)
    layout into this dataset's entity/subspace block layout (model projection,
    reference ModelProjection.scala:30-85). ``to_device=False`` keeps the
    result in host numpy (streamed datasets must not materialize [E, S] on
    device)."""
    blocks = dataset.blocks
    # multi-process: blocks.proj_cols is entity-sharded (not host-addressable)
    # or, streamed+sharded, holds only the local block-row range; the dataset
    # carries a GLOBAL host copy for layout checks and projection — shapes
    # derive from it so the projection is always in the global entity layout
    pc_host = dataset.host_proj_cols
    if pc_host is None:
        pc_host = logged_fetch("coordinate.project_layout", blocks.proj_cols)
    E, S = np.shape(pc_host)
    idx = np.asarray(
        logged_fetch("coordinate.project_layout", model.coef_indices)
    )
    if (
        idx.shape == (E, S)
        and model.num_entities == E
        and np.array_equal(idx, pc_host)
        and list(map(str, model.entity_ids)) == list(map(str, dataset.entity_ids))
    ):
        # same layout: reuse directly
        if not to_device:
            return np.asarray(
                logged_fetch("coordinate.project_values", values), dtype
            )
        return jnp.asarray(values, dtype)
    # general path: one vectorized sorted-key lookup over all (entity, column)
    # support pairs — no per-entity Python loop and no dense [E, global_dim]
    # intermediate, so re-projecting a large RE model from a differently
    # laid-out checkpoint stays O(nnz log nnz) host time.
    dim = int(max(int(pc_host.max(initial=0)), int(idx.max(initial=0))) + 1)
    vals = np.asarray(logged_fetch("coordinate.project_values", values))
    me, ms = np.nonzero(idx >= 0)
    mkeys = me.astype(np.int64) * dim + idx[me, ms]
    order = np.argsort(mkeys, kind="stable")
    mkeys_s = mkeys[order]
    mvals_s = vals[me, ms][order]

    rows = np.asarray(
        jax.device_get(model.rows_for(dataset.entity_ids))
    )  # [E] model row or -1
    pc = pc_host
    de, dsl = np.nonzero((pc >= 0) & (rows[:, None] >= 0))
    dkeys = rows[de].astype(np.int64) * dim + pc[de, dsl]
    w0 = np.zeros((E, S))
    if len(mkeys_s) and len(dkeys):
        # side='right' - 1: among duplicate support columns the LAST stored
        # value wins, matching numpy fancy-assignment (the prior dense path)
        pos = np.clip(np.searchsorted(mkeys_s, dkeys, side="right") - 1, 0, None)
        hit = mkeys_s[pos] == dkeys
        w0[de[hit], dsl[hit]] = mvals_s[pos[hit]]
    return np.asarray(w0, dtype) if not to_device else jnp.asarray(w0, dtype)


def _initial_subspace_coefficients(
    dataset: RandomEffectDataset, model: RandomEffectModel, dtype
) -> Array:
    """Warm-start coefficients in this dataset's block layout."""
    return _project_model_values(dataset, model, model.coef_values, dtype)


@partial(
    jax.jit,
    static_argnames=(
        "task",
        "l2",
        "l1",
        "optimizer_type",
        "tolerance",
        "max_iterations",
        "num_corrections",
        "max_cg_iterations",
        "max_improvement_failures",
    ),
)
def _train_blocks(
    features: Array,  # [E, K, S]
    labels: Array,
    offsets: Array,
    weights: Array,
    w0: Array,  # [E, S]
    prior_mean: Array,  # [E, S]; zeros = plain L2
    prior_prec: Array,  # [E, S]; ones = plain L2
    *,
    task: str,
    l2: float,
    l1: float,
    optimizer_type: str,
    tolerance: float,
    max_iterations: int,
    num_corrections: int,
    max_cg_iterations: int,
    max_improvement_failures: int,
) -> SolverResult:
    """One vmapped masked solve over all entity blocks."""
    loss = get_loss(task)
    S = features.shape[-1]

    def solve_one(feat, y, off, wt, w0_e, pm_e, pp_e):
        batch = LabeledBatch(
            features=FeatureMatrix(dim=S, dense=feat),
            labels=y,
            offsets=off,
            weights=wt,
        )
        obj = GLMObjective(
            loss=loss, batch=batch, l2=l2, prior_mean=pm_e, prior_precision=pp_e
        )
        loss_tol, grad_tol = abs_tolerances(obj.value_and_grad, w0_e, tolerance)
        if optimizer_type == "TRON":
            return solve_tron(
                obj.value_and_grad,
                obj.hessian_vector,
                w0_e,
                loss_tol,
                grad_tol,
                max_iterations=max_iterations,
                max_cg_iterations=max_cg_iterations,
                max_improvement_failures=max_improvement_failures,
            )
        return solve_lbfgs(
            obj.value_and_grad,
            w0_e,
            loss_tol,
            grad_tol,
            max_iterations=max_iterations,
            num_corrections=num_corrections,
            l1_weight=l1,
        )

    return jax.vmap(solve_one)(
        features, labels, offsets, weights, w0, prior_mean, prior_prec
    )


@partial(
    jax.jit,
    static_argnames=(
        "task",
        "l2",
        "l1",
        "optimizer_type",
        "tolerance",
        "max_iterations",
        "num_corrections",
        "max_cg_iterations",
        "max_improvement_failures",
    ),
)
def _train_blocks_packed(
    features: Array,  # [E, K, S]
    labels: Array,
    offsets: Array,
    weights: Array,
    w0: Array,  # [E, S]
    prior_mean: Array,  # [E, S]; zeros = plain L2
    prior_prec: Array,  # [E, S]; ones = plain L2
    *,
    task: str,
    l2: float,
    l1: float,
    optimizer_type: str,
    tolerance: float,
    max_iterations: int,
    num_corrections: int,
    max_cg_iterations: int,
    max_improvement_failures: int,
) -> SolverResult:
    """Entity-minor lockstep solve over all entity blocks.

    Same contract as :func:`_train_blocks`, but instead of vmapping with the
    entity axis leading ([E, K, S] puts S in the TPU's 128-wide lane dimension
    — at S=32 that wastes 3/4 of every vector op), the data is transposed so
    the ENTITY axis is minor: features [K, S, E], coefficients [S, E]. Every
    solver op is then elementwise over a fully packed lane dimension whatever
    S is, and the per-entity reductions are axis-0 sums. This is the
    lane-packing redesign of the reference's per-partition sequential solves
    (RandomEffectCoordinate.scala:273-329). The transpose happens inside jit
    so GSPMD sharding propagates (entity-sharded blocks stay entity-sharded
    on the trailing axis).
    """
    loss = get_loss(task)
    # features may be stored narrower (bf16); products below promote to the
    # labels' (solve) dtype on the fly, halving the F sweep traffic
    F = jnp.transpose(features, (1, 2, 0))  # [K, S, E]
    y = labels.T  # [K, E]
    off = offsets.T.astype(labels.dtype)
    wt = weights.T
    w0t = w0.T  # [S, E]
    pm = prior_mean.T
    pp = prior_prec.T

    def value_and_grad(w):  # [S, E] -> ([E], [S, E])
        z = jnp.sum(F * w[None, :, :], axis=1) + off  # [K, E]
        lvals, dz = loss.loss_and_dz(z, y)
        wdz = wt * dz
        value = jnp.sum(wt * lvals, axis=0)  # [E]
        grad = jnp.sum(F * wdz[:, None, :], axis=0)  # [S, E]
        delta = w - pm
        value = value + 0.5 * l2 * jnp.sum(pp * delta * delta, axis=0)
        grad = grad + l2 * pp * delta
        return value, grad

    def hessian_vector(w, v):
        z = jnp.sum(F * w[None, :, :], axis=1) + off
        c = wt * loss.d2z(z, y) * jnp.sum(F * v[None, :, :], axis=1)  # [K, E]
        return jnp.sum(F * c[:, None, :], axis=0) + l2 * pp * v

    loss_tol, grad_tol = abs_tolerances(value_and_grad, w0t, tolerance)
    if optimizer_type == "TRON":
        res = solve_tron(
            value_and_grad,
            hessian_vector,
            w0t,
            loss_tol,
            grad_tol,
            max_iterations=max_iterations,
            max_cg_iterations=max_cg_iterations,
            max_improvement_failures=max_improvement_failures,
        )
    else:
        res = solve_lbfgs(
            value_and_grad,
            w0t,
            loss_tol,
            grad_tol,
            max_iterations=max_iterations,
            num_corrections=num_corrections,
            l1_weight=l1,
            batched=True,
        )
    return SolverResult(
        coefficients=res.coefficients.T,
        loss=res.loss,
        gradient=res.gradient.T,
        iterations=res.iterations,
        reason=res.reason,
        loss_history=res.loss_history.T,
        grad_norm_history=res.grad_norm_history.T,
    )


@partial(
    jax.jit,
    static_argnames=(
        "task",
        "optimizer_type",
        "tolerance",
        "max_iterations",
        "num_corrections",
        "max_cg_iterations",
        "max_improvement_failures",
    ),
)
def _train_blocks_packed_lanes(
    features: Array,  # [E, K, S]
    labels: Array,  # [E, K]
    offsets_lanes: Array,  # [E, K, L] residual-composed per-lane offsets
    weights: Array,  # [E, K]
    w0: Array,  # [E, S, L]
    l2_lanes: Array,  # f[L] — dynamic operand, NOT static: candidate
    # refreshes must reuse the executable
    *,
    task: str,
    optimizer_type: str,
    tolerance: float,
    max_iterations: int,
    num_corrections: int,
    max_cg_iterations: int,
    max_improvement_failures: int,
) -> SolverResult:
    """Entity-minor lockstep solve widened by the lambda-lane axis.

    Same contract as :func:`_train_blocks_packed`, with the solver lane set
    the (entity, lambda) product: coefficients run as ``[S, E, L]`` so every
    per-problem reduction stays axis-0 and the L2 weight vector broadcasts
    from the trailing lane axis. One executable covers every candidate batch
    of the same L (the lambdas are data, not shape)."""
    loss = get_loss(task)
    F = jnp.transpose(features, (1, 2, 0))  # [K, S, E]
    y = labels.T[:, :, None]  # [K, E, 1]
    off = jnp.transpose(offsets_lanes, (1, 0, 2)).astype(labels.dtype)  # [K, E, L]
    wt = weights.T[:, :, None]
    w0t = jnp.transpose(w0, (1, 0, 2)).astype(labels.dtype)  # [S, E, L]

    def value_and_grad(w):  # [S, E, L] -> ([E, L], [S, E, L])
        z = jnp.einsum("kse,sel->kel", F, w) + off  # [K, E, L]
        lvals, dz = loss.loss_and_dz(z, y)
        wdz = wt * dz
        value = jnp.sum(wt * lvals, axis=0)  # [E, L]
        grad = jnp.einsum("kse,kel->sel", F, wdz)  # [S, E, L]
        value = value + 0.5 * l2_lanes * jnp.sum(w * w, axis=0)
        grad = grad + l2_lanes * w
        return value, grad

    def hessian_vector(w, v):
        z = jnp.einsum("kse,sel->kel", F, w) + off
        c = wt * loss.d2z(z, y) * jnp.einsum("kse,sel->kel", F, v)
        return jnp.einsum("kse,kel->sel", F, c) + l2_lanes * v

    loss_tol, grad_tol = abs_tolerances(value_and_grad, w0t, tolerance)
    if optimizer_type == "TRON":
        res = solve_tron(
            value_and_grad,
            hessian_vector,
            w0t,
            loss_tol,
            grad_tol,
            max_iterations=max_iterations,
            max_cg_iterations=max_cg_iterations,
            max_improvement_failures=max_improvement_failures,
        )
    else:
        res = solve_lbfgs(
            value_and_grad,
            w0t,
            loss_tol,
            grad_tol,
            max_iterations=max_iterations,
            num_corrections=num_corrections,
            batched=True,
        )
    back = lambda a: jnp.transpose(a, (1, 0, 2))  # noqa: E731 — [S,E,L]->[E,S,L]
    return SolverResult(
        coefficients=back(res.coefficients),
        loss=res.loss,  # [E, L]
        gradient=back(res.gradient),
        iterations=res.iterations,
        reason=res.reason,  # [E, L]
        loss_history=jnp.moveaxis(res.loss_history, 0, -1),  # [E, L, T]
        grad_norm_history=jnp.moveaxis(res.grad_norm_history, 0, -1),
    )


@dataclasses.dataclass
class ModelCoordinate(Coordinate):
    """Locked coordinate: scores a pretrained model, never retrains
    (ModelCoordinate.scala / Fixed-/RandomEffectModelCoordinate — partial
    retraining, CoordinateDescent.scala:280-300)."""

    inner: Coordinate
    locked_model: Union[FixedEffectModel, RandomEffectModel]

    def __post_init__(self):
        self.coordinate_id = self.inner.coordinate_id

    @property
    def n_rows(self) -> int:
        return self.inner.n_rows

    def train(self, residual_scores, initial_model=None):
        return self.locked_model, None

    def score(self, model=None) -> Array:
        return self.inner.score(self.locked_model)
