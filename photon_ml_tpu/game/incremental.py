"""Day-chained incremental retraining with no-degrade promotion gates.

The reference treats warm-start / partial retrain as a first-class production
scenario ("Regularize by Previous Model During Warm-Start Training",
reference README.md:102-103, and the warm-start integration battery in
GameTrainingDriverIntegTest.scala:60-474). This module closes the
train->serve loop around that machinery: walk a time-partitioned feed one
day at a time, warm-start day k+1 from day k's accepted model with
prior-centered L2 (``CoordinateConfig.regularize_by_prior``), re-solve ONLY
what the new rows touch, gate the candidate behind a per-metric no-degrade
check against the live model, and publish accepted models into a running
``cli serve`` via ``serving.refresh.publish_snapshot``.

Partial re-solve falls out of the data layout rather than bookkeeping: a
day's ``RawDataset`` contains exactly the entities its rows touch, so the
day's coordinate descent trains per-entity models for those entities only.
:func:`merge_models` then grows the accepted prior in place —

- entities untouched by the day carry forward **bitwise** (their coefficient
  rows are copied, never recomputed);
- touched entities take the day's re-solved rows (support remapped into the
  merged padded width);
- entities appearing mid-stream are appended, growing the model (their
  warm-start came from the zero-mean prior ``_project_model_values``
  assigns to unseen entities).

Promotion is refused, not assumed: :func:`no_degrade_gate` scores candidate
and live on the SAME held-out validation set and rejects the candidate if
any requested metric (e.g. ``AUC`` and the per-group ``AUC:groupId``)
degrades beyond ``margin``. A rejection is typed and counted
(``photon_retrain_rejected_total{reason=}``) and the previous snapshot keeps
serving — a poisoned day (NaN storm, quarantined rows) can cost a day's
update but never the chain or the live store.

Failure drill points (``PHOTON_FAULTS``):

- ``retrain.day`` — checked once per chain day before any of its work; a
  ``kill`` there is the crash-between-days drill (the ledger resumes).
- ``retrain.publish`` — checked immediately before snapshot publication; an
  ``io`` error there is the torn-publish drill (the decision is already in
  the ledger, the next cycle's :func:`_ensure_published` repairs the store).

Mid-day kills resume through the ordinary boundary-checkpoint path: each
day's CD runs under a ``robust.CheckpointManager`` whose manifests carry the
chain position and the accepted/rejected ledger so far (``base_meta``), and
the chain state file marks the day in progress.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from .. import obs
from ..analysis.runtime import logged_fetch
from ..evaluation import build_suite
from ..models.game import GameModel, RandomEffectModel
from ..robust import faults
from ..robust.atomic import atomic_write_json
from ..robust.checkpoint import CheckpointManager
from ..robust.retry import io_call

logger = logging.getLogger(__name__)

CHAIN_STATE_NAME = "chain-state.json"
_CHAIN_STATE_VERSION = 1


# -- random-effect growth ----------------------------------------------------


def grow_random_effect(
    prior: RandomEffectModel, update: RandomEffectModel
) -> RandomEffectModel:
    """Merge a day's re-solved entities into ``prior``, growing it in place.

    Entities present in ``update`` take their re-solved rows; entities only
    in ``prior`` carry forward bitwise (row copies, no recompute); entities
    new to ``update`` are appended after the prior rows (model growth). The
    padded support width widens to fit both sides; widening pads with the
    ``-1`` sentinel, so untouched rows score identically.

    Posterior variances merge only when BOTH sides carry them (a means-only
    day update invalidates the prior's stale variances for touched rows, so
    the merged model drops them rather than serving a mix).
    """
    import jax.numpy as jnp

    if prior.random_effect_type != update.random_effect_type:
        raise ValueError(
            "cannot merge random-effect models of different types: "
            f"{prior.random_effect_type!r} vs {update.random_effect_type!r}"
        )
    if prior.feature_shard != update.feature_shard:
        raise ValueError(
            "cannot merge random-effect models of different feature shards: "
            f"{prior.feature_shard!r} vs {update.feature_shard!r}"
        )

    p_idx = np.asarray(logged_fetch("retrain.merge", prior.coef_indices))
    p_val = np.asarray(logged_fetch("retrain.merge", prior.coef_values))
    u_idx = np.asarray(logged_fetch("retrain.merge", update.coef_indices))
    u_val = np.asarray(logged_fetch("retrain.merge", update.coef_values))

    S = max(p_idx.shape[1], u_idx.shape[1])
    val_dt = np.result_type(p_val.dtype, u_val.dtype)

    def _widen_idx(a):
        if a.shape[1] == S:
            return a
        return np.pad(a, ((0, 0), (0, S - a.shape[1])), constant_values=-1)

    def _widen_val(a):
        if a.shape[1] == S:
            return a
        return np.pad(a, ((0, 0), (0, S - a.shape[1])))

    # destination row for every update entity: the prior's row when it exists
    # (re-solve in place), else a fresh appended row (model growth)
    dest = np.empty(update.num_entities, dtype=np.int64)
    ids = list(map(str, prior.entity_ids))
    for e, ent in enumerate(update.entity_ids):
        r = prior.entity_row(ent)
        if r < 0:
            r = len(ids)
            ids.append(str(ent))
        dest[e] = r
    E_out = len(ids)

    out_idx = np.full((E_out, S), -1, dtype=np.int32)
    out_val = np.zeros((E_out, S), dtype=val_dt)
    out_idx[: prior.num_entities] = _widen_idx(p_idx)
    out_val[: prior.num_entities] = _widen_val(p_val).astype(val_dt, copy=False)
    out_idx[dest] = _widen_idx(u_idx)
    out_val[dest] = _widen_val(u_val).astype(val_dt, copy=False)

    variances = None
    if prior.variances is not None and update.variances is not None:
        p_var = np.asarray(logged_fetch("retrain.merge", prior.variances))
        u_var = np.asarray(logged_fetch("retrain.merge", update.variances))
        out_var = np.zeros((E_out, S), dtype=val_dt)
        out_var[: prior.num_entities] = _widen_val(p_var).astype(val_dt, copy=False)
        out_var[dest] = _widen_val(u_var).astype(val_dt, copy=False)
        variances = jnp.asarray(out_var)

    return RandomEffectModel(
        random_effect_type=prior.random_effect_type,
        feature_shard=prior.feature_shard,
        task=update.task,
        entity_ids=np.asarray(ids, dtype=object),
        coef_indices=jnp.asarray(out_idx),
        coef_values=jnp.asarray(out_val),
        variances=variances,
    )


def merge_models(
    prior: Optional[GameModel], update: GameModel
) -> Tuple[GameModel, Dict[str, int]]:
    """Fold a day's trained model into the accepted prior.

    Fixed effects are whole-model replacements (every row carries the global
    features, so the day re-solves them entirely). Random effects grow via
    :func:`grow_random_effect`. Coordinates absent from the update carry
    forward untouched. Returns ``(merged, touched)`` where ``touched`` maps
    each random-effect coordinate to the number of entities the day
    re-solved or added."""
    if prior is None:
        touched = {
            name: m.num_entities
            for name, m in update.models.items()
            if isinstance(m, RandomEffectModel)
        }
        return update, touched

    merged = dict(prior.models)
    touched: Dict[str, int] = {}
    for name, m in update.models.items():
        old = merged.get(name)
        if isinstance(m, RandomEffectModel) and isinstance(old, RandomEffectModel):
            merged[name] = grow_random_effect(old, m)
            touched[name] = m.num_entities
        else:
            merged[name] = m
            if isinstance(m, RandomEffectModel):
                touched[name] = m.num_entities
    return GameModel(models=merged, task=update.task), touched


# -- the no-degrade promotion gate -------------------------------------------


@dataclasses.dataclass(frozen=True)
class GateDecision:
    """Outcome of one candidate-vs-live promotion check."""

    accepted: bool
    reason: str  # "accepted" | "first-publish" | "non-finite" | "degraded:<metric>"
    candidate_metrics: Dict[str, float]
    live_metrics: Optional[Dict[str, float]] = None


def no_degrade_gate(
    candidate: GameModel,
    live: Optional[GameModel],
    validation,
    evaluator_specs: Sequence[str],
    margin: float = 0.0,
    dtype=None,
) -> GateDecision:
    """Score candidate and live on the SAME held-out validation set; refuse
    the candidate if any requested metric degrades beyond ``margin`` in that
    metric's own direction (per-group ``AUC:groupId`` specs degrade when the
    unweighted mean of per-group AUCs drops). A candidate with non-finite
    scores or a NaN metric is refused outright — a NaN-poisoned day must
    never reach the live store. With no live model the first candidate is
    accepted (``first-publish``)."""
    import jax.numpy as jnp

    from ..estimators.game_estimator import GameTransformer

    dtype = jnp.float32 if dtype is None else dtype
    with obs.span("retrain.gate"):
        scores, evaluation = GameTransformer(
            model=candidate, dtype=dtype
        ).transform(validation, evaluator_specs)
        cand_metrics = dict(evaluation.metrics)
        host_scores = np.asarray(scores)
        if not np.all(np.isfinite(host_scores)) or any(
            not np.isfinite(v) for v in cand_metrics.values()
        ):
            return GateDecision(False, "non-finite", cand_metrics, None)
        if live is None:
            return GateDecision(True, "first-publish", cand_metrics, None)
        _, live_eval = GameTransformer(model=live, dtype=dtype).transform(
            validation, evaluator_specs
        )
        live_metrics = dict(live_eval.metrics)
        suite = build_suite(
            evaluator_specs, validation.labels, validation.weights,
            id_tags=validation.id_tags,
        )
        for ev in suite.evaluators:
            cand_v = cand_metrics[ev.name]
            live_v = live_metrics[ev.name]
            if not np.isfinite(live_v):
                continue  # a broken live metric cannot veto an improvement
            degraded = (
                live_v - cand_v > margin
                if ev.higher_is_better
                else cand_v - live_v > margin
            )
            if degraded:
                return GateDecision(
                    False, f"degraded:{ev.name}", cand_metrics, live_metrics
                )
        return GateDecision(True, "accepted", cand_metrics, live_metrics)


# -- the day chain -----------------------------------------------------------


@dataclasses.dataclass
class DayRecord:
    """One ledger row: the chain's decision for one day."""

    day: str
    index: int
    accepted: bool
    reason: str
    rows: int
    touched_entities: Dict[str, int]
    snapshot: Optional[str] = None
    published: bool = False
    metrics: Optional[Dict[str, float]] = None


@dataclasses.dataclass
class ChainResult:
    """Final state of one :func:`run_chain` invocation."""

    model: Optional[GameModel]  # the live (last accepted) model
    ledger: List[DayRecord]
    rows_touched: int  # rows the incremental chain actually trained on
    rows_cumulative: int  # rows a daily from-scratch retrain would have touched

    @property
    def rows_touched_fraction(self) -> float:
        return self.rows_touched / max(self.rows_cumulative, 1)


def _record_decision(decision: GateDecision, day_index: int) -> None:
    outcome = "accepted" if decision.accepted else "rejected"
    registry = obs.current_run().registry
    registry.counter(
        "photon_retrain_days_total",
        "chain days processed, by promotion outcome",
    ).labels(outcome=outcome).inc()
    if not decision.accepted:
        registry.counter(
            "photon_retrain_rejected_total",
            "candidate models refused by the no-degrade promotion gate",
        ).labels(reason=decision.reason).inc()
    obs.current_run().registry.gauge(
        "photon_retrain_day_index", "index of the chain day last processed"
    ).set(float(day_index))


def _load_chain_state(chain_dir: Optional[str]) -> dict:
    if not chain_dir:
        return {"version": _CHAIN_STATE_VERSION, "days": [], "in_progress": None}
    path = os.path.join(chain_dir, CHAIN_STATE_NAME)
    if not os.path.exists(path):
        return {"version": _CHAIN_STATE_VERSION, "days": [], "in_progress": None}

    def _read():
        with open(path) as f:
            return json.load(f)

    state = io_call(_read, site="io.chain_state")
    if state.get("version") != _CHAIN_STATE_VERSION:
        raise ValueError(
            f"{path}: unsupported chain-state version {state.get('version')!r}"
        )
    return state


def _save_chain_state(chain_dir: Optional[str], state: dict) -> None:
    if not chain_dir:
        return
    os.makedirs(chain_dir, exist_ok=True)
    io_call(
        atomic_write_json,
        os.path.join(chain_dir, CHAIN_STATE_NAME),
        state, indent=2,
        site="io.chain_state",
    )


def _ledger_meta(ledger: Sequence[DayRecord]) -> List[dict]:
    return [dataclasses.asdict(r) for r in ledger]


def _ensure_published(serving_root: str, record: DayRecord, model: GameModel) -> bool:
    """Repair path: make the last accepted decision visible in the serving
    store. Called at the top of every cycle — a torn publish (crash or IO
    error between the gate decision and the store flip) leaves the old
    snapshot serving until this makes the accepted one live. Idempotent:
    an already-live snapshot is a no-op."""
    from ..serving import refresh

    if record.snapshot is None:
        return False
    if (
        refresh.current_snapshot(serving_root) == record.snapshot
        and os.path.isdir(refresh.snapshot_path(serving_root, record.snapshot))
    ):
        return True
    try:
        faults.check("retrain.publish")
        refresh.publish_snapshot(
            serving_root, record.snapshot, game_model=model, replace=True
        )
    except OSError:
        obs.swallowed_error("retrain.publish")
        return False
    obs.current_run().registry.counter(
        "photon_retrain_published_total",
        "accepted snapshots published into the serving store",
    ).inc()
    return True


DayData = Union["RawDataset", Callable[[], "RawDataset"]]  # noqa: F821


def run_chain(
    estimator,
    days: Sequence[Tuple[str, DayData]],
    validation,
    *,
    initial_model: Optional[GameModel] = None,
    chain_dir: Optional[str] = None,
    serving_root: Optional[str] = None,
    snapshot_prefix: str = "retrain",
    evaluator_specs: Optional[Sequence[str]] = None,
    gate_margin: float = 0.0,
    checkpoint_every: int = 0,
    checkpoint_keep: int = 3,
    index_maps: Optional[Mapping[str, object]] = None,
    dtype=None,
) -> ChainResult:
    """Walk ``days`` (ordered ``(label, dataset-or-thunk)`` pairs), training
    each day warm-started from the last ACCEPTED model with prior-centered
    L2, gating every candidate through :func:`no_degrade_gate`, and
    publishing accepted models into ``serving_root``.

    ``chain_dir`` makes the chain durable: the day ledger persists in
    ``chain-state.json``, accepted models are saved under ``models/`` (when
    ``index_maps`` are given), and each day's CD checkpoints (enabled via
    ``checkpoint_every``) carry the chain position in their manifests. A
    re-invocation over the same ``days`` resumes: decided days are skipped
    (their thunks never load), a day killed mid-CD resumes from its newest
    valid boundary checkpoint, and a torn publish is repaired before any new
    work. Day thunks are only called for undecided days, so resume cost is
    proportional to the remaining work."""
    import jax.numpy as jnp

    from ..io.model_io import load_game_model, save_game_model

    dtype = jnp.float32 if dtype is None else dtype
    specs = list(evaluator_specs or estimator.evaluator_specs or ["RMSE"])

    state = _load_chain_state(chain_dir)
    ledger = [DayRecord(**d) for d in state["days"]]
    rows_touched = int(state.get("rows_touched", 0))
    rows_cumulative = int(state.get("rows_cumulative", 0))
    rows_seen = int(state.get("rows_seen", 0))

    live = initial_model
    if ledger and state.get("live_model_dir") and index_maps is not None:
        # resume: the last accepted model reloads from the chain's own store
        live = load_game_model(
            state["live_model_dir"], index_maps, task=estimator.task
        )

    last_accepted = next((r for r in reversed(ledger) if r.accepted), None)
    if serving_root and last_accepted is not None and live is not None:
        if _ensure_published(serving_root, last_accepted, live):
            if not last_accepted.published:
                last_accepted.published = True
                state["days"] = _ledger_meta(ledger)
                _save_chain_state(chain_dir, state)

    for day_index, (label, data) in enumerate(days):
        if day_index < len(ledger):
            continue  # decided on a previous invocation; ledger is durable
        faults.check("retrain.day")
        raw = data() if callable(data) else data
        resume_snap = None
        mgr = None
        if chain_dir and checkpoint_every:
            mgr = CheckpointManager(
                os.path.join(chain_dir, "checkpoints", f"day-{day_index:04d}"),
                keep_last=checkpoint_keep,
                every=checkpoint_every,
                base_meta={
                    "chain_day": label,
                    "chain_day_index": day_index,
                    "chain_ledger": _ledger_meta(ledger),
                },
            )
            if state.get("in_progress") == label:
                resume_snap = mgr.latest_valid()
                if resume_snap is not None:
                    logger.info(
                        "day %s: resuming mid-day from boundary step %s",
                        label, resume_snap.manifest.get("step"),
                    )
        state["in_progress"] = label
        _save_chain_state(chain_dir, state)

        for cc in estimator.coordinate_configs:
            # prior-centered L2 only once a prior exists; day 0 is plain L2
            cc.regularize_by_prior = live is not None

        with obs.span("retrain.day", day=label):
            boundary_fn = None
            if mgr is not None:
                boundary_fn = lambda _w, st, _m=mgr: _m.on_boundary(st)
            results = estimator.fit(
                raw,
                validation=validation,
                initial_model=live,
                boundary_fn=boundary_fn,
                resume_state=resume_snap,
            )
            day_model = estimator.select_best(results).model
            candidate, touched = merge_models(live, day_model)
            decision = no_degrade_gate(
                candidate, live, validation, specs,
                margin=gate_margin, dtype=dtype,
            )

        _record_decision(decision, day_index)
        rows_seen += int(raw.n_rows)
        rows_touched += int(raw.n_rows)
        rows_cumulative += rows_seen  # a from-scratch daily retrain refits the union

        record = DayRecord(
            day=label,
            index=day_index,
            accepted=decision.accepted,
            reason=decision.reason,
            rows=int(raw.n_rows),
            touched_entities=touched,
            metrics=decision.candidate_metrics,
        )
        if decision.accepted:
            live = candidate
            record.snapshot = f"{snapshot_prefix}-{label}"
            if chain_dir and index_maps is not None:
                model_dir = os.path.join(chain_dir, "models", f"day-{label}")
                save_game_model(model_dir, live, index_maps)
                state["live_model_dir"] = model_dir
            if serving_root:
                record.published = _ensure_published(serving_root, record, live)
        else:
            logger.warning(
                "day %s: candidate refused by the promotion gate (%s); "
                "the previous model keeps serving", label, decision.reason,
            )

        ledger.append(record)
        state["days"] = _ledger_meta(ledger)
        state["in_progress"] = None
        state["rows_touched"] = rows_touched
        state["rows_cumulative"] = rows_cumulative
        state["rows_seen"] = rows_seen
        _save_chain_state(chain_dir, state)

    return ChainResult(
        model=live,
        ledger=ledger,
        rows_touched=rows_touched,
        rows_cumulative=rows_cumulative,
    )
