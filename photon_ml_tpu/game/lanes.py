"""Lane-stacked hyperparameter sweeps: K lambda candidates per solve.

The reference assumes a cluster running tuning trials concurrently
(GameTrainingDriver + the hyperparameter service); on one chip the same
concurrency is a LANE AXIS. Every trial in a batch shares each coordinate's
data residency and compiled solver executable — the per-lane reg weight is a
vector operand, never a static argument — so a K-trial batch costs roughly
one solve that is K lanes wide instead of K sequential solves
(ROADMAP item 5; the done-state is K-batched wall ≪ K x single-trial wall).

``fit_lanes`` mirrors game/descent.py's coordinate-descent loop per lane:
residual composition, warm starts across sweeps, the divergence guard, and
best-model tracking all follow the sequential semantics so lane k of a
K-lane batch reproduces the sequential single-trial fit at the same lambda
(tests/test_sweep_lanes.py pins the parity). Lane isolation is enforced by
the solvers' masked-commit machinery (PR 4): a diverged lane freezes at its
last committed iterate without stalling or perturbing its neighbors; this
module adds a per-lane guard fetch as defense in depth.
"""

from __future__ import annotations

import dataclasses
import logging
import weakref
from typing import Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..analysis.runtime import logged_fetch
from ..models.coefficients import Coefficients
from ..models.game import FixedEffectModel, GameModel, RandomEffectModel
from ..models.glm import model_for_task
from ..optimize import ConvergenceReason
from .coordinate import FixedEffectCoordinate, RandomEffectCoordinate

Array = jax.Array

logger = logging.getLogger("photon_ml_tpu")

_DIVERGED = int(ConvergenceReason.NUMERICAL_DIVERGENCE.value)


def check_lane_composition(estimator, n_lanes: int, distributed: bool = False):
    """Refuse compositions the lane path does not support — delegates to the
    execution planner (plan/planner.py), which owns every ledger-pinned
    composition-legality message."""
    from ..plan import check_lane_composition as _check

    _check(
        estimator.coordinate_configs,
        n_lanes,
        mesh=estimator.mesh,
        n_processes=jax.process_count(),
        distributed=distributed,
        pipeline_depth=estimator.pipeline_depth,
        partial_retrain_locked=tuple(estimator.partial_retrain_locked),
    )


def _lane_model(estimator, cc, coord, coeffs: Array, lane: int):
    """Slice lane ``lane`` out of a coordinate's lane-stacked coefficients
    into an ordinary (FixedEffect|RandomEffect)Model."""
    if cc.is_random_effect:
        ds = coord.dataset
        model = RandomEffectModel(
            random_effect_type=ds.random_effect_type,
            feature_shard=ds.feature_shard,
            task=estimator.task,
            entity_ids=ds.entity_ids,
            coef_indices=ds.blocks.proj_cols,
            coef_values=coeffs[:, :, lane],
        )
        # provenance mark: this model's support layout IS the dataset's
        # block layout (scoring fast path, see coordinate.train)
        object.__setattr__(model, "_support_layout_of", weakref.ref(ds))
        return model
    glm = model_for_task(
        estimator.task, Coefficients(means=coeffs[:, lane], variances=None)
    )
    return FixedEffectModel(model=glm, feature_shard=cc.feature_shard)


def _summarize_reasons(reason_h: np.ndarray) -> np.ndarray:
    """Per-lane ConvergenceReason code from a solve's reason array: [L]
    passes through; entity-stacked [E, L] summarizes each lane as DIVERGED
    if any entity diverged, else the modal code."""
    r = np.asarray(reason_h)
    if r.ndim == 1:
        return r.astype(np.int32)
    out = np.empty(r.shape[1], np.int32)
    for lane in range(r.shape[1]):
        col = r[:, lane]
        if np.any(col == _DIVERGED):
            out[lane] = _DIVERGED
        else:
            vals, cnt = np.unique(col, return_counts=True)
            out[lane] = vals[np.argmax(cnt)]
    return out


def _evaluate_lane(validation, models: Mapping[str, object]):
    """Per-lane validation eval, mirroring descent._evaluate: device-side
    when every metric supports it, host fallback otherwise."""
    acc = None
    for name, model in models.items():
        fn = validation.score_fns.get(name)
        if fn is not None:
            s = fn(model)
            acc = s if acc is None else acc + s
    if acc is not None:
        total_dev = acc + jnp.asarray(validation.offsets, acc.dtype)
        res = validation.suite.evaluate_device(total_dev)
        if res is not None:
            return res
    total = np.asarray(validation.offsets, dtype=np.float64)
    if acc is not None:
        total = total + np.asarray(
            logged_fetch("lanes.validation_scores", acc), dtype=np.float64
        )
    return validation.suite.evaluate(total)


def fit_lanes(
    estimator,
    raw,
    combos: Sequence[Mapping[str, float]],
    validation=None,
    datasets: Optional[Dict[str, object]] = None,
    n_cd_iterations: Optional[int] = None,
) -> List:
    """Train ``len(combos)`` reg-weight configurations as lanes of ONE
    coordinate-descent run; returns one GameResult per lane, in combo order.

    Each lane is an independent trial: zero-initialized, warm-started across
    its own sweeps, guarded and best-tracked separately — only the data
    residency and the compiled kernels are shared. ``trackers['lane']``
    carries the lane index and per-coordinate ConvergenceReason codes so
    tuner trial records surface per-lane solver outcomes."""
    from ..estimators.game_estimator import GameResult

    L = len(combos)
    check_lane_composition(estimator, L)
    if datasets is None:
        datasets = estimator._prepare_datasets(raw)
    validation_ctx = None
    if validation is not None:
        if hasattr(validation, "result"):
            validation = validation.result()
        elif callable(validation):
            validation = validation()
        validation_ctx, _ = estimator._validation_context(validation)

    names = [cc.name for cc in estimator.coordinate_configs]
    ccs = {cc.name: cc for cc in estimator.coordinate_configs}
    coords = {}
    for cc in estimator.coordinate_configs:
        if cc.is_random_effect:
            coords[cc.name] = RandomEffectCoordinate(
                dataset=datasets[cc.name], task=estimator.task, config=cc.config
            )
        else:
            coords[cc.name] = FixedEffectCoordinate(
                dataset=datasets[cc.name],
                task=estimator.task,
                config=cc.config,
                normalization=cc.normalization,
            )
    # per-coordinate per-lane L2 weights: the lambda-lane vector operands
    l2_by_coord = {
        name: np.asarray(
            [
                ccs[name].config.regularization.l2_weight(
                    float(combo.get(name, ccs[name].config.reg_weight))
                )
                for combo in combos
            ],
            dtype=np.float64,
        )
        for name in names
    }

    n = coords[names[0]].n_rows
    dtype = estimator.dtype
    n_iterations = (
        estimator.n_cd_iterations if n_cd_iterations is None else n_cd_iterations
    )

    registry = obs.current_run().registry
    lanes_gauge = registry.gauge(
        "photon_tuning_lanes_in_flight",
        "lambda lanes currently training in a batched sweep",
    )
    frozen_counter = registry.counter(
        "photon_tuning_frozen_lanes_total",
        "lanes frozen by per-lane divergence containment during batched sweeps",
    )
    lanes_gauge.set(L)

    scores: Dict[str, Array] = {}  # name -> committed [n, L]
    coeffs: Dict[str, Array] = {}  # name -> committed lane-stacked weights
    reasons: Dict[str, np.ndarray] = {}  # name -> per-lane reason codes
    summed = jnp.zeros((n, L), dtype)
    evaluations: List[list] = [[] for _ in range(L)]
    best_eval = [None] * L
    best_models: List[Optional[dict]] = [None] * L
    try:
        for it in range(n_iterations):
            for name in names:
                coord = coords[name]
                own = scores.get(name)
                residual = summed - own if own is not None else summed
                with obs.span(
                    "lanes.train",
                    phase="solve",
                    coordinate=name,
                    iteration=it,
                    lanes=L,
                ):
                    W, result = coord.train_lanes(
                        residual,
                        l2_by_coord[name],
                        w0_lanes=coeffs.get(name),
                    )
                    new_scores = coord.score_lanes(W)
                # per-lane guard (defense in depth around the solver's own
                # masked freeze): finite scores AND finite per-lane loss;
                # one fetch carries the flags + the reason codes
                loss_l = result.loss
                if loss_l.ndim > 1:
                    loss_l = jnp.sum(loss_l, axis=0)
                finite = jnp.all(jnp.isfinite(new_scores), axis=0) & jnp.isfinite(
                    loss_l
                )
                finite_h, reason_h = logged_fetch(
                    "lanes.update_guard", (finite, result.reason)
                )
                finite_h = np.asarray(finite_h)
                lane_reasons = _summarize_reasons(reason_h)
                n_bad = int(np.sum(lane_reasons == _DIVERGED)) + int(
                    np.sum(~finite_h & (lane_reasons != _DIVERGED))
                )
                if n_bad:
                    frozen_counter.inc(n_bad)
                if not bool(np.all(finite_h)):
                    # revert the poisoned lanes to their previous committed
                    # state; clean lanes commit untouched (bitwise)
                    ok = jnp.asarray(finite_h)
                    prev_W = coeffs.get(name)
                    prev_scores = own
                    W = jnp.where(
                        ok, W, jnp.zeros_like(W) if prev_W is None else prev_W
                    )
                    new_scores = jnp.where(
                        ok,
                        new_scores,
                        jnp.zeros_like(new_scores)
                        if prev_scores is None
                        else prev_scores,
                    )
                    logger.warning(
                        "lanes iter %d coordinate %s: %d lane(s) frozen "
                        "(non-finite scores/loss); previous state stands",
                        it,
                        name,
                        int(np.sum(~finite_h)),
                    )
                summed = residual + new_scores
                scores[name] = new_scores
                coeffs[name] = W
                reasons[name] = lane_reasons
                if validation_ctx is not None and (
                    estimator.validation_frequency == "COORDINATE"
                    or name == names[-1]
                ):
                    complete = len(coeffs) == len(names)
                    with obs.span(
                        "lanes.eval", phase="eval", iteration=it, coordinate=name
                    ):
                        for lane in range(L):
                            models_l = {
                                nm: _lane_model(
                                    estimator, ccs[nm], coords[nm], coeffs[nm], lane
                                )
                                for nm in coeffs
                            }
                            res = _evaluate_lane(validation_ctx, models_l)
                            evaluations[lane].append((name, res))
                            primary = validation_ctx.suite.primary
                            if complete and (
                                best_eval[lane] is None
                                or primary.better(
                                    res.primary_metric,
                                    best_eval[lane].primary_metric,
                                )
                            ):
                                best_eval[lane] = res
                                best_models[lane] = models_l
            obs.sample_memory(registry)
    finally:
        lanes_gauge.set(0)

    results = []
    for lane in range(L):
        if best_eval[lane] is not None:
            models_l = best_models[lane]
        else:
            models_l = {
                nm: _lane_model(estimator, ccs[nm], coords[nm], coeffs[nm], lane)
                for nm in names
            }
        results.append(
            GameResult(
                model=GameModel(models=models_l, task=estimator.task),
                config=dict(combos[lane]),
                evaluation=best_eval[lane],
                trackers={
                    "lane": {
                        "index": lane,
                        "n_lanes": L,
                        "reasons": {
                            nm: int(reasons[nm][lane]) for nm in reasons
                        },
                    }
                },
            )
        )
    return results
