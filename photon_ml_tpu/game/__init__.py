from .coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    ModelCoordinate,
    RandomEffectCoordinate,
)
from .data import (
    EntityBlocks,
    FixedEffectDataset,
    RandomEffectDataset,
    build_fixed_effect_dataset,
    build_random_effect_dataset,
)
from .descent import CoordinateDescent, CoordinateDescentResult, ValidationContext
from .problem import GLMOptimizationConfig, GLMProblem
from .sampling import down_sample

__all__ = [
    "Coordinate",
    "FixedEffectCoordinate",
    "RandomEffectCoordinate",
    "ModelCoordinate",
    "FixedEffectDataset",
    "RandomEffectDataset",
    "EntityBlocks",
    "build_fixed_effect_dataset",
    "build_random_effect_dataset",
    "CoordinateDescent",
    "CoordinateDescentResult",
    "ValidationContext",
    "GLMOptimizationConfig",
    "GLMProblem",
    "down_sample",
]
