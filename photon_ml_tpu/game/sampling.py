"""Down-samplers for fixed-effect training.

Reference: photon-lib .../sampling/ — BinaryClassificationDownSampler.scala:46-69
(keep all positives; keep negatives with probability r and rescale their weight
by 1/r) and DefaultDownSampler.scala (uniform row sample), selected per task in
DownSamplerHelper.scala:26-40.

Down-sampling only affects the *training* batch; scoring always sees all rows.
Realized as a weight transform (dropped rows get weight 0) so batch shapes stay
static for jit; determinism comes from a counter-based ``jax.random`` key,
mirroring the reference's per-partition deterministic seeds (recomputability,
SURVEY §5). Runs entirely on device — no host round-trip per train call.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..ops.features import LabeledBatch
from ..ops.losses import POSITIVE_RESPONSE_THRESHOLD

_BINARY_TASKS = {"logistic_regression", "smoothed_hinge_loss_linear_svm"}


def is_binary_task(task: str) -> bool:
    return task.lower() in _BINARY_TASKS


def down_sample(
    batch: LabeledBatch, task: str, rate: float, seed: int = 0
) -> LabeledBatch:
    """Return a batch with down-sampled weights (no-op when rate >= 1)."""
    if rate >= 1.0:
        return batch
    if not (0.0 < rate < 1.0):
        raise ValueError(f"down-sampling rate must be in (0, 1): {rate}")
    keep = (
        jax.random.uniform(jax.random.PRNGKey(seed), (batch.n_rows,)) < rate
    )
    labels = batch.labels
    weights = batch.weights
    if is_binary_task(task):
        pos = labels > POSITIVE_RESPONSE_THRESHOLD
        new_w = jnp.where(pos, weights, jnp.where(keep, weights / rate, 0.0))
    else:
        new_w = jnp.where(keep, weights, 0.0)
    return dataclasses.replace(batch, weights=new_w.astype(batch.weights.dtype))
