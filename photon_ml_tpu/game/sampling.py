"""Down-samplers for fixed-effect training.

Reference: photon-lib .../sampling/ — BinaryClassificationDownSampler.scala:46-69
(keep all positives; keep negatives with probability r and rescale their weight
by 1/r) and DefaultDownSampler.scala (uniform row sample), selected per task in
DownSamplerHelper.scala:26-40.

Down-sampling only affects the *training* batch; scoring always sees all rows.
Realized as a weight transform (dropped rows get weight 0) so batch shapes stay
static for jit; determinism comes from a seeded ``numpy`` generator, mirroring
the reference's per-partition deterministic seeds (recomputability, SURVEY §5).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..ops.features import LabeledBatch
from ..ops.losses import POSITIVE_RESPONSE_THRESHOLD

_BINARY_TASKS = {"logistic_regression", "smoothed_hinge_loss_linear_svm"}


def is_binary_task(task: str) -> bool:
    return task.lower() in _BINARY_TASKS


def down_sample(
    batch: LabeledBatch, task: str, rate: float, seed: int = 0
) -> LabeledBatch:
    """Return a batch with down-sampled weights (no-op when rate >= 1)."""
    if rate >= 1.0:
        return batch
    if not (0.0 < rate < 1.0):
        raise ValueError(f"down-sampling rate must be in (0, 1): {rate}")
    rng = np.random.default_rng(seed)
    n = batch.n_rows
    keep = rng.uniform(size=n) < rate
    labels = np.asarray(batch.labels)
    weights = np.asarray(batch.weights)
    if is_binary_task(task):
        pos = labels > POSITIVE_RESPONSE_THRESHOLD
        new_w = np.where(pos, weights, np.where(keep, weights / rate, 0.0))
    else:
        new_w = np.where(keep, weights, 0.0)
    import dataclasses

    return dataclasses.replace(batch, weights=jnp.asarray(new_w, batch.weights.dtype))
