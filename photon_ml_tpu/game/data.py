"""GAME datasets: fixed-effect batches and entity-blocked random-effect data.

Reference: photon-api .../data/ — FixedEffectDataset.scala,
RandomEffectDataset.scala:51-600 (build pipeline: key-by-entity -> subspace
projectors -> project -> reservoir-cap -> passive split), LocalDataset.scala,
RandomEffectDatasetPartitioner.scala (entity sharding), and the
LinearSubspaceProjector (photon-api .../projector/LinearSubspaceProjector.scala:37-90).

TPU re-design (SURVEY.md §7.3): instead of an RDD of per-entity iterables,
a random-effect dataset is a set of *dense entity blocks*:

    features  f[E, K, S]   per-entity rows projected into the entity's
    labels    f[E, K]      feature subspace (S = max subspace dim,
    weights   f[E, K]      K = max (capped) rows per entity; zero-padded)
    offsets   f[E, K]
    proj_cols i32[E, S]    local dim -> global feature column (-1 pad)
    active_rows i32[E, K]  global sample row of each block cell (-1 pad)

Per-entity local solves then become one vmapped masked solver call — the
MXU-friendly replacement for the reference's per-entity sequential L-BFGS
fan-out (RandomEffectCoordinate.scala:273-329). Entity order doubles as the
sharding axis: shard dim 0 over the mesh and each device owns a contiguous
entity range (the bin-packing partitioner's role, P5).

Active/passive split parity: entities with more than ``active_cap`` samples
train on a deterministic hash-priority reservoir of ``active_cap`` rows with
weights rescaled by count/cap (RandomEffectDataset.scala:403-506,
MinHeapWithFixedCapacity semantics); the remaining *passive* rows are scored
but never trained on. Entities with fewer than ``active_lower_bound`` samples
are dropped from training entirely (scored as zeros until some other
coordinate explains them).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.features import FeatureMatrix, LabeledBatch
from ..io.data import RawDataset

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class HostRowBatch:
    """Host-resident row-major fixed-effect training data for the streamed
    (out-of-core) path: the row axis slices trivially for both supported
    layouts (dense ``[n, d]`` and ELL ``idx/val [n, F]``), which is what lets
    game/fe_streaming.py stage budget-sized row windows through the chip.
    COO (column-sorted) and tiled (mesh) layouts are NOT row-sliceable and
    are refused upstream (GameEstimator)."""

    dim: int
    labels: np.ndarray  # f[n] solve dtype
    offsets: np.ndarray  # f[n]
    weights: np.ndarray  # f[n]
    dense: Optional[np.ndarray] = None  # f[n, d] feature dtype
    ell_idx: Optional[np.ndarray] = None  # i32[n, F]
    ell_val: Optional[np.ndarray] = None  # f[n, F] feature dtype

    @property
    def n_rows(self) -> int:
        return int(self.labels.shape[0])

    @property
    def layout(self) -> str:
        return "dense" if self.dense is not None else "ell"

    def feature_row_nbytes(self) -> int:
        if self.dense is not None:
            return self.dim * self.dense.dtype.itemsize
        return self.ell_idx.shape[1] * (
            self.ell_val.dtype.itemsize + self.ell_idx.dtype.itemsize
        )


@dataclasses.dataclass(frozen=True)
class FixedEffectDataset:
    """All samples' features from one shard (FixedEffectDataset.scala:26-152).

    ``true_dim`` / ``true_n_rows`` are the UNPADDED shard dimension and sample
    count: mesh-tiled layouts pad both to device multiples, but models and
    exchanged score vectors live in the true space (trim/pad happens at the
    coordinate boundary).

    Out-of-core mode (game/fe_streaming.py): when ``streamed`` is set,
    ``batch`` is None and ``host_batch`` holds the row-major host arrays;
    training/scoring pipeline double-buffered row slices through the chip
    under ``hbm_budget_bytes`` — the FE twin of the streamed random effects
    (reference: DISK_ONLY spill + treeAggregate,
    CoordinateDescent.scala:262,404 / AvroDataReader.scala:165-209)."""

    coordinate_id: str
    feature_shard: str
    batch: Optional[LabeledBatch]
    true_dim: Optional[int] = None
    true_n_rows: Optional[int] = None
    host_batch: Optional[HostRowBatch] = None
    streamed: bool = False
    hbm_budget_bytes: Optional[int] = None
    # streamed + mesh/multi-process: host_batch holds THIS host's row slice;
    # the mesh is kept so scoring can reassemble the global row-sharded
    # score vector (n_rows stays the LOCAL true row count)
    mesh: Optional[object] = None

    @property
    def n_rows(self) -> int:
        if self.true_n_rows is not None:
            return self.true_n_rows
        return self.batch.n_rows if self.batch is not None else self.host_batch.n_rows

    @property
    def dim(self) -> int:
        if self.true_dim is not None:
            return self.true_dim
        return self.batch.dim if self.batch is not None else self.host_batch.dim


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EntityBlocks:
    """Device-side entity-blocked training data (see module docstring)."""

    features: Array  # f[E, K, S]
    labels: Array  # f[E, K]
    offsets: Array  # f[E, K] (base offsets only; residuals added at train time)
    weights: Array  # f[E, K]; 0 = padding
    proj_cols: Array  # i32[E, S]; -1 = padding
    active_rows: Array  # i32[E, K]; -1 = padding

    @property
    def num_entities(self) -> int:
        return self.features.shape[0]

    @property
    def rows_per_entity(self) -> int:
        return self.features.shape[1]

    @property
    def subspace_dim(self) -> int:
        return self.features.shape[2]


@dataclasses.dataclass(frozen=True)
class RandomEffectDataset:
    """Entity-blocked random-effect dataset + full-row scoring arrays."""

    coordinate_id: str
    feature_shard: str
    random_effect_type: str
    entity_ids: np.ndarray  # object[E], order = block row
    blocks: EntityBlocks
    # scoring representation for ALL rows of the full dataset (ELL, global space)
    row_entity: Array  # i32[n] block row per sample, -1 = entity dropped/unseen
    ell_idx: Array  # i32[n, F]
    ell_val: Array  # f[n, F]
    passive_rows: np.ndarray  # i64[*] rows not in any active block (info only)
    # host-side per-entity stats (entities are size-sorted descending), used
    # to bucket the vmapped solver by block size so small entities don't pay
    # the padding of the largest (the TPU analogue of the reference's
    # size-aware partitioning, RandomEffectDatasetPartitioner.scala:117-180)
    entity_counts: Optional[np.ndarray] = None  # i64[E] active rows per entity
    entity_subspace_dims: Optional[np.ndarray] = None  # i64[E] real S per entity
    # multi-process: host copy of blocks.proj_cols (the device array is
    # entity-sharded across processes, so not host-addressable); model
    # projection / warm-start layout checks read this instead
    host_proj_cols: Optional[np.ndarray] = None
    # out-of-core mode (game/streaming.py): blocks hold HOST numpy arrays and
    # training/scoring stream entity slices through the chip under this HBM
    # budget — the product path for models bigger than device memory
    # (reference: DISK_ONLY spill, CoordinateDescent.scala:262,404)
    streamed: bool = False
    hbm_budget_bytes: Optional[int] = None
    # streamed + multi-process (game/data_mp.py): blocks hold only THIS
    # host's contiguous [lo, hi) block-row range; entity-level host tables
    # (entity_ids / counts / host_proj_cols) stay GLOBAL. ``mesh`` is kept so
    # scoring can reassemble the global row-sharded score vector.
    entity_shard_range: Optional[Tuple[int, int]] = None
    mesh: Optional[object] = None

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)


@dataclasses.dataclass(frozen=True)
class _EntityPlan:
    """The deterministic entity layout every process must agree on: which
    entities train, their block order (size-sorted descending, stable), the
    padded block count, the per-entity active cap, and weight rescales.
    Computed from the (possibly cross-process-merged) per-entity counts alone,
    so identical inputs give identical plans on every host."""

    kept_entities: np.ndarray  # i64[E_real] indices into uniq, size-sorted
    old_to_block: np.ndarray  # i64[len(uniq)] -> block row or -1
    E_real: int
    E: int  # padded block count
    cap: int
    K: int  # block row capacity
    weight_scale: np.ndarray  # f8[E] count/cap rescale for capped entities


def _entity_plan(
    counts: np.ndarray,
    active_lower_bound: int,
    active_cap: Optional[int],
    pad_entities_to_multiple: int,
) -> _EntityPlan:
    kept_mask = counts >= active_lower_bound
    kept_entities = np.nonzero(kept_mask)[0]
    # order entities by descending size: natural bin-packing order for sharding
    kept_entities = kept_entities[np.argsort(-counts[kept_entities], kind="stable")]
    E_real = len(kept_entities)
    E = max(
        ((E_real + pad_entities_to_multiple - 1) // pad_entities_to_multiple)
        * pad_entities_to_multiple,
        pad_entities_to_multiple,
    )
    old_to_block = np.full(len(counts), -1, dtype=np.int64)
    old_to_block[kept_entities] = np.arange(E_real)
    cap = active_cap if active_cap is not None else int(counts.max() if len(counts) else 1)
    K = int(min(int(counts[kept_entities].max()) if E_real else 1, cap)) or 1
    weight_scale = np.ones(E)
    if E_real:
        counts_kept = counts[kept_entities].astype(np.float64)
        weight_scale[:E_real] = np.where(counts_kept > cap, counts_kept / cap, 1.0)
    return _EntityPlan(
        kept_entities=kept_entities,
        old_to_block=old_to_block,
        E_real=E_real,
        E=E,
        cap=cap,
        K=K,
        weight_scale=weight_scale,
    )


def _hash64(a: np.ndarray, seed: int) -> np.ndarray:
    """Deterministic splitmix64-style mix of row ids (the reservoir priority;
    plays the role of byteswap64(hash ^ uniqueId), RandomEffectDataset.scala:483-491)."""
    x = a.astype(np.uint64) + np.uint64((seed * 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


def _rows_to_ell(
    rows: np.ndarray, cols: np.ndarray, vals: np.ndarray, n: int,
    width: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """COO -> per-row padded (idx, val) with idx=0/val=0 padding. Vectorized.
    ``width`` overrides the ELL width (multi-process: the GLOBAL max row nnz,
    so per-host shapes agree)."""
    order = np.lexsort((cols, rows))
    r, c, v = rows[order], cols[order], vals[order]
    counts = np.bincount(r, minlength=n)
    F = width if width is not None else max(int(counts.max()) if n else 1, 1)
    idx = np.zeros((n, F), dtype=np.int32)
    val = np.zeros((n, F), dtype=np.float64)
    if len(r):
        starts = np.cumsum(np.concatenate([[0], counts[:-1]]))
        within = np.arange(len(r)) - starts[r]
        idx[r, within] = c
        val[r, within] = v
    return idx, val


def build_fixed_effect_dataset(
    raw: RawDataset,
    coordinate_id: str,
    feature_shard: str,
    dtype=jnp.float32,
    layout: str = "auto",
    mesh=None,
    feature_dtype=None,
    hbm_budget_bytes: Optional[int] = None,
) -> FixedEffectDataset:
    """``hbm_budget_bytes``: when set and the resident device batch would
    exceed this many bytes, the dataset is built STREAMED — features stay in
    host numpy (dense or ELL rows) and training/scoring stream row slices
    (game/fe_streaming.py). Under a mesh / multi-process topology ``raw`` is
    this host's row slice, so the budget governs the PER-HOST stream (the
    planner's streamed+sharded routing); the coo/tiled layouts are refused by
    the execution planner before this point."""
    d = raw.shard_dims[feature_shard]
    if hbm_budget_bytes is not None:
        eff_layout = layout
        if eff_layout == "auto":
            # same rule as RawDataset.to_batch's auto resolution
            eff_layout = "dense" if d <= 4096 else "ell"
        if eff_layout not in ("dense", "ell"):
            raise ValueError(
                f"coordinate {coordinate_id}: hbm_budget_mb on a fixed effect "
                f"requires a row-sliceable layout (auto|dense|ell), got "
                f"layout={layout!r}"
            )
        from .fe_streaming import estimate_fe_batch_bytes

        fdt = np.dtype(jnp.zeros((), feature_dtype or dtype).dtype)
        sdt = np.dtype(jnp.zeros((), dtype).dtype)
        rows, cols, vals = raw.shard_coo[feature_shard]
        n = raw.n_rows
        if eff_layout == "ell":
            counts = np.bincount(rows, minlength=n) if n else np.zeros(0, np.int64)
            width = max(int(counts.max()) if n else 1, 1)
        else:
            width = 0
        est = estimate_fe_batch_bytes(
            n, d, eff_layout, ell_width=width,
            feature_itemsize=fdt.itemsize, scalar_itemsize=sdt.itemsize,
        )
        if est > hbm_budget_bytes:
            if eff_layout == "dense":
                dense = np.zeros((n, d), np.float64)
                np.add.at(dense, (rows, cols), vals)
                host = HostRowBatch(
                    dim=d,
                    labels=raw.labels.astype(sdt),
                    offsets=raw.offsets.astype(sdt),
                    weights=raw.weights.astype(sdt),
                    dense=dense.astype(fdt),
                )
            else:
                ell_idx, ell_val = _rows_to_ell(rows, cols, vals, n, width=width)
                host = HostRowBatch(
                    dim=d,
                    labels=raw.labels.astype(sdt),
                    offsets=raw.offsets.astype(sdt),
                    weights=raw.weights.astype(sdt),
                    ell_idx=ell_idx,
                    ell_val=ell_val.astype(fdt),
                )
            # multi-process: the coordinate's row space is the padded GLOBAL
            # row space (scores/residuals stay [N_global], matching the
            # resident multi-process batch); host_batch keeps the LOCAL rows
            n_true = n
            if mesh is not None and jax.process_count() > 1:
                from ..parallel.mesh import DATA_AXIS

                n_proc = jax.process_count()
                chunk = max(mesh.shape[DATA_AXIS] // n_proc, 1)
                n_true = (-(-n // chunk) * chunk) * n_proc
            return FixedEffectDataset(
                coordinate_id=coordinate_id,
                feature_shard=feature_shard,
                batch=None,
                true_dim=d,
                true_n_rows=n_true,
                host_batch=host,
                streamed=True,
                hbm_budget_bytes=hbm_budget_bytes,
                mesh=mesh,
            )
    return FixedEffectDataset(
        coordinate_id=coordinate_id,
        feature_shard=feature_shard,
        batch=raw.to_batch(
            feature_shard, dtype=dtype, layout=layout, mesh=mesh,
            feature_dtype=feature_dtype,
        ),
        true_dim=raw.shard_dims[feature_shard],
        true_n_rows=raw.n_rows,
    )


def build_fixed_effect_dataset_from_disk(
    path,
    shard_configs,
    coordinate_id: str,
    feature_shard: str,
    hbm_budget_bytes: int,
    *,
    index_maps=None,
    id_tag_columns=(),
    response_column: str = "label",
    columns=None,
    reader_schema=None,
    dtype=jnp.float32,
    layout: str = "auto",
    feature_dtype=None,
    workers=None,
    pool=None,
    ingest_budget_bytes: Optional[int] = None,
    prefetch_depth: int = 2,
):
    """Disk → :class:`HostRowBatch` without ever materializing the full
    ``RawDataset``: part files decode across the ingest worker pool
    (``io/data.read_avro_part_pieces``) and each part's rows are written
    straight into the preallocated host feature planes, so rows go
    disk → decode → stage → chip with peak record residency of one part
    plus the decode pipeline. Returns ``(dataset, index_maps)`` with the
    dataset ALWAYS in streamed form (``game/fe_streaming.py`` row slices
    under ``hbm_budget_bytes``) — this path exists to feed the streamed
    fixed effect; use ``read_avro_dataset_chunked`` +
    :func:`build_fixed_effect_dataset` when a resident batch is wanted.

    Bitwise parity with the in-memory builder: parts arrive in file order
    with contiguous ascending row blocks, so the per-part
    ``np.add.at`` / ``_rows_to_ell(width=global)`` fills produce arrays
    identical to the global constructions on the concatenated COO, and
    scalar planes are filled elementwise (``astype`` commutes with
    concatenation). The dense layout truly streams (one part's COO alive
    at a time); the ELL layout buffers each part's compact COO arrays
    until the global row-nnz width is known — O(nnz) host memory, still
    never the record dicts or a concatenated ``RawDataset``.

    ``workers``/``pool``/``ingest_budget_bytes``/``prefetch_depth`` pass
    through to the decode pool; the pool's RSS backpressure
    (``ingest_budget_bytes``, compressed bytes in flight) composes with the
    ``hbm_budget_bytes`` slice accounting the streamed objective applies
    on the device side."""
    from .. import obs
    from ..io.avro import count_avro_rows, list_avro_parts
    from ..io.data import read_avro_part_pieces, scan_index_maps_pipelined

    paths = [path] if isinstance(path, str) else list(path)
    parts = [part for p in paths for part in list_avro_parts(p)]
    if not parts:
        raise ValueError(f"no .avro part files under {paths!r}")

    with obs.span("ingest.disk_slice", n_parts=len(parts)):
        if index_maps is None:
            index_maps = scan_index_maps_pipelined(
                parts, shard_configs, reader_schema,
                prefetch_depth=prefetch_depth, workers=workers, pool=pool,
                ingest_budget_bytes=ingest_budget_bytes,
            )
        d = len(index_maps[feature_shard])
        eff_layout = layout
        if eff_layout == "auto":
            # same rule as RawDataset.to_batch's auto resolution
            eff_layout = "dense" if d <= 4096 else "ell"
        if eff_layout not in ("dense", "ell"):
            raise ValueError(
                f"coordinate {coordinate_id}: the disk-to-slice ingest path "
                f"requires a row-sliceable layout (auto|dense|ell), got "
                f"layout={layout!r}"
            )
        # header-only row counts: block counts, no decompression
        n = sum(count_avro_rows(part) for part in parts)
        fdt = np.dtype(jnp.zeros((), feature_dtype or dtype).dtype)
        sdt = np.dtype(jnp.zeros((), dtype).dtype)

        labels = np.empty(n, sdt)
        offsets = np.empty(n, sdt)
        weights = np.empty(n, sdt)
        row0 = 0
        if eff_layout == "dense":
            # f64 accumulator, cast once at the end — identical to the
            # in-memory streamed branch's global np.add.at + astype
            dense = np.zeros((n, d), np.float64)

            def _drain(_i, piece) -> None:
                nonlocal row0
                np_rows = piece.n_rows
                labels[row0:row0 + np_rows] = piece.labels.astype(sdt)
                offsets[row0:row0 + np_rows] = piece.offsets.astype(sdt)
                weights[row0:row0 + np_rows] = piece.weights.astype(sdt)
                rows, cols, vals = piece.shard_coo[feature_shard]
                np.add.at(dense[row0:row0 + np_rows], (rows, cols), vals)
                row0 += np_rows

            read_avro_part_pieces(
                paths, shard_configs, _drain, index_maps,
                id_tag_columns=id_tag_columns,
                response_column=response_column, columns=columns,
                reader_schema=reader_schema, prefetch_depth=prefetch_depth,
                workers=workers, pool=pool,
                ingest_budget_bytes=ingest_budget_bytes,
            )
            host = HostRowBatch(
                dim=d, labels=labels, offsets=offsets, weights=weights,
                dense=dense.astype(fdt),
            )
        else:
            # ELL needs the GLOBAL max row nnz before allocation: buffer
            # each part's compact COO (O(nnz)), then fill per part with the
            # shared width — bit-identical to the global _rows_to_ell
            # because row blocks are contiguous and ascending
            coo_parts = []

            def _buffer(_i, piece) -> None:
                nonlocal row0
                np_rows = piece.n_rows
                labels[row0:row0 + np_rows] = piece.labels.astype(sdt)
                offsets[row0:row0 + np_rows] = piece.offsets.astype(sdt)
                weights[row0:row0 + np_rows] = piece.weights.astype(sdt)
                coo_parts.append((np_rows, piece.shard_coo[feature_shard]))
                row0 += np_rows

            read_avro_part_pieces(
                paths, shard_configs, _buffer, index_maps,
                id_tag_columns=id_tag_columns,
                response_column=response_column, columns=columns,
                reader_schema=reader_schema, prefetch_depth=prefetch_depth,
                workers=workers, pool=pool,
                ingest_budget_bytes=ingest_budget_bytes,
            )
            width = 1
            for np_rows, (rows, _c, _v) in coo_parts:
                counts = np.bincount(rows, minlength=np_rows)
                if np_rows:
                    width = max(width, int(counts.max()))
            ell_idx = np.zeros((n, width), np.int32)
            ell_val = np.zeros((n, width), np.float64)
            r0 = 0
            for np_rows, (rows, cols, vals) in coo_parts:
                idx_p, val_p = _rows_to_ell(rows, cols, vals, np_rows, width=width)
                ell_idx[r0:r0 + np_rows] = idx_p
                ell_val[r0:r0 + np_rows] = val_p
                r0 += np_rows
            del coo_parts
            host = HostRowBatch(
                dim=d, labels=labels, offsets=offsets, weights=weights,
                ell_idx=ell_idx, ell_val=ell_val.astype(fdt),
            )

        reg = obs.current_run().registry
        reg.counter(
            "photon_ingest_parts_total",
            "part files decoded by the chunked reader",
        ).labels(mode="disk_slice").inc(len(parts))
        reg.counter(
            "photon_ingest_rows_total", "rows produced by the chunked reader"
        ).labels(mode="disk_slice").inc(n)

    dataset = FixedEffectDataset(
        coordinate_id=coordinate_id,
        feature_shard=feature_shard,
        batch=None,
        true_dim=d,
        true_n_rows=n,
        host_batch=host,
        streamed=True,
        hbm_budget_bytes=hbm_budget_bytes,
    )
    return dataset, dict(index_maps)


def _pearson_keep_mask(
    feats: np.ndarray,  # f8[E, K, S] zero-padded per-entity features
    labels: np.ndarray,  # f8[E, K]
    row_mask: np.ndarray,  # bool[E, K] filled (active) slots
    proj_cols: np.ndarray,  # i32[E, S], -1 = padding
    ratio: float,
) -> np.ndarray:
    """Per-entity Pearson-correlation feature selection, vectorized over all
    entities at once.

    Reference: LocalDataset.filterFeaturesByPearsonCorrelationScore
    (photon-api .../data/LocalDataset.scala:103-130) keeps, per entity, the
    ceil(ratio * n_rows) features with the largest |Pearson(feature, label)|
    (stable one-pass scores, :180-258), where a constant feature with value
    1.0 — the intercept — scores 1.0 (first such column only) and other
    constant features score 0. Selection only applies when it would shrink
    the entity's active feature set.

    Returns bool[E, S]: True = keep the column.
    """
    E, K, S = feats.shape
    EPS = np.finfo(np.float64).eps
    n_e = row_mask.sum(axis=1)  # rows per entity
    n_safe = np.maximum(n_e, 1).astype(np.float64)

    mean_y = (labels * row_mask).sum(axis=1) / n_safe
    dy = (labels - mean_y[:, None]) * row_mask
    std_y = np.sqrt((dy * dy).sum(axis=1))

    mean_x = (feats * row_mask[:, :, None]).sum(axis=1) / n_safe[:, None]
    dx = (feats - mean_x[:, None, :]) * row_mask[:, :, None]
    cov = np.einsum("eks,ek->es", dx, dy)
    std_x = np.sqrt((dx * dx).sum(axis=1))  # sum over K -> [E, S]
    score = cov / (std_y[:, None] * std_x + EPS)

    # constant columns: intercept (value 1.0, first occurrence) scores 1.0,
    # any other constant scores 0 (LocalDataset.scala:225-236)
    const = std_x < np.sqrt(n_safe)[:, None] * EPS
    cand = const & (np.abs(mean_x - 1.0) < 1e-12) & (proj_cols >= 0)
    first_one = np.zeros_like(cand)
    has = cand.any(axis=1)
    first_one[np.nonzero(has)[0], np.argmax(cand, axis=1)[has]] = True
    score = np.where(const, np.where(first_one, 1.0, 0.0), score)

    n_active = (proj_cols >= 0).sum(axis=1)
    k_keep = np.ceil(ratio * n_e).astype(np.int64)
    k_keep = np.where(k_keep < n_active, k_keep, n_active)

    # rank columns by descending |score| (stable: earlier column wins ties).
    # |score| is quantized to a 1e-12 grid first: host-numpy and XLA f64
    # reductions can disagree in the last ulps (~1e-13), which would turn an
    # exact host tie into a device near-tie and flip which tied column is
    # kept — the grid collapses both onto the same key so the column-order
    # tie-break decides identically on both paths (determinism-for-recovery,
    # SURVEY §5 A2). Residual window: a score ~1 ulp from a grid midpoint can
    # still round apart — vanishing, not provably zero.
    absc = np.where(proj_cols >= 0, np.round(np.abs(score), 12), -1.0)
    order = np.argsort(-absc, axis=1, kind="stable")
    rank = np.empty((E, S), dtype=np.int64)
    np.put_along_axis(rank, order, np.broadcast_to(np.arange(S), (E, S)), axis=1)
    return (rank < k_keep[:, None]) & (proj_cols >= 0)


def build_random_effect_dataset(
    raw: RawDataset,
    coordinate_id: str,
    feature_shard: str,
    random_effect_type: str,
    active_cap: Optional[int] = None,
    active_lower_bound: int = 1,
    seed: int = 0,
    dtype=jnp.float32,
    pad_entities_to_multiple: int = 1,
    features_to_samples_ratio: Optional[float] = None,
    feature_dtype=None,
    hbm_budget_bytes: Optional[int] = None,
) -> RandomEffectDataset:
    """Host-side dataset build (the one-time "shuffle" of SURVEY.md §2.1 P13).

    active_cap: numActiveDataPointsUpperBound — reservoir-cap per entity with
    count/cap weight rescale. active_lower_bound: numActiveDataPointsLowerBound
    — entities with fewer samples are not trained.
    features_to_samples_ratio: numFeaturesToSamplesRatioUpperBound — per
    entity, keep only the ceil(ratio * n_rows) features with the largest
    |Pearson(feature, label)| (RandomEffectDataset.scala:553-565).
    feature_dtype: optional narrower storage type (e.g. bfloat16) for the
    entity-block FEATURES and the ELL scoring values only — labels, offsets,
    weights and all solver state stay ``dtype``; objective products promote
    on the fly (halves the HBM traffic of the RE solve, which dominates the
    GLMix sweep).
    hbm_budget_bytes: when set and the entity blocks would exceed this many
    device bytes, the dataset is built STREAMED: blocks stay in host numpy
    and training/scoring pipeline double-buffered entity slices through the
    chip (game/streaming.py) — the out-of-core path for models bigger than
    HBM.
    """
    n = raw.n_rows
    ids = raw.id_tags[random_effect_type]
    rows, cols, vals = raw.shard_coo[feature_shard]

    # --- group rows by entity ------------------------------------------------
    # unique in the ids' native dtype (string conversion of millions of int
    # ids costs more than the whole rest of the build); entity ids are
    # stringified only in the E-sized entity_ids output below
    ids_arr = np.asarray(ids)
    if ids_arr.dtype == object:
        ids_arr = ids_arr.astype(str)
    uniq, inv = np.unique(ids_arr, return_inverse=True)
    counts = np.bincount(inv, minlength=len(uniq))

    plan = _entity_plan(
        counts, active_lower_bound, active_cap, pad_entities_to_multiple
    )
    kept_entities, old_to_block = plan.kept_entities, plan.old_to_block
    E_real, E, cap, K = plan.E_real, plan.E, plan.cap, plan.K

    # --- per-entity active selection (deterministic reservoir) ---------------
    row_ids = np.arange(n, dtype=np.int64)
    priority = _hash64(row_ids, seed)
    # sort rows by (entity, priority): active set = first K rows of each group
    entity_of_row = old_to_block[inv]
    order = np.lexsort((priority, entity_of_row))
    sorted_rows = row_ids[order]
    sorted_entity = entity_of_row[order]
    # rank within entity group
    if E_real:
        starts = np.searchsorted(sorted_entity, np.arange(E_real))
        rank = np.arange(n) - starts[np.clip(sorted_entity, 0, E_real - 1)]
        is_active = (sorted_entity >= 0) & (rank < K)
    else:
        # every entity fell below active_lower_bound: empty (padded) blocks
        rank = np.zeros(n, dtype=np.int64)
        is_active = np.zeros(n, dtype=bool)

    active_rows_np = np.full((E, K), -1, dtype=np.int64)
    weight_scale = plan.weight_scale
    sel = np.nonzero(is_active)[0]
    active_rows_np[sorted_entity[sel], rank[sel]] = sorted_rows[sel]

    passive = sorted_rows[~is_active & (sorted_entity >= 0)]

    # --- ELL features for all rows (scoring path) ----------------------------
    ell_idx_np, ell_val_np = _rows_to_ell(rows, cols, vals, n)

    # --- per-entity subspace projection + dense blocks, fully vectorized -----
    # (reference pipeline: RandomEffectDataset.generateLinearSubspaceProjectors
    # + project, RandomEffectDataset.scala:255-360; the reference shuffled
    # per-entity iterables through Spark — here it is one sorted/segmented
    # numpy pass over the active nnz, no per-entity Python loop, so millions
    # of entities build in seconds.)
    ae = sorted_entity[sel]  # block row per active sample        [A]
    ak = rank[sel]  # slot within block                           [A]
    ar = sorted_rows[sel]  # global sample row                    [A]

    labels_b = np.zeros((E, K))
    offsets_b = np.zeros((E, K))
    weights_b = np.zeros((E, K))
    labels_b[ae, ak] = raw.labels[ar]
    offsets_b[ae, ak] = raw.offsets[ar]
    weights_b[ae, ak] = raw.weights[ar] * weight_scale[ae]

    d_shard = raw.shard_dims[feature_shard]
    fi = ell_idx_np[ar]  # [A, F] global cols of active rows
    fv = ell_val_np[ar]  # [A, F]
    nz = fv != 0.0
    # unique (entity, col) pairs, entity-major and col-ascending: exactly the
    # per-entity sorted active-index union of LinearSubspaceProjector.scala:37-90
    keys = ae[:, None].astype(np.int64) * d_shard + fi  # [A, F]
    uniq_keys = np.unique(keys[nz])
    ent_of_key = (uniq_keys // d_shard).astype(np.int64)
    col_of_key = (uniq_keys % d_shard).astype(np.int32)
    per_entity_s = np.bincount(ent_of_key, minlength=E)
    S = max(int(per_entity_s.max()) if len(uniq_keys) else 1, 1)
    key_starts = np.concatenate([[0], np.cumsum(per_entity_s)[:-1]])
    pos_within = np.arange(len(uniq_keys)) - key_starts[ent_of_key]
    proj_cols_np = np.full((E, S), -1, dtype=np.int32)
    proj_cols_np[ent_of_key, pos_within] = col_of_key

    feats = np.zeros((E, K, S), dtype=np.float64)
    aa, ff = np.nonzero(nz)  # active nnz coordinates (row-major, like the
    # assignment order of the loop implementation)
    loc = np.searchsorted(uniq_keys, keys[aa, ff]) - key_starts[ae[aa]]
    feats[ae[aa], ak[aa], loc] = fv[aa, ff]

    if features_to_samples_ratio is not None:
        keep = _pearson_keep_mask(
            feats, labels_b, active_rows_np >= 0, proj_cols_np,
            features_to_samples_ratio,
        )
        # compact kept columns to the front (stable: column order preserved)
        # and shrink the block S dim to the new max subspace size
        order = np.argsort(~keep, axis=1, kind="stable")
        proj_cols_np = np.take_along_axis(
            np.where(keep, proj_cols_np, -1), order, axis=1
        )
        feats = np.take_along_axis(
            np.where(keep[:, None, :], feats, 0.0), order[:, None, :], axis=2
        )
        per_entity_s = keep.sum(axis=1).astype(np.int64)
        S = max(int(per_entity_s.max()) if E_real else 1, 1)
        proj_cols_np = proj_cols_np[:, :S]
        feats = feats[:, :, :S]

    fdt = np.dtype(jnp.zeros((), feature_dtype or dtype).dtype)
    sdt = np.dtype(jnp.zeros((), dtype).dtype)
    streamed = False
    if hbm_budget_bytes is not None:
        from .streaming import estimate_block_bytes

        E_b, K_b, S_b = feats.shape
        streamed = (
            estimate_block_bytes(E_b, K_b, S_b, fdt.itemsize) > hbm_budget_bytes
        )
    if streamed:
        # host-resident blocks: train/score stream slices (game/streaming.py)
        blocks = EntityBlocks(
            features=feats.astype(fdt),
            labels=labels_b.astype(sdt),
            offsets=offsets_b.astype(sdt),
            weights=weights_b.astype(sdt),
            proj_cols=proj_cols_np.astype(np.int32),
            active_rows=active_rows_np.astype(np.int32),
        )
    else:
        blocks = EntityBlocks(
            features=jnp.asarray(feats, feature_dtype or dtype),
            labels=jnp.asarray(labels_b, dtype),
            offsets=jnp.asarray(offsets_b, dtype),
            weights=jnp.asarray(weights_b, dtype),
            proj_cols=jnp.asarray(proj_cols_np),
            active_rows=jnp.asarray(active_rows_np.astype(np.int32)),
        )

    row_entity = np.where(entity_of_row >= 0, entity_of_row, -1).astype(np.int32)
    kept_ids = uniq[kept_entities].astype(str)
    entity_ids = np.concatenate(
        [kept_ids, np.asarray([f"__pad{i}" for i in range(E - E_real)], dtype=object)]
    ) if E > E_real else kept_ids

    return RandomEffectDataset(
        coordinate_id=coordinate_id,
        feature_shard=feature_shard,
        random_effect_type=random_effect_type,
        entity_ids=entity_ids.astype(object),
        blocks=blocks,
        row_entity=jnp.asarray(row_entity),
        ell_idx=jnp.asarray(ell_idx_np),
        ell_val=jnp.asarray(ell_val_np, feature_dtype or dtype),
        passive_rows=passive,
        entity_counts=np.sum(active_rows_np >= 0, axis=1).astype(np.int64),
        entity_subspace_dims=per_entity_s.astype(np.int64),
        streamed=streamed,
        hbm_budget_bytes=hbm_budget_bytes if streamed else None,
    )
