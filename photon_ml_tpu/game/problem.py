"""GLM optimization problems: objective + optimizer + regularization in one unit.

Reference: photon-api .../optimization/ —
GeneralizedLinearOptimizationProblem.scala:45-162 (run / initializeZeroModel /
de-normalization back to original space), DistributedOptimizationProblem
(fixed effect: down-sampling hook, mutable reg weight for lambda sweeps,
variance computation) and SingleNodeOptimizationProblem (per-entity local
problems). On TPU both are this one class: "distributed" = the batch is
sharded over the mesh, "single node" = the problem is one vmap lane.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..models.coefficients import Coefficients
from ..models.glm import GeneralizedLinearModel, model_for_task
from ..ops.features import FeatureMatrix, LabeledBatch
from ..ops.glm import GLMObjective, compute_variances
from ..ops.losses import get_loss
from ..ops.normalization import NormalizationContext
from ..ops.regularization import NO_REGULARIZATION, RegularizationContext
from ..optimize import (
    OptimizerConfig,
    OptimizerType,
    SolverResult,
    optimize,
    solve_lbfgs,
    solve_tron,
)
from ..optimize.common import abs_tolerances

Array = jax.Array


def _fusion_mode(batch: LabeledBatch):
    """Decide whether this batch takes the single-sweep Pallas kernels
    (ops/pallas_glm.py). Returns (mode, mesh): mode None = jnp two-pass path;
    mesh is set when the batch is DATA-axis-sharded over >1 device, in which
    case the kernels run per-shard under shard_map + psum (a bare pallas_call
    has no GSPMD partitioning rule — without the explicit shard_map XLA would
    all-gather the sharded X around it). Model-axis-sharded dense batches
    keep the jnp path."""
    from ..ops import pallas_glm

    none = (None, None)
    mode = pallas_glm.mode()
    if mode == "off":
        return none
    f = batch.features
    if not f.is_dense:
        return none
    x = f.dense
    if isinstance(x, jax.core.Tracer):
        return none
    n, d = x.shape
    if not pallas_glm.eligible(n, d, x.dtype):
        return none
    mesh = None
    sharding = getattr(x, "sharding", None)
    if sharding is not None and len(getattr(sharding, "device_set", ())) > 1:
        from jax.sharding import NamedSharding
        from ..parallel.mesh import DATA_AXIS

        if not isinstance(sharding, NamedSharding):
            return none
        spec = tuple(sharding.spec)
        # rows on the data axis, feature dim unsharded
        if len(spec) == 0 or spec[0] != DATA_AXIS:
            return none
        if any(s is not None for s in spec[1:]):
            return none
        mesh = sharding.mesh
    if mode == "interpret":
        return "interpret", mesh
    return ("compiled", mesh) if jax.default_backend() == "tpu" else none


def _pad_dim(v: Array, dim: int, fill: float) -> Array:
    """Zero/one-pad a [d] vector up to a mesh-padded feature dim."""
    if v.shape[0] >= dim:
        return v
    return jnp.concatenate(
        [v, jnp.full((dim - v.shape[0],), fill, dtype=v.dtype)]
    )


@dataclasses.dataclass(frozen=True)
class GLMOptimizationConfig:
    """Per-coordinate optimization settings (reference:
    CoordinateOptimizationConfiguration + OptimizerConfig)."""

    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    regularization: RegularizationContext = NO_REGULARIZATION
    reg_weight: float = 0.0
    down_sampling_rate: float = 1.0
    variance_type: str = "NONE"  # NONE | SIMPLE | FULL

    def with_reg_weight(self, w: float) -> "GLMOptimizationConfig":
        return dataclasses.replace(self, reg_weight=w)

    def solver_config(self) -> OptimizerConfig:
        """OptimizerConfig with the regularization split applied
        (OptimizerFactory.scala:30-74: L1/elastic-net -> OWLQN l1 weight)."""
        return dataclasses.replace(
            self.optimizer,
            l1_weight=self.regularization.l1_weight(self.reg_weight),
        )


@dataclasses.dataclass(frozen=True)
class GLMProblem:
    """A ready-to-run training problem over one batch."""

    task: str
    config: GLMOptimizationConfig
    normalization: Optional[NormalizationContext] = None
    # incremental training: L2 centered on a prior model's means, weighted by
    # its precisions (README.md:102-103 "Regularize by Previous Model")
    prior: Optional[Coefficients] = None

    def _norm_for(self, batch: LabeledBatch) -> Optional[NormalizationContext]:
        """Normalization stats padded to the batch's (possibly mesh-padded)
        feature dim — identity entries on structural padding dims."""
        if self.normalization is None:
            return None
        return self.normalization.padded(batch.dim)

    def objective(
        self,
        batch: LabeledBatch,
        fused: Optional[str] = None,
        fused_mesh=None,
    ) -> GLMObjective:
        norm = self._norm_for(batch)
        prior_mean = prior_precision = None
        if self.prior is not None:
            dtype = batch.labels.dtype
            prior_mean = jnp.asarray(self.prior.means, dtype)
            if self.normalization is not None:
                prior_mean = self.normalization.model_to_transformed_space(prior_mean)
            if self.prior.variances is not None:
                var = jnp.asarray(self.prior.variances, dtype)
                prior_precision = 1.0 / jnp.maximum(var, 1e-12)
            else:
                prior_precision = jnp.ones_like(prior_mean)
            # mesh-tiled batches pad the feature dim; padded coords have no
            # data — prior mean 0 / precision 1 pins them at zero
            prior_mean = _pad_dim(prior_mean, batch.dim, 0.0)
            if prior_precision is not None:
                prior_precision = _pad_dim(prior_precision, batch.dim, 1.0)
        return GLMObjective(
            loss=get_loss(self.task),
            batch=batch,
            l2=self.config.regularization.l2_weight(self.config.reg_weight),
            norm=norm,
            prior_mean=prior_mean,
            prior_precision=prior_precision,
            fused=fused,
            fused_mesh=fused_mesh,
        )

    def run(
        self,
        batch: LabeledBatch,
        initial_model: Optional[GeneralizedLinearModel] = None,
    ) -> Tuple[GeneralizedLinearModel, SolverResult]:
        """Train; returns (model in ORIGINAL space, solver result).

        Normalization semantics parity (Optimizer.scala:161-185 +
        GeneralizedLinearOptimizationProblem): warm-start coefficients are
        mapped to the transformed space, optimization runs there, the final
        coefficients map back.
        """
        if self.config.variance_type.upper() == "FULL":
            # fail BEFORE the (possibly hours-long) solve, not after it —
            # same check (and exception) as the post-solve entry points
            from ..ops.glm import check_full_variance_dim

            check_full_variance_dim(batch.dim)
        fused, fused_mesh = _fusion_mode(batch)
        obj = self.objective(batch, fused=fused, fused_mesh=fused_mesh)
        dtype = batch.labels.dtype
        if initial_model is not None:
            w0 = jnp.asarray(initial_model.coefficients.means, dtype)
            if self.normalization is not None:
                w0 = self.normalization.model_to_transformed_space(w0)
            w0 = _pad_dim(w0, batch.dim, 0.0)
        else:
            w0 = jnp.zeros(batch.dim, dtype)
        mesh = getattr(batch.features, "mesh", None)
        if mesh is not None:
            # tiled batch: shard the coefficient vector over the model axis so
            # every solver state array ([m, d] L-BFGS history included)
            # inherits the partition instead of replicating d on one device
            # (multi-process safe, no host round trip: every process built the
            # same w0, the jitted reshard places it)
            from jax.sharding import PartitionSpec
            from ..parallel.multihost import reshard
            from ..parallel.sparse import MODEL_AXIS

            w0 = reshard(jnp.asarray(w0, dtype), mesh, PartitionSpec(MODEL_AXIS))

        from ..ops.glm import hvp_fn, vg_fn

        result = optimize(vg_fn(obj), w0, self.config.solver_config(), hvp=hvp_fn(obj))

        variances = compute_variances(obj, result.coefficients, self.config.variance_type)

        means = result.coefficients
        if self.normalization is not None:
            # padded to batch.dim: tiled coefficients live in the mesh-padded
            # space until the coordinate trims them back to d_true
            means = self._norm_for(batch).model_to_original_space(means)
            # variances stay in transformed space in the reference as well

        model = model_for_task(
            self.task, Coefficients(means=means, variances=variances)
        )
        return model, result

    def run_streamed(
        self,
        host_batch,  # game.data.HostRowBatch
        budget_bytes: int,
        residual_scores: Optional[Array] = None,  # device f[n] or None
        initial_model: Optional[GeneralizedLinearModel] = None,
    ) -> Tuple[GeneralizedLinearModel, SolverResult]:
        """Train out-of-core: row slices of the host batch stream through the
        chip double-buffered (game/fe_streaming.py) while the optimizer runs
        on the host (optimize/host_driver.py) — the reference's
        Breeze-on-the-driver + treeAggregate-per-evaluation split. Same
        normalization / warm-start / prior semantics as ``run``; returns a
        host-materialized SolverResult."""
        from ..optimize import host_optimize
        from .fe_streaming import StreamedFEObjective

        vt = self.config.variance_type.upper()
        if vt != "NONE":
            raise ValueError(
                f"variance={vt} is not supported on the streamed fixed-effect"
                " path (out-of-core row slices never materialize the Hessian);"
                " use variance=NONE or raise hbm.budget.mb so the batch is"
                " HBM-resident"
            )
        dim = host_batch.dim
        dtype = host_batch.labels.dtype
        norm = None
        if self.normalization is not None:
            norm = self.normalization.padded(dim)
        prior_mean = prior_precision = None
        if self.prior is not None:
            prior_mean = jnp.asarray(self.prior.means, dtype)
            if self.normalization is not None:
                prior_mean = self.normalization.model_to_transformed_space(prior_mean)
            if self.prior.variances is not None:
                var = jnp.asarray(self.prior.variances, dtype)
                prior_precision = 1.0 / jnp.maximum(var, 1e-12)
            else:
                prior_precision = jnp.ones_like(prior_mean)
        if initial_model is not None:
            w0 = jnp.asarray(initial_model.coefficients.means, dtype)
            if self.normalization is not None:
                w0 = self.normalization.model_to_transformed_space(w0)
            w0 = np.asarray(jax.device_get(w0))
        else:
            w0 = np.zeros(dim, dtype)

        obj = StreamedFEObjective(
            get_loss(self.task),
            host_batch,
            budget_bytes,
            norm=norm,
            l2_weight=self.config.regularization.l2_weight(self.config.reg_weight),
            prior_mean=prior_mean,
            prior_precision=prior_precision,
            residual_scores=residual_scores,
        )
        try:
            with obs.span(
                "fe_stream.solve",
                phase="solve",
                n_slices=obj.n_slices,
                budget_bytes=int(budget_bytes),
            ) as solve_span:
                # at pipeline depth >= 2 the driver gets the deferred form
                # too, so the tolerance pass and the first real evaluation
                # are both in flight before either is fetched
                deferred = (
                    obj.value_and_grad_deferred if obj.pipeline_depth > 1 else None
                )
                result = host_optimize(
                    obj.value_and_grad,
                    w0,
                    self.config.solver_config(),
                    hvp=obj.hessian_vector,
                    value_and_grad_deferred=deferred,
                )
            obj.record_metrics("fe.train", solve_span.duration_s)
        finally:
            obj.close()

        means = jnp.asarray(result.coefficients, dtype)
        if self.normalization is not None:
            means = norm.model_to_original_space(means)
        model = model_for_task(
            self.task, Coefficients(means=means, variances=None)
        )
        return model, result

    def run_lanes(
        self,
        batch: LabeledBatch,
        offsets_lanes: Array,  # f[n, L] effective offsets per lambda lane
        l2_lanes: Array,  # f[L] per-lane L2 weights (DYNAMIC operand)
        w0: Optional[Array] = None,  # f[d, L] warm start; None = zeros
    ) -> Tuple[Array, SolverResult]:
        """Lane-stacked solve: L regularization candidates share one data
        residency and ONE compiled kernel. The per-lane reg weight enters as a
        vector operand (never a static argument), so a refreshed candidate set
        from the tuner reuses the executable instead of recompiling.

        Returns (coefficients f[d, L], per-lane SolverResult — loss/reason/
        iterations all [L]). A lane that is born corrupt or diverges freezes
        at its warm start with ``ConvergenceReason.NUMERICAL_DIVERGENCE``
        without stalling its neighbors (PR 4's masked-commit machinery; see
        optimize/lbfgs.py).

        Composition limits (checked here because this is the deep entry
        point; game/lanes.py pins the user-facing refusals): L2-only
        regularization (the OWL-QN l1 weight is compile-time static, not a
        per-lane operand), variance=NONE, no normalization, no prior."""
        solver_cfg = self.config.solver_config()
        if solver_cfg.l1_weight > 0.0:
            raise ValueError(
                "trial-lanes sweeps support L2 regularization only (the "
                "OWL-QN l1 weight is compile-time static, not a per-lane "
                "operand)"
            )
        if self.config.variance_type.upper() != "NONE":
            raise ValueError(
                "trial-lanes sweeps require variance=NONE (per-lane "
                "Hessian inversion is not lane-stacked)"
            )
        if self.normalization is not None:
            raise ValueError(
                "feature normalization is not supported with trial-lanes"
            )
        if self.prior is not None:
            raise ValueError(
                "regularize-by-prior is not supported with trial-lanes"
            )
        dtype = batch.labels.dtype
        L = offsets_lanes.shape[1]
        if w0 is None:
            w0 = jnp.zeros((batch.dim, L), dtype)
        result = _train_fe_lanes(
            batch.features,
            batch.labels,
            offsets_lanes,
            batch.weights,
            jnp.asarray(w0, dtype),
            jnp.asarray(l2_lanes, dtype),
            task=self.task,
            optimizer_type=OptimizerType(solver_cfg.normalized_type()).value,
            tolerance=solver_cfg.tolerance,
            max_iterations=solver_cfg.max_iterations,
            num_corrections=solver_cfg.num_corrections,
            max_cg_iterations=solver_cfg.max_cg_iterations,
            max_improvement_failures=solver_cfg.max_improvement_failures,
        )
        return result.coefficients, result

    def zero_model(self, dim: int, dtype=jnp.float32) -> GeneralizedLinearModel:
        return model_for_task(self.task, Coefficients.zeros(dim, dtype))


@partial(
    jax.jit,
    static_argnames=(
        "task",
        "optimizer_type",
        "tolerance",
        "max_iterations",
        "num_corrections",
        "max_cg_iterations",
        "max_improvement_failures",
    ),
)
def _train_fe_lanes(
    features: FeatureMatrix,
    labels: Array,  # f[n]
    offsets_lanes: Array,  # f[n, L]
    weights: Array,  # f[n]
    w0: Array,  # f[d, L]
    l2_lanes: Array,  # f[L] — dynamic operand, NOT static: candidate
    # refreshes must reuse the executable
    *,
    task: str,
    optimizer_type: str,
    tolerance: float,
    max_iterations: int,
    num_corrections: int,
    max_cg_iterations: int,
    max_improvement_failures: int,
) -> SolverResult:
    """Batched fixed-effect objective over the lambda-lane axis.

    Same algebra as GLMObjective, with the coefficient vector widened to
    ``[d, L]``: margins are one ``matmat`` ([n, L]), the gradient one
    ``rmatmat`` ([d, L]), and the L2 term broadcasts the per-lane weight
    vector. Every solver reduction is axis-0 (optimize/common._norm), so the
    trailing lane axis rides through L-BFGS/TRON untouched — exactly the
    entity-minor batched-solve contract of PR 4, with lambdas instead of
    entities as the lane dimension."""
    loss = get_loss(task)
    y = labels[:, None]
    wt = weights[:, None]

    def value_and_grad(w):  # [d, L] -> ([L], [d, L])
        z = features.matmat(w) + offsets_lanes  # [n, L]
        lvals, dz = loss.loss_and_dz(z, y)
        value = jnp.sum(wt * lvals, axis=0)  # [L]
        grad = features.rmatmat(wt * dz)  # [d, L]
        value = value + 0.5 * l2_lanes * jnp.sum(w * w, axis=0)
        grad = grad + l2_lanes[None, :] * w
        return value, grad

    def hessian_vector(w, v):
        z = features.matmat(w) + offsets_lanes
        c = wt * loss.d2z(z, y) * features.matmat(v)  # [n, L]
        return features.rmatmat(c) + l2_lanes[None, :] * v

    loss_tol, grad_tol = abs_tolerances(value_and_grad, w0, tolerance)  # [L]
    if optimizer_type == "TRON":
        return solve_tron(
            value_and_grad,
            hessian_vector,
            w0,
            loss_tol,
            grad_tol,
            max_iterations=max_iterations,
            max_cg_iterations=max_cg_iterations,
            max_improvement_failures=max_improvement_failures,
        )
    return solve_lbfgs(
        value_and_grad,
        w0,
        loss_tol,
        grad_tol,
        max_iterations=max_iterations,
        num_corrections=num_corrections,
        batched=True,
    )
