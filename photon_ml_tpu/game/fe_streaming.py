"""Out-of-core FIXED-effect training: row slices streamed through HBM.

The missing twin of ``game/streaming.py`` (which streams entity blocks for
the random effects). The reference trains its fixed effect at any n by
streaming disk-persisted partitions through ``treeAggregate``
(photon-lib .../data/avro/AvroDataReader.scala:165-209, DISK_ONLY persists at
CoordinateDescent.scala:262,404): each partition computes the partial sums of
the GLM objective (seqOp) and the driver combines them (combOp) before Breeze
takes an optimizer step on the driver. The TPU re-design mirrors that split
exactly:

- the FE batch lives in HOST memory (``HostRowBatch``: row-major numpy), and
  only budget-sized ROW SLICES of the feature planes are resident on device
  at a time — slice size comes from the same ``hbm.budget.mb`` contract as
  the RE stream, halved for double buffering;
- slice k+1's ``jax.device_put`` is dispatched before slice k's partial sums
  are consumed, so H2D staging overlaps compute;
- per-slice partials (``ops/glm.py: slice_value_grad_partials`` /
  ``slice_hessian_vector_partials``) are accumulated SEQUENTIALLY in slice
  order on device — a fixed left-to-right reduction, so results are bitwise
  stable run-to-run — and the per-evaluation algebra (normalization shifts /
  factors, prior delta, L2) applies once to the totals
  (``finalize_value_grad`` / ``finalize_hessian_vector``), making the
  streamed objective equal to the resident one up to float summation order;
- the optimizer itself runs on the HOST (``optimize/host_driver.py``), one
  evaluation per full pass over the slices — the Breeze-on-the-driver shape
  of the reference, where device state is bounded by ~2 slices of features
  plus O(d) vectors regardless of n.

The [n]-sized scalar planes (labels / offsets / weights, plus the residual
score vector the coordinate composes in) stay device-resident: they are the
same order of footprint as the RE stream's row-sized ELL arrays, which are
device-resident by the same assumption — the budget governs the n*d feature
mass, which is what actually scales.

All slices share ONE step size (the tail slice is zero-padded host-side at
construction, pad rows carry weight 0 and are invisible to the objective),
so each kernel compiles once per (layout, step, d) — no per-remainder
recompiles.

Streaming composes with the mesh / multi-process topology (the execution
planner's streamed+sharded routing, plan/planner.py): each host streams ITS
OWN row slice under the per-host budget — the seqOp stays local — and the
combOp grows one cross-host rung: the accumulated per-pass partial sums
(O(d), not O(n*d)) are exchanged host-side in process order before the
finalize kernels, exactly where the reference's treeAggregate combined
executor partials on the driver. Single-process, that rung is a no-op and
the math is bit-identical to the resident path up to float summation order.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..analysis.runtime import logged_fetch
from ..utils.futures import PrefetchQueue
from . import pipeline
from ..ops.features import FeatureMatrix, LabeledBatch
from ..ops.glm import (
    finalize_hessian_vector,
    finalize_value_grad,
    slice_hessian_vector_partials,
    slice_value_grad_partials,
)
from ..ops.losses import PointwiseLoss
from ..ops.normalization import NormalizationContext, identity_normalization

Array = jax.Array

# ELL index planes are int32 (io/data.py builds them that way); derived so a
# future widening keeps the HBM estimate honest
_ELL_INDEX_ITEMSIZE = int(np.dtype(np.int32).itemsize)


def estimate_fe_batch_bytes(
    n_rows: int,
    dim: int,
    layout: str,
    ell_width: int = 0,
    feature_itemsize: int = 4,
    scalar_itemsize: int = 4,
) -> int:
    """Device bytes of an in-HBM fixed-effect LabeledBatch of this shape
    (features + labels/offsets/weights). The streamed-vs-resident decision in
    ``build_fixed_effect_dataset`` compares this against ``hbm_budget_bytes``.

    ``scalar_itemsize`` is the labels/offsets/weights itemsize (8 for an
    x64-configured dataset); callers derive both itemsizes from the actual
    dtypes, like the RE estimator."""
    if layout == "dense":
        feat = n_rows * dim * feature_itemsize
    elif layout == "ell":
        feat = n_rows * ell_width * (feature_itemsize + _ELL_INDEX_ITEMSIZE)
    else:
        raise ValueError(
            f"estimate_fe_batch_bytes: layout must be dense|ell, got {layout!r}"
        )
    return int(feat + 3 * n_rows * scalar_itemsize)


# slice row counts are rounded to this lane multiple (not a byte itemsize)
_ROW_MULTIPLE = 8


def rows_per_slice(
    budget_bytes: int, feature_row_nbytes: int, multiple: int = _ROW_MULTIPLE
) -> int:
    """Rows per streamed slice under ``budget_bytes``: double-buffered (2
    slices of feature planes resident at once). Only the feature planes are
    staged per evaluation — the [n] scalar planes are device-resident by
    assumption (see module docstring) — so the slice size is governed by the
    per-row feature bytes alone, rounded down to a lane multiple."""
    r = max(budget_bytes // max(2 * feature_row_nbytes, 1), multiple)
    return int(r // multiple * multiple)


# --- per-slice kernels -------------------------------------------------------
#
# Module-level jits shared by every StreamedFEObjective: the loss is a
# register_static pytree and FeatureMatrix carries its dim statically, so one
# compilation covers every evaluation of a given (layout, step, d) — and the
# L2 weight rides through the finalize kernels as a DYNAMIC scalar, so a
# regularization sweep re-uses the same executables.


@jax.jit
def _vg_slice_kernel(
    loss: PointwiseLoss,
    feats: FeatureMatrix,
    labels: Array,
    offsets: Array,
    weights: Array,
    eff: Array,
    mshift: Array,
):
    batch = LabeledBatch(features=feats, labels=labels, offsets=offsets, weights=weights)
    return slice_value_grad_partials(loss, batch, eff, mshift)


@jax.jit
def _hvp_slice_kernel(
    loss: PointwiseLoss,
    feats: FeatureMatrix,
    labels: Array,
    offsets: Array,
    weights: Array,
    eff: Array,
    mshift: Array,
    eff_v: Array,
    vshift: Array,
):
    batch = LabeledBatch(features=feats, labels=labels, offsets=offsets, weights=weights)
    return slice_hessian_vector_partials(loss, batch, eff, mshift, eff_v, vshift)


@jax.jit
def _finalize_vg_kernel(coef, value_sum, raw_grad_sum, wdz_sum, norm, l2, pm, pp):
    return finalize_value_grad(coef, value_sum, raw_grad_sum, wdz_sum, norm, l2, pm, pp)


@jax.jit
def _finalize_hvp_kernel(v, hv_sum, csum, norm, l2, pp):
    return finalize_hessian_vector(v, hv_sum, csum, norm, l2, pp)


class StreamedFEObjective:
    """Row-sliced, double-buffered fixed-effect GLM objective for the host
    solver driver: ``value_and_grad(w)`` / ``hessian_vector(w, v)`` take and
    return host numpy, and each call is one full streamed pass over the
    batch (the reference's treeAggregate per Breeze evaluation)."""

    def __init__(
        self,
        loss: PointwiseLoss,
        host_batch,  # game.data.HostRowBatch
        budget_bytes: int,
        norm: Optional[NormalizationContext] = None,
        l2_weight: float = 0.0,
        prior_mean: Optional[Array] = None,
        prior_precision: Optional[Array] = None,
        residual_scores: Optional[Array] = None,  # device f[n] or None
        pipeline_depth: Optional[int] = None,  # None -> pipeline.active_depth()
    ):
        self.loss = loss
        self.hb = host_batch
        self.budget_bytes = int(budget_bytes)
        self.dim = int(host_batch.dim)
        n = host_batch.n_rows
        self.n_rows = n
        sdt = np.dtype(host_batch.labels.dtype)
        self.sdt = sdt
        self.norm = identity_normalization() if norm is None else norm
        self._l2 = jnp.asarray(l2_weight, sdt)
        self._pm = None if prior_mean is None else jnp.asarray(prior_mean)
        self._pp = None if prior_precision is None else jnp.asarray(prior_precision)

        row_bytes = host_batch.feature_row_nbytes()
        # never slice wider than the batch itself (lane-multiple rounding up)
        n_up = -(-n // _ROW_MULTIPLE) * _ROW_MULTIPLE
        step = min(rows_per_slice(self.budget_bytes, row_bytes), n_up)
        self.step = step
        self.n_slices = -(-n // step)
        n_padded = self.step * self.n_slices
        pad = n_padded - n

        # the tail slice is padded ONCE, host-side, to the common step size:
        # a private copy of just that slice (never of the whole batch), so
        # every slice shares one compiled kernel shape
        self._tail = None
        if pad:
            s0 = (self.n_slices - 1) * step
            if host_batch.dense is not None:
                t = np.zeros((step, self.dim), host_batch.dense.dtype)
                t[: n - s0] = host_batch.dense[s0:]
                self._tail = (t,)
            else:
                ti = np.zeros((step, host_batch.ell_idx.shape[1]), host_batch.ell_idx.dtype)
                tv = np.zeros((step, host_batch.ell_val.shape[1]), host_batch.ell_val.dtype)
                ti[: n - s0] = host_batch.ell_idx[s0:]
                tv[: n - s0] = host_batch.ell_val[s0:]
                self._tail = (ti, tv)

        # device-resident scalar planes, padded with weight-0 rows
        def _padded(a: np.ndarray) -> np.ndarray:
            a = np.ascontiguousarray(a, sdt)
            if pad:
                a = np.concatenate([a, np.zeros(pad, sdt)])
            return a

        labels = _padded(host_batch.labels)
        offsets = _padded(host_batch.offsets)
        weights = _padded(host_batch.weights)
        obs.add_device_put_bytes(
            "fe_streaming.resident", labels.nbytes + offsets.nbytes + weights.nbytes
        )
        dl = jax.device_put(labels)
        do = jax.device_put(offsets)
        dw = jax.device_put(weights)
        if residual_scores is not None:
            res = residual_scores.astype(dl.dtype)
            if pad:
                res = jnp.concatenate([res, jnp.zeros(pad, res.dtype)])
            do = do + res
        self._scalar_slices = [
            (
                dl[k * step : (k + 1) * step],
                do[k * step : (k + 1) * step],
                dw[k * step : (k + 1) * step],
            )
            for k in range(self.n_slices)
        ]

        self.stats = {
            "vg_passes": 0,
            "hvp_passes": 0,
            "slices": 0,
            "staged_bytes": 0,
            "max_slice_bytes": 0,
            "stage_seconds": 0.0,
        }

        # sweep pipelining (game/pipeline.py): depth >= 2 moves staging onto
        # a background thread whose queue is bounded by the SAME byte budget
        # (queued + held slice bytes <= budget_bytes, queue-empty admits one
        # — the inline double buffer's 2-resident worst case, so slice
        # geometry and the left-to-right accumulation bits never change).
        # The stager cycles 0..n_slices-1 forever: every pass (vg and hvp)
        # consumes slices in that exact order, so the NEXT pass's slice 0 is
        # already staged while this pass's finalize fetch is in flight.
        self.pipeline_depth = (
            pipeline.active_depth() if pipeline_depth is None else int(pipeline_depth)
        )
        # multi-process: each host streams its OWN row slice; the per-pass
        # O(d) partial sums are combined across hosts before finalize (the
        # treeAggregate combOp rung — see module docstring)
        self._cross_host = jax.process_count() > 1
        self._anchor = pipeline.stage_anchor()
        self._slice_cost = self.step * row_bytes
        self._prefetch: Optional[PrefetchQueue] = None
        # (start, end) host wall intervals behind photon_stream_overlap_ratio:
        # "pass" covers each dispatch loop (kernels for earlier slices are in
        # flight the whole time under async dispatch), "collect" the blocking
        # result fetch — together the host-observable compute shadow
        self._intervals = {"stage": [], "collect": [], "pass": []}

    # -- staging --------------------------------------------------------------

    def _acquire(self, k: int) -> FeatureMatrix:
        """Slice k's staged features: inline at depth 1, popped from the
        background stager at depth >= 2 (started lazily on first use)."""
        if self.pipeline_depth <= 1 or self.n_slices <= 1:
            return self._stage_features(k)
        if self._prefetch is None:
            self._prefetch = PrefetchQueue(
                lambda i: self._stage_features(i, parent=self._anchor),
                self.n_slices,
                depth=self.pipeline_depth,
                cyclic=True,
                cost=lambda i: self._slice_cost,
                budget=self.budget_bytes,
                name="photon-fe-stage",
            )
        idx, staged = self._prefetch.get()
        if idx != k:
            raise RuntimeError(
                f"fe_streaming prefetch out of order: staged slice {idx}, "
                f"consumer wants {k}"
            )
        return staged

    def _stage_features(self, k: int, parent: Optional[obs.Span] = None) -> FeatureMatrix:
        """H2D-stage slice k's feature planes (dispatched before the previous
        slice's partials are consumed, so the copy overlaps compute). On the
        stager thread ``parent`` anchors the span under the sweep — the
        contextvar ancestry does not cross threads."""
        with obs.span("fe_stream.stage", parent=parent, phase="stage", slice=k) as sp:
            s0 = k * self.step
            s1 = s0 + self.step
            if self._tail is not None and k == self.n_slices - 1:
                host = self._tail
            elif self.hb.dense is not None:
                host = (self.hb.dense[s0:s1],)
            else:
                host = (self.hb.ell_idx[s0:s1], self.hb.ell_val[s0:s1])
            nbytes = int(sum(a.nbytes for a in host))
            self.stats["slices"] += 1
            self.stats["staged_bytes"] += nbytes
            self.stats["max_slice_bytes"] = max(self.stats["max_slice_bytes"], nbytes)
            obs.add_device_put_bytes("fe_streaming.stage", nbytes)
            dev = [jax.device_put(np.ascontiguousarray(a)) for a in host]
        # duration_s is set when the span closes; route all slice timing
        # through the span so the timeline stays complete (lint rule R7)
        self.stats["stage_seconds"] += sp.duration_s
        self._intervals["stage"].append((sp.start_perf, sp.start_perf + sp.duration_s))
        obs.current_run().registry.histogram(
            "photon_stream_slice_stage_seconds",
            "host wall per H2D slice-staging dispatch",
        ).observe(sp.duration_s)
        if len(dev) == 1:
            return FeatureMatrix(dim=self.dim, dense=dev[0])
        return FeatureMatrix(dim=self.dim, idx=dev[0], val=dev[1])

    # -- objective ------------------------------------------------------------

    def _combine_partials(self, acc):
        """Sum this pass's accumulated partials across processes (multi-host
        combOp). Each host's acc covers only its own rows; the exchange is
        host-side (allgather of O(d) arrays) and summed in process order, so
        every host computes the identical totals deterministically.
        Single-process: identity."""
        if not self._cross_host:
            return acc
        from ..parallel import multihost

        local = tuple(logged_fetch("fe_streaming.cross_host", a) for a in acc)
        parts = multihost.allgather_object(local)
        totals = list(parts[0])
        for p in parts[1:]:
            totals = [t + q for t, q in zip(totals, p)]
        return tuple(jnp.asarray(t) for t in totals)

    def _collect(self, kind: str, out):
        """The pass's single blocking fetch, wrapped in a phase="collect"
        span so the overlap ratio can measure staging hidden under it."""
        with obs.span("fe_stream.collect", phase="collect", kind=kind) as cp:
            out = logged_fetch("fe_streaming.collect", out)
        self._intervals["collect"].append((cp.start_perf, cp.start_perf + cp.duration_s))
        return out

    def value_and_grad_deferred(self, w: np.ndarray):
        """Dispatch one streamed (value, grad) pass WITHOUT fetching; returns
        a zero-arg closure that fetches the result. Async dispatch means the
        device is already chewing on this pass while the caller dispatches
        the next one (host_driver overlaps the tolerance pass with the first
        real evaluation this way) — and at depth >= 2 the background stager
        is meanwhile staging the next pass's slices."""
        coef = jnp.asarray(w, self.sdt)
        eff, mshift = self.norm.effective_coefficients(coef)
        self.stats["vg_passes"] += 1
        with obs.span("fe_stream.pass", kind="vg", n_slices=self.n_slices) as pp:
            acc = None
            staged = self._acquire(0)
            for k in range(self.n_slices):
                labels, offsets, weights = self._scalar_slices[k]
                part = _vg_slice_kernel(
                    self.loss, staged, labels, offsets, weights, eff, mshift
                )
                if k + 1 < self.n_slices:
                    staged = self._acquire(k + 1)  # overlaps slice k
                # fixed left-to-right accumulation: bitwise-stable run-to-run
                acc = part if acc is None else tuple(a + p for a, p in zip(acc, part))
            acc = self._combine_partials(acc)
            value, grad = _finalize_vg_kernel(
                coef, acc[0], acc[1], acc[2], self.norm, self._l2, self._pm, self._pp
            )
        self._intervals["pass"].append((pp.start_perf, pp.start_perf + pp.duration_s))
        return lambda: self._collect("vg", (value, grad))

    def value_and_grad(self, w: np.ndarray):
        """One streamed pass: (objective value, gradient) as host numpy."""
        return self.value_and_grad_deferred(w)()

    def hessian_vector(self, w: np.ndarray, v: np.ndarray) -> np.ndarray:
        """One streamed pass of H(w) v (the TRON inner-CG kernel)."""
        coef = jnp.asarray(w, self.sdt)
        vv = jnp.asarray(v, self.sdt)
        eff, mshift = self.norm.effective_coefficients(coef)
        eff_v, vshift = self.norm.effective_coefficients(vv)
        self.stats["hvp_passes"] += 1
        with obs.span("fe_stream.pass", kind="hvp", n_slices=self.n_slices) as pp:
            acc = None
            staged = self._acquire(0)
            for k in range(self.n_slices):
                labels, offsets, weights = self._scalar_slices[k]
                part = _hvp_slice_kernel(
                    self.loss, staged, labels, offsets, weights,
                    eff, mshift, eff_v, vshift,
                )
                if k + 1 < self.n_slices:
                    staged = self._acquire(k + 1)
                acc = part if acc is None else tuple(a + p for a, p in zip(acc, part))
            acc = self._combine_partials(acc)
            hv = _finalize_hvp_kernel(vv, acc[0], acc[1], self.norm, self._l2, self._pp)
        self._intervals["pass"].append((pp.start_perf, pp.start_perf + pp.duration_s))
        (hv,) = self._collect("hvp", (hv,))
        return hv

    def close(self) -> None:
        """Stop the background stager (idempotent; depth-1 objectives have
        nothing to stop). An in-flight device_put completes harmlessly."""
        if self._prefetch is not None:
            self._prefetch.close()
            self._prefetch = None

    # -- metrics --------------------------------------------------------------

    def record_metrics(self, site: str, solve_seconds: float) -> None:
        """Emit the stream counters for one completed solve; ``site``
        distinguishes the FE stream ("fe.train") from the RE stream
        ("re.train") in the shared metric families. stage_seconds vs
        solve_seconds is the measured overlap claim: staging wall that the
        double buffer failed to hide shows up as their ratio."""
        reg = obs.current_run().registry
        st = self.stats
        reg.counter(
            "photon_stream_slices_total", "streamed slices staged through the chip"
        ).labels(site=site).inc(st["slices"])
        reg.counter(
            "photon_stream_staged_bytes_total", "host bytes staged to device"
        ).labels(site=site).inc(st["staged_bytes"])
        reg.counter(
            "photon_stream_passes_total", "full streamed passes over the batch"
        ).labels(site=site, kind="vg").inc(st["vg_passes"])
        reg.counter(
            "photon_stream_passes_total", "full streamed passes over the batch"
        ).labels(site=site, kind="hvp").inc(st["hvp_passes"])
        reg.gauge(
            "photon_stream_budget_bytes", "configured HBM budget"
        ).labels(site=site).set(self.budget_bytes)
        reg.gauge(
            "photon_stream_actual_slice_bytes", "largest slice actually staged"
        ).labels(site=site).set(st["max_slice_bytes"])
        reg.gauge(
            "photon_stream_budget_headroom_bytes",
            "budget minus double-buffered peak (negative = over budget)",
        ).labels(site=site).set(self.budget_bytes - 2 * st["max_slice_bytes"])
        reg.gauge(
            "photon_stream_stage_seconds",
            "host wall spent dispatching H2D stages (overlapped under compute)",
        ).labels(site=site).set(st["stage_seconds"])
        reg.gauge(
            "photon_stream_solve_seconds", "wall of the whole streamed solve"
        ).labels(site=site).set(solve_seconds)
        # measured (not inferred) overlap: fraction of staging wall that ran
        # concurrently with the compute shadow (dispatch-loop pass windows,
        # where async-dispatched slice kernels are in flight, plus the
        # blocking collect fetch). One source of truth, shared with the
        # timeline's phase math (obs.timeline.overlap_ratio). Inline staging
        # (depth 1) executes ON the solve thread inside those same windows —
        # serial with the compute it sits between, so the serial double
        # buffer scores exactly 0 rather than a self-overlap 1.0.
        if self.pipeline_depth <= 1 or self._prefetch is None:
            measured_overlap = 0.0
        else:
            measured_overlap = obs.overlap_ratio(
                self._intervals["stage"],
                self._intervals["pass"] + self._intervals["collect"],
            )
        reg.gauge(
            "photon_stream_overlap_ratio",
            "fraction of staging wall overlapped with in-flight compute",
        ).labels(site=site).set(measured_overlap)
        if self._prefetch is not None:
            reg.gauge(
                "photon_stream_inflight_peak_bytes",
                "peak staged bytes in flight (queued + held), bounded by the budget",
            ).labels(site=site).set(self._prefetch.peak_inflight)


def score_streamed_fe(
    host_batch,  # game.data.HostRowBatch
    means: Array,  # device f[d] model coefficients (original space)
    budget_bytes: int,
    score_dtype,
) -> Array:
    """Score all rows against device-resident coefficients by streaming
    budget-sized row slices of the host feature planes through the chip
    (double-buffered, like training). Returns device scores ``[n]`` in
    ``score_dtype`` — row-sized, device-resident by assumption."""
    n, d = host_batch.n_rows, host_batch.dim
    step = min(
        rows_per_slice(budget_bytes, host_batch.feature_row_nbytes()),
        -(-n // _ROW_MULTIPLE) * _ROW_MULTIPLE,
    )
    w = means.astype(score_dtype)

    def stage(s0: int):
        s1 = min(s0 + step, n)
        if host_batch.dense is not None:
            host = (host_batch.dense[s0:s1],)
        else:
            host = (host_batch.ell_idx[s0:s1], host_batch.ell_val[s0:s1])
        obs.add_device_put_bytes(
            "fe_streaming.score_stage", int(sum(a.nbytes for a in host))
        )
        dev = [jax.device_put(np.ascontiguousarray(a)) for a in host]
        if len(dev) == 1:
            return FeatureMatrix(dim=d, dense=dev[0])
        return FeatureMatrix(dim=d, idx=dev[0], val=dev[1])

    parts = []
    starts = list(range(0, n, step))
    staged = stage(starts[0])
    for i, s0 in enumerate(starts):
        parts.append(staged.matvec(w).astype(score_dtype))
        if i + 1 < len(starts):
            staged = stage(starts[i + 1])
    reg = obs.current_run().registry
    reg.counter(
        "photon_stream_slices_total", "streamed slices staged through the chip"
    ).labels(site="fe.score").inc(len(starts))
    if len(parts) == 1:
        return parts[0]
    return jnp.concatenate(parts)
