"""Coordinate descent over GAME coordinates with residual score exchange.

Reference: photon-lib .../algorithm/CoordinateDescent.scala:43-670 — the outer
loop trains each coordinate against the residual of all others, maintains the
summed scores incrementally (summedScores - oldScores + newScores, :441-446),
evaluates on validation data after every coordinate update, and tracks the
best model seen by the primary validation metric (:607-622). Locked
coordinates (partial retraining) are fetched, never trained (:280-300), and
the invariant checks of checkInvariants:71-92 are enforced up front.

Scores here are plain device arrays in fixed sample order, so the reference's
fullOuterJoin RDD arithmetic is elementwise adds (SURVEY.md §2.1 P7).
"""

from __future__ import annotations

import collections
import dataclasses
import logging
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from .. import obs
from ..analysis.runtime import allow_transfers, logged_fetch, transfer_guard
from ..robust import distributed as robust_dist
from ..robust import faults
from ..evaluation.suite import EvaluationResults, EvaluationSuite
from ..models.game import GameModel
from ..optimize.trackers import build_tracker, record_tracker_metrics
from ..utils.timed import timed
from . import pipeline
from .coordinate import Coordinate, ModelCoordinate

logger = logging.getLogger("photon_ml_tpu")


def _process_count() -> int:
    """Process count without requiring an initialized backend (host-only
    callers — planner dry runs, unit tests with jax stubbed out — see 1)."""
    try:
        import jax

        return jax.process_count()
    except Exception:  # photon: ignore[R4] - no-jax fallback, single process
        return 1


def _local_devices():
    """Device handles for memory sampling; empty when the backend is not up
    (sampling then covers host RSS only)."""
    try:
        import jax

        return jax.local_devices()
    except Exception:  # photon: ignore[R4] - no-jax fallback, host-only sample
        return ()


@dataclasses.dataclass
class CoordinateDescentResult:
    model: GameModel
    evaluations: List[Tuple[str, EvaluationResults]]  # (coordinate, results) per update
    best_evaluation: Optional[EvaluationResults]
    # coordinate -> Fixed/RandomEffectOptimizationTracker (raw SolverResult on
    # tracker.result)
    trackers: Dict[str, object]


@dataclasses.dataclass
class CDBoundaryState:
    """Everything the outer loop knows at a coordinate-update boundary — the
    unit a crash-safe checkpoint persists (robust.checkpoint) and a resumed
    run restores. Between coordinate updates the entire algorithm state is
    these few values; mid-update there is no consistent host-visible state,
    which is why boundaries are the only snapshot points."""

    iteration: int  # sweep index of the update just finished
    coordinate_index: int  # position in ``coordinate_order`` just finished
    coordinate: str
    coordinate_order: List[str]
    n_iterations: int
    models: Dict[str, object]
    summed_scores: jnp.ndarray
    best_eval: Optional[EvaluationResults]
    best_models: Dict[str, object]
    evaluations: List[Tuple[str, EvaluationResults]]
    trackers: Dict[str, object]
    # last ACCEPTED total train loss per coordinate — the divergence guard's
    # regression baseline; persisted so a resumed run rejects exactly the
    # updates the uninterrupted run would have rejected
    train_losses: Dict[str, float] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ValidationContext:
    """Validation-side scoring: per-coordinate score fn over the validation set."""

    suite: EvaluationSuite
    score_fns: Mapping[str, object]  # coordinate -> (model -> scores f[n_val])
    offsets: np.ndarray  # base offsets of validation rows


class CoordinateDescent:
    """Train GAME coordinates by block coordinate descent."""

    def __init__(
        self,
        coordinates: Mapping[str, Coordinate],  # ordered
        n_iterations: int = 1,
        validation: Optional[ValidationContext] = None,
        checkpoint_fn: Optional[object] = None,
        validation_frequency: str = "COORDINATE",
        boundary_fn: Optional[object] = None,
        resume_state: Optional[object] = None,
        divergence_guard: bool = True,
        rejection_tolerance: Optional[float] = None,
        pipeline_depth: int = 1,
    ):
        """``checkpoint_fn(iteration, models)`` runs after each completed
        sweep (crash recovery for long runs: resume = warm-start from the
        checkpointed models with the remaining iterations; the score state
        reconstructs exactly from the models).

        ``boundary_fn(state: CDBoundaryState)`` runs after EVERY coordinate
        update — finer-grained crash recovery than ``checkpoint_fn``
        (robust.CheckpointManager.on_boundary is the intended callee). It is
        invoked inside :func:`allow_transfers`, so serializers may fetch
        device arrays freely; the surrounding sweep stays transfer-guarded.

        ``resume_state``: a restored boundary state (duck type:
        robust.CheckpointSnapshot — iteration / coordinate_index / models /
        summed_scores / best_eval / best_models / evaluations). ``run``
        then continues from the update AFTER the snapshot: per-coordinate
        scores re-derive from the restored models (deterministic re-score),
        the summed scores restore exactly from the snapshot, and best-model
        tracking continues rather than restarting. ``initial_models`` passed
        to :meth:`run` are ignored on resume — the snapshot already embeds
        the warm-start lineage. Trackers restart empty (their summaries are
        checkpointed as strings, not as resumable solver state).

        ``validation_frequency``: 'COORDINATE' evaluates after every
        coordinate update (reference semantics, CoordinateDescent.scala:
        312-333); 'SWEEP' evaluates once per full sweep — same best-model
        tracking at 1/n_coordinates of the metric cost (round-4 verdict
        item 5: per-update host metrics dominate large sweeps).

        ``divergence_guard``: reject a coordinate update whose new scores or
        total train loss are non-finite — the previous (model, scores) stand,
        ``summed`` is never poisoned, and the sweep continues (counted in
        ``photon_coordinate_rejections_total{coordinate=}``). Costs one
        scalar :func:`logged_fetch` per update; False restores the strictly
        zero-fetch sweep. ``rejection_tolerance``: additionally reject when
        the update's train loss regresses more than this above the
        coordinate's last accepted loss (None — the default — disables the
        regression check; divergence rejection is purely about finiteness).

        ``pipeline_depth``: async-dispatch lookahead across the three sweep
        lanes (host staging, device solve, device score/eval). Depth 1 (the
        default) is exactly the serial loop. Depth >= 2 dispatches the
        accepted-score sum before the divergence guard's fetch, runs
        validation evaluations on a background lane (up to ``depth - 1`` in
        flight), and lets the streaming layers prefetch their next slice
        while a solve is in flight — all drained back in submit order, so
        accepted bits, the accept/reject ledger, and every boundary state
        handed to ``boundary_fn`` are identical to depth 1."""
        if not coordinates:
            raise ValueError("CoordinateDescent needs at least one coordinate")
        if n_iterations < 1:
            raise ValueError(f"n_iterations must be >= 1: {n_iterations}")
        # checkInvariants (CoordinateDescent.scala:71-92): locked coordinates
        # must not be retrained; with a single coordinate multiple iterations
        # are pointless (reference logs a warning).
        if validation_frequency not in ("COORDINATE", "SWEEP"):
            raise ValueError(
                f"validation_frequency must be COORDINATE or SWEEP: "
                f"{validation_frequency!r}"
            )
        if rejection_tolerance is not None and rejection_tolerance < 0:
            raise ValueError(
                f"rejection_tolerance must be >= 0: {rejection_tolerance}"
            )
        if pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1: {pipeline_depth}")
        self.coordinates = dict(coordinates)
        self.order = list(coordinates)
        self.n_iterations = n_iterations
        self.validation = validation
        self.checkpoint_fn = checkpoint_fn
        self.validation_frequency = validation_frequency
        self.boundary_fn = boundary_fn
        self.resume_state = resume_state
        self.divergence_guard = divergence_guard
        self.rejection_tolerance = rejection_tolerance
        self.pipeline_depth = int(pipeline_depth)
        n_trainable = sum(
            0 if isinstance(c, ModelCoordinate) else 1 for c in self.coordinates.values()
        )
        if n_trainable == 0:
            raise ValueError("all coordinates are locked; nothing to train")
        if len(self.order) == 1 and n_iterations > 1:
            logger.warning(
                "single-coordinate descent with %d iterations is wasteful", n_iterations
            )

    def run(
        self, initial_models: Optional[Mapping[str, object]] = None
    ) -> CoordinateDescentResult:
        initial_models = dict(initial_models or {})
        coords = self.coordinates
        n = next(iter(coords.values())).n_rows
        for c in coords.values():
            if c.n_rows != n:
                raise ValueError(
                    f"coordinate {c.coordinate_id} has {c.n_rows} rows, expected {n}"
                )

        models: Dict[str, object] = {}
        trackers: Dict[str, object] = {}
        scores: Dict[str, jnp.ndarray] = {}
        train_losses: Dict[str, float] = {}
        start_it = 0
        start_idx = 0
        resume = self.resume_state
        if resume is not None:
            # restore the boundary state exactly: models come back verbatim,
            # per-coordinate scores re-derive from them (deterministic XLA →
            # bit-identical to what the dead process held), and the summed
            # scores restore from the snapshot so the incremental arithmetic
            # (summed - own + new) continues on the same values it would have
            # had uninterrupted
            models = dict(resume.models)
            for name in self.order:
                if name in models:
                    scores[name] = coords[name].score(models[name])
            summed = jnp.asarray(resume.summed_scores)
            evaluations = list(resume.evaluations)
            best_eval = resume.best_eval
            best_models = dict(resume.best_models)
            # older snapshots predate the divergence guard's regression
            # ledger — resume with an empty one (first accepted update of
            # each coordinate re-seeds it)
            train_losses = dict(getattr(resume, "train_losses", None) or {})
            start_it = int(resume.iteration)
            start_idx = int(resume.coordinate_index) + 1
            if start_idx >= len(self.order):
                start_it += 1
                start_idx = 0
        else:
            # initialize scores from warm-start models where available
            for name in self.order:
                if name in initial_models:
                    models[name] = initial_models[name]
                    scores[name] = coords[name].score(initial_models[name])
            zero = jnp.zeros((n,), jnp.float32)
            summed = sum(scores.values(), zero)

            evaluations = []
            best_eval = None
            best_models = dict(models)

        for it in range(start_it, self.n_iterations):
            first = start_idx if it == start_it else 0
            with obs.span(
                "cd.sweep", iteration=it, pipeline_depth=self.pipeline_depth
            ) as sweep_span:
                # background eval lane (depth >= 2, per-coordinate
                # validation): coordinate k's eval overlaps coordinate k+1's
                # solve; results drain in submit order, so the evaluation
                # ledger and best-model choices are the serial loop's
                lane = None
                lane_snaps: collections.deque = collections.deque()
                if (
                    self.pipeline_depth > 1
                    and self.validation is not None
                    and self.validation_frequency == "COORDINATE"
                    and _process_count() == 1
                ):
                    # multi-process runs keep validation eval on the main
                    # thread: every process must enqueue device computations
                    # (and any collectives hiding in sharded score fns) in
                    # the SAME order, and a background eval thread interleaves
                    # its dispatches nondeterministically against the solve
                    # stream — a cross-host ordering mismatch is a deadlock.
                    # Depth >= 2 still pipelines the score-sum dispatch ahead
                    # of the guard fetch and the streaming slice prefetch.
                    lane = pipeline.EvalLane(
                        self._evaluate,
                        capacity=self.pipeline_depth - 1,
                        anchor=sweep_span,
                    )

                def _absorb(drained):
                    nonlocal best_eval, best_models
                    for eit, ename, res in drained:
                        best_eval, best_models = self._absorb_eval(
                            eit,
                            ename,
                            res,
                            lane_snaps.popleft(),
                            evaluations,
                            best_eval,
                            best_models,
                        )

                # zero-fetch invariant, runtime-enforced: inside the sweep
                # every device->host transfer must be an explicit
                # jax.device_get (logged_fetch) — an implicit fetch
                # (float(arr), np.asarray(arr), arr.item()) raises instead of
                # silently stalling the device pipeline. The static half of
                # this contract is photon_ml_tpu.analysis rule R1.
                with pipeline.pipelined(
                    self.pipeline_depth, anchor=sweep_span
                ), pipeline.closing(lane), transfer_guard():
                    for idx in range(first, len(self.order)):
                        name = self.order[idx]
                        coordinate = coords[name]
                        own = scores.get(name)
                        residual = summed - own if own is not None else summed

                        # current-position board for /statusz scrapes: cheap
                        # host dict writes, live even with no sink registered
                        obs.current_run().status.update(
                            sweep=it,
                            n_sweeps=self.n_iterations,
                            coordinate=name,
                            coordinate_index=idx,
                        )
                        with obs.span("cd.coordinate", iteration=it, coordinate=name):
                            with timed(
                                f"cd iter {it} coordinate {name}: train",
                                phase="solve",
                                coordinate=name,
                            ):
                                model, solver_result = coordinate.train(
                                    residual, initial_model=models.get(name)
                                )
                            tracker = build_tracker(coordinate, solver_result)
                            if tracker is not None:
                                trackers[name] = tracker
                                # logOptimizationSummary (CoordinateDescent.scala:
                                # 230-248): per-coordinate convergence histogram /
                                # iteration stats. Gated: both the summary string
                                # and the metrics recording FETCH device arrays (a
                                # ~100ms+ pipeline stall per fetch on remote
                                # links); with INFO disabled and no telemetry sink
                                # the sweep stays fetch-free
                                if logger.isEnabledFor(logging.INFO):
                                    logger.info(
                                        "cd iter %d coordinate %s optimization "
                                        "summary:\n%s",
                                        it,
                                        name,
                                        tracker.to_summary_string(),
                                    )
                                if obs.active():
                                    record_tracker_metrics(
                                        obs.current_run().registry, name, tracker
                                    )

                            with timed(
                                f"cd iter {it} coordinate {name}: score",
                                phase="score",
                                coordinate=name,
                            ):
                                new_scores = coordinate.score(model)
                            if faults.active():
                                # fault site coordinate.scores: the schedule
                                # decision is host-side (eager, never traced)
                                # and the planting is a pure device scatter —
                                # legal under the sweep's transfer guard
                                new_scores = faults.corrupt(
                                    "coordinate.scores", new_scores
                                )
                            # depth >= 2: dispatch the accepted-score sum
                            # BEFORE the guard's blocking fetch — async
                            # dispatch queues the add behind the scores, the
                            # fetch overlaps it, and a rejection simply drops
                            # the candidate (models/scores/summed untouched,
                            # same op and operands as the serial add →
                            # bit-identical on accept)
                            candidate = (
                                residual + new_scores
                                if self.pipeline_depth > 1
                                else None
                            )
                            accepted, train_loss = (
                                self._guard(
                                    name, new_scores, solver_result, train_losses
                                )
                                if self.divergence_guard
                                else (True, None)
                            )
                            if accepted:
                                models[name] = model
                                # summedScores - oldScores + newScores (:441-446)
                                summed = (
                                    candidate
                                    if candidate is not None
                                    else residual + new_scores
                                )
                                scores[name] = new_scores
                                if train_loss is not None:
                                    train_losses[name] = train_loss
                                    # cheap host registry write (the loss
                                    # already traveled in the guard's fetch):
                                    # per-sweep JSONL flushes turn this gauge
                                    # into the accepted-loss trajectory the
                                    # post-hoc report plots
                                    obs.current_run().registry.gauge(
                                        "photon_cd_accepted_loss",
                                        "last accepted total train loss per "
                                        "coordinate",
                                    ).labels(coordinate=name).set(train_loss)
                                    obs.current_run().status.update(
                                        accepted_losses={
                                            k: float(v)
                                            for k, v in train_losses.items()
                                        }
                                    )

                                if (
                                    self.validation is not None
                                    and self.validation_frequency == "COORDINATE"
                                ):
                                    if lane is not None:
                                        snapshot = dict(models)
                                        lane_snaps.append(snapshot)
                                        lane.submit(it, name, snapshot)
                                        _absorb(lane.drain_ready())
                                    else:
                                        best_eval, best_models = self._track_best(
                                            models, evaluations, best_eval, best_models, it, name
                                        )
                            else:
                                # quarantine the update: models / scores /
                                # summed were never touched, so the sweep
                                # continues exactly as if this train had not
                                # happened (a never-yet-trained coordinate
                                # simply stays untrained until its next turn);
                                # no re-evaluation either — the GAME model is
                                # unchanged
                                self._reject(it, name)
                        if self.boundary_fn is not None:
                            # coordinate-update boundary: the only point where
                            # the outer-loop state is consistent and host-
                            # reachable. Serialization fetches device arrays,
                            # so lift the transfer guard for exactly this call
                            # — a checkpoint is a deliberate sync point.
                            # In-flight evals drain first: the boundary state
                            # must embed the same evaluations/best ledger the
                            # serial loop would have at this exact update.
                            if lane is not None:
                                _absorb(lane.drain_all())
                            with allow_transfers(), obs.span(
                                "cd.checkpoint", phase="checkpoint", coordinate=name
                            ):
                                self.boundary_fn(
                                    CDBoundaryState(
                                        iteration=it,
                                        coordinate_index=idx,
                                        coordinate=name,
                                        coordinate_order=list(self.order),
                                        n_iterations=self.n_iterations,
                                        models=dict(models),
                                        summed_scores=summed,
                                        best_eval=best_eval,
                                        best_models=dict(best_models),
                                        evaluations=list(evaluations),
                                        trackers=dict(trackers),
                                        train_losses=dict(train_losses),
                                    )
                                )
                    if lane is not None:
                        # sweep end is a serial point: everything submitted
                        # this sweep lands in the ledger before the sweep
                        # span closes (and before any sweep checkpoint)
                        _absorb(lane.drain_all())
                    if self.validation is not None and self.validation_frequency == "SWEEP":
                        best_eval, best_models = self._track_best(
                            models, evaluations, best_eval, best_models, it, self.order[-1]
                        )
                # checkpointing runs OUTSIDE the guard: serializers fetch
                # model arrays however they like (np.asarray included), and a
                # checkpoint is a deliberate pipeline sync point anyway
                if self.checkpoint_fn is not None:
                    with obs.span("cd.checkpoint", phase="checkpoint"):
                        self.checkpoint_fn(it, dict(models))
            # sweep-boundary liveness rendezvous: in a distributed run every
            # process must reach the end of the sweep within the collective
            # budget — a dead peer surfaces here as a typed timeout instead
            # of a hang inside next sweep's collectives. Also the once-per-
            # sweep `dist.collective` fault site (the kill-a-worker drill).
            robust_dist.sweep_barrier(it)
            # memory watermarks at the sweep boundary (host RSS via /proc,
            # device HBM via memory_stats when the backend has it): cheap
            # host-only reads, recorded with or without a sink so the peaks
            # land in run_summary.json for every run
            obs.sample_memory(
                obs.current_run().registry, devices=_local_devices()
            )
            if obs.active():
                # one metrics line per sweep in the JSONL stream
                obs.current_run().flush_metrics()

        final_models = best_models if best_eval is not None else models
        task = self._infer_task()
        return CoordinateDescentResult(
            model=GameModel(models=final_models, task=task),
            evaluations=evaluations,
            best_evaluation=best_eval,
            trackers=trackers,
        )

    def _guard(self, name, new_scores, solver_result, train_losses):
        """Decide whether a freshly trained coordinate update is numerically
        sound: one scalar :func:`logged_fetch` per update (the finiteness
        flag and total train loss travel in the same fetch).

        Accepts unless (a) any new score is non-finite, (b) the solver's
        total loss is non-finite (a born-corrupt solve: divergence at
        initialization leaves no good iterate to roll back to), or (c)
        ``rejection_tolerance`` is set and the loss regressed beyond it.
        Returns ``(accepted, train_loss)``; ``train_loss`` is None for
        locked coordinates (no solver result), which keeps the regression
        ledger scoped to real solves."""
        finite_dev = jnp.all(jnp.isfinite(new_scores))
        if solver_result is None:
            ok = bool(logged_fetch("cd.update_guard", finite_dev))
            return ok, None
        finite_h, loss_h = logged_fetch(
            "cd.update_guard", (finite_dev, jnp.sum(solver_result.loss))
        )
        if not bool(finite_h):
            return False, None
        loss = float(loss_h)
        if not np.isfinite(loss):
            return False, None
        prev = train_losses.get(name)
        tol = self.rejection_tolerance
        if tol is not None and prev is not None and loss > prev + tol:
            return False, None
        return True, loss

    def _reject(self, it: int, name: str) -> None:
        # cheap host-only registry work, recorded with or without a sink
        # (same contract as obs.swallowed_error) — rejections must be visible
        # in run_summary.json even for runs that never attach a listener
        obs.current_run().registry.counter(
            "photon_coordinate_rejections_total",
            "coordinate updates rejected by the divergence guard",
        ).labels(coordinate=name).inc()
        logger.warning(
            "cd iter %d coordinate %s: update REJECTED (non-finite scores/"
            "loss or objective regression); previous model stands",
            it,
            name,
        )

    def _track_best(self, models, evaluations, best_eval, best_models, it, name):
        with obs.span("cd.eval", phase="eval", iteration=it, coordinate=name):
            res = self._evaluate(models)
        return self._absorb_eval(
            it, name, res, models, evaluations, best_eval, best_models
        )

    def _absorb_eval(self, it, name, res, snapshot, evaluations, best_eval, best_models):
        """Fold one evaluation result into the ledger: the serial loop calls
        this right after evaluating; the pipelined loop calls it when the
        eval lane drains (same submit order → same ledger). ``snapshot`` is
        the models dict AS OF the evaluated update."""
        evaluations.append((name, res))
        primary = self.validation.suite.primary
        # only snapshots with every coordinate trained are candidates for
        # "best model" — a mid-first-sweep partial model is not a valid GAME
        # model
        complete = len(snapshot) == len(self.order)
        if complete and (
            best_eval is None
            or primary.better(res.primary_metric, best_eval.primary_metric)
        ):
            best_eval = res
            best_models = dict(snapshot)
        if obs.active():
            # res.metrics values are already host floats — no extra fetch
            gauge = obs.current_run().registry.gauge(
                "photon_validation_metric", "validation metric after an update"
            )
            for metric, value in res.metrics.items():
                gauge.labels(metric=metric, coordinate=name).set(float(value))
        logger.info("cd iter %d coordinate %s: %s", it, name, res.metrics)
        return best_eval, best_models

    def _infer_task(self) -> str:
        """Task from the coordinate definitions (every trainable coordinate
        carries it; locked ModelCoordinates delegate to their inner)."""
        for c in self.coordinates.values():
            inner = c.inner if isinstance(c, ModelCoordinate) else c
            task = getattr(inner, "task", None)
            if task:
                return task
        return "linear_regression"

    def _evaluate(self, models: Mapping[str, object]) -> EvaluationResults:
        """Accumulate per-coordinate validation scores on device and, when
        every metric has a device implementation, evaluate there too — one
        scalar fetch per update instead of a score-vector transfer plus host
        sorts (evaluation/device.py). Grouped/ranking metrics fall back to
        the host path."""
        v = self.validation
        acc = None
        for name, model in models.items():
            fn = v.score_fns.get(name)
            if fn is not None:
                s = fn(model)
                acc = s if acc is None else acc + s
        if acc is not None:
            total_dev = acc + jnp.asarray(v.offsets, acc.dtype)
            res = v.suite.evaluate_device(total_dev)
            if res is not None:
                return res
        total = np.asarray(v.offsets, dtype=np.float64)
        if acc is not None:
            total = total + np.asarray(
                logged_fetch("cd.validation_scores", acc), dtype=np.float64
            )
        return v.suite.evaluate(total)
