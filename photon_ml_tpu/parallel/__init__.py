from . import multihost
from .mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    data_parallel_mesh,
    make_mesh,
    pad_rows_for_mesh,
    replicate,
    shard_batch,
    shard_coefficients,
    shard_entity_blocks,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "data_parallel_mesh",
    "pad_rows_for_mesh",
    "shard_batch",
    "shard_coefficients",
    "shard_entity_blocks",
    "replicate",
    "multihost",
]
