"""(data x model)-tiled sparse feature matrix: the huge-d fixed-effect path.

This is the TPU answer to the reference's claim of scaling to "hundreds of
billions of coefficients" (/root/reference/README.md:56) for the *fixed
effect*: the coefficient vector is sharded over a "model" mesh axis and the
sample rows over a "data" axis, so the batch gradient

    g = X^T c     (ValueAndGradientAggregator.scala:137-161's hot axpy loop)

becomes, per device tile, a local sorted scatter over that device's column
range followed by a psum over the data axis — the exact analogue of the
reference's treeAggregate all-reduce (SURVEY.md P1), with the model axis
adding what Spark never had: a partitioned coefficient vector.

Why tiling (and not GSPMD auto-sharding): unstructured gather/scatter on TPU
executes serially at ~7 cycles/element (measured on v5e; there is no HBM
cache and pre-SparseCore hardware has no vectorized large-table gather), so
the single-chip sparse kernel is serialization-bound. Partitioning the nnz by
(row-range, column-range) divides that serial cost by the device count on
both the gather (c by row) and scatter (g by column) sides — sparse
throughput scales linearly with chips, which is the property that matters at
pod scale. Collectives ride ICI: z partials psum over the model axis,
gradient partials psum over the data axis.

Layout contract per tile (host-built, static): triplets sorted by local
column (so the rmatvec scatter runs XLA's sorted fast path and the column
axis partitions contiguously); padding entries carry lcol = d_local - 1,
lval = 0, lrow = 0.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # pre-0.6 jax ships it under experimental only
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.features import LabeledBatch
from .mesh import DATA_AXIS, MODEL_AXIS

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class TiledSparseMatrix:
    """FeatureMatrix-compatible sparse matrix tiled over a (data, model) mesh.

    Arrays are [n_data, n_model, m_tile], sharded P(data, model, None): each
    device holds exactly its tile. ``dim`` / ``n_rows`` are the padded global
    sizes (multiples of the mesh axes).
    """

    dim: int = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    mesh: Mesh = dataclasses.field(metadata=dict(static=True))
    lcol: Optional[Array] = None  # i32[D, M, m_tile], sorted per tile
    lrow: Optional[Array] = None  # i32[D, M, m_tile]
    lval: Optional[Array] = None  # f[D, M, m_tile]
    # the UNPADDED feature dim (0 = unknown): lets consumers distinguish
    # structural mesh padding from real-but-inactive features
    dim_true: int = dataclasses.field(default=0, metadata=dict(static=True))

    @property
    def layout(self) -> str:
        return "tiled"

    @property
    def is_dense(self) -> bool:
        return False

    @property
    def n_local_rows(self) -> int:
        return self.n_rows // self.mesh.shape[DATA_AXIS]

    @property
    def d_local(self) -> int:
        return self.dim // self.mesh.shape[MODEL_AXIS]

    def matvec(self, w: Array) -> Array:
        """x @ w -> [n] (sharded over data). w: [dim], sharded over model."""
        n_loc = self.n_local_rows

        def f(lcol, lrow, lval, w_loc):
            lc, lr, lv = lcol[0, 0], lrow[0, 0], lval[0, 0]
            wv = jnp.take(w_loc, lc) * lv
            z = jnp.zeros(n_loc, wv.dtype).at[lr].add(wv)
            return jax.lax.psum(z, MODEL_AXIS)

        return shard_map(
            f,
            mesh=self.mesh,
            in_specs=(
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS, MODEL_AXIS, None),
                P(MODEL_AXIS),
            ),
            out_specs=P(DATA_AXIS),
        )(self.lcol, self.lrow, self.lval, w)

    def _rmat(self, c: Array, square: bool) -> Array:
        d_loc = self.d_local

        def f(lcol, lrow, lval, c_loc):
            lc, lr, lv = lcol[0, 0], lrow[0, 0], lval[0, 0]
            if square:
                lv = lv * lv
            contrib = jnp.take(c_loc, lr) * lv
            g = jnp.zeros(d_loc, contrib.dtype).at[lc].add(
                contrib, indices_are_sorted=True
            )
            return jax.lax.psum(g, DATA_AXIS)

        return shard_map(
            f,
            mesh=self.mesh,
            in_specs=(
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS),
            ),
            out_specs=P(MODEL_AXIS),
        )(self.lcol, self.lrow, self.lval, c)

    def rmatvec(self, c: Array) -> Array:
        """x^T @ c -> [dim] (sharded over model). c: [n], sharded over data."""
        return self._rmat(c, square=False)

    def sq_rmatvec(self, c: Array) -> Array:
        return self._rmat(c, square=True)

    def to_dense(self) -> Array:
        # photon: ignore[R10] — internal API guard on a layout class, not a
        # user-facing configuration refusal; the supported paths are named
        # in the message, and no config combination routes here
        raise NotImplementedError(
            "TiledSparseMatrix is for huge d; densification is not supported "
            "(use variance_type SIMPLE, or FULL which runs the chunked "
            "sharded xtcx path without materializing X)"
        )

    def xtcx(self, c: Array, row_chunk: Optional[int] = None) -> Array:
        """X^T diag(c) X -> [dim, dim], sharded over the model axis on dim 0:
        the FULL-variance Hessian on the tiled layout
        (reference: HessianMatrixAggregator.scala:92-128 — per-partition outer
        products tree-aggregated; here per-tile chunked outer products psum'd
        over the data axis).

        Each device scans its rows in ``row_chunk`` windows: densify the local
        (chunk x d_local) tile, all-gather the chunk's full feature rows over
        the model axis, and accumulate the device's [d_local, dim] Hessian
        row-block — so peak memory is O(row_chunk * dim + d_local * dim), never
        O(n * dim). The dim ceiling is enforced by the caller
        (ops/glm.py: MAX_FULL_VARIANCE_DIM) since [dim, dim] must be
        invertible on one device afterwards.

        Cost note: every scan step masks the tile's whole nnz array (entries
        are column-sorted for rmatvec's fast path, so a chunk's rows are not
        contiguous), i.e. scatter work is O(m_tile * n_chunks). To bound that
        multiplier, the DEFAULT ``row_chunk`` (None) is auto-raised so
        n_chunks <= 64 as long as the chunk's gathered rows stay under
        ~256 MB — a once-per-train trade of memory for the serialized-scatter
        constant. An explicitly passed ``row_chunk`` is respected as-is so
        memory-constrained callers can cap the peak below the heuristic.
        """
        d_loc, n_loc = self.d_local, self.n_local_rows
        if row_chunk is None:
            row_itemsize = np.dtype(self.lval.dtype).itemsize
            mem_cap_rows = max(
                (256 << 20) // (row_itemsize * max(self.dim, 1)), 1024
            )
            row_chunk = max(4096, min(-(-n_loc // 64), mem_cap_rows))
        chunk = min(row_chunk, n_loc)
        n_chunks = -(-n_loc // chunk)
        n_pad = n_chunks * chunk
        dim = self.dim

        def f(lcol, lrow, lval, c_loc):
            lc, lr, lv = lcol[0, 0], lrow[0, 0], lval[0, 0]
            c_pad = jnp.pad(c_loc, (0, n_pad - n_loc))

            def body(h, k):
                start = k * chunk
                in_r = (lr >= start) & (lr < start + chunk)
                xt = (
                    jnp.zeros((chunk, d_loc), lv.dtype)
                    .at[jnp.where(in_r, lr - start, 0), lc]
                    .add(jnp.where(in_r, lv, 0.0))
                )
                xg = jax.lax.all_gather(xt, MODEL_AXIS, axis=1, tiled=True)
                cc = jax.lax.dynamic_slice_in_dim(c_pad, start, chunk)
                return h + xt.T @ (cc[:, None] * xg), None

            h0 = jax.lax.pcast(
                jnp.zeros((d_loc, dim), lv.dtype),
                (DATA_AXIS, MODEL_AXIS),
                to="varying",
            )
            h, _ = jax.lax.scan(body, h0, jnp.arange(n_chunks))
            return jax.lax.psum(h, DATA_AXIS)

        return shard_map(
            f,
            mesh=self.mesh,
            in_specs=(
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS, MODEL_AXIS, None),
                P(DATA_AXIS),
            ),
            out_specs=P(MODEL_AXIS, None),
        )(self.lcol, self.lrow, self.lval, c)


def tile_sparse_matrix(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    n_rows: int,
    dim: int,
    mesh: Mesh,
    dtype=jnp.float32,
) -> TiledSparseMatrix:
    """Host-side one-time tiling (the analogue of the reference's dataset
    partitioning shuffle, SURVEY.md P13). Pads n and d to mesh multiples and
    each tile's nnz to the max tile size.

    Multi-process: ``rows``/``n_rows`` are this process's LOCAL row slice.
    Each process owns a contiguous block of the data axis with every model
    column, so tiles build locally from local COO — the only cross-host
    agreement is the max tile size (one scalar allgather). The global row
    space is the concatenation of the per-process padded slices, matching
    the padded global sample space of the other coordinates.
    """
    from . import multihost

    D = mesh.shape[DATA_AXIS]
    M = mesh.shape[MODEL_AXIS]
    n_proc = jax.process_count()
    if D % n_proc != 0:
        raise ValueError(
            f"tiled layout: data axis ({D}) must divide evenly across "
            f"{n_proc} processes"
        )
    D_local = D // n_proc
    # pad LOCAL rows to the local share of the data axis; the global padded
    # row count is the sum of the (equal) per-process shares
    n_loc_rows = max(((n_rows + D_local - 1) // D_local) * D_local, D_local)
    n_pad = n_loc_rows * n_proc
    d_pad = max(((dim + M - 1) // M) * M, M)
    n_loc, d_loc = n_loc_rows // D_local, d_pad // M

    tile_r = rows // n_loc
    tile_c = cols // d_loc
    key = tile_r * M + tile_c
    order = np.lexsort((cols, key))
    r_s, c_s, v_s, k_s = rows[order], cols[order], vals[order], key[order]
    counts = np.bincount(k_s, minlength=D_local * M)
    m_local = max(int(counts.max()) if len(counts) else 0, 1)
    m_tile = max(t for t in multihost.allgather_object(m_local))

    lcol = np.full((D_local * M, m_tile), d_loc - 1, dtype=np.int32)
    lrow = np.zeros((D_local * M, m_tile), dtype=np.int32)
    lval = np.zeros((D_local * M, m_tile), dtype=np.float64)
    if len(k_s):
        starts = np.cumsum(np.concatenate([[0], counts[:-1]]))
        within = np.arange(len(k_s)) - starts[k_s]
        lcol[k_s, within] = c_s % d_loc
        lrow[k_s, within] = r_s % n_loc
        lval[k_s, within] = v_s

    spec = P(DATA_AXIS, MODEL_AXIS, None)
    put = lambda a: multihost.put_global(a, mesh, spec)
    return TiledSparseMatrix(
        dim=d_pad,
        n_rows=n_pad,
        mesh=mesh,
        lcol=put(lcol.reshape(D_local, M, m_tile)),
        lrow=put(lrow.reshape(D_local, M, m_tile)),
        lval=put(lval.reshape(D_local, M, m_tile).astype(np.dtype(dtype))),
        dim_true=dim,
    )


def tiled_sparse_batch(
    rows: np.ndarray,
    cols: np.ndarray,
    vals: np.ndarray,
    y: np.ndarray,
    dim: int,
    mesh: Mesh,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    dtype=jnp.float32,
) -> LabeledBatch:
    """Build a LabeledBatch whose features are mesh-tiled; labels/offsets/
    weights are zero-padded to the mesh row multiple and sharded over the
    data axis (padded rows carry weight 0)."""
    from . import multihost

    n = len(y)
    feats = tile_sparse_matrix(rows, cols, vals, n, dim, mesh, dtype=dtype)
    # per-process local share of the padded global row space
    n_loc_pad = feats.n_rows // jax.process_count()

    def pad1(a, fill=0.0):
        out = np.full(n_loc_pad, fill, dtype=np.float64)
        out[:n] = a
        return multihost.put_global(
            np.asarray(out, np.dtype(dtype)), mesh, P(DATA_AXIS)
        )

    return LabeledBatch(
        features=feats,
        labels=pad1(y),
        offsets=pad1(np.zeros(n) if offsets is None else offsets),
        weights=pad1(np.ones(n) if weights is None else weights, fill=0.0),
    )


def replicated_coefficients(w: np.ndarray, mesh: Mesh, dtype=jnp.float32) -> Array:
    """Place a [dim]-padded coefficient vector sharded over the model axis
    (multi-process: every process passes the full host vector and contributes
    its devices' slices)."""
    from . import multihost

    return multihost.put_global_from_full(
        np.asarray(w, np.dtype(dtype)), mesh, P(MODEL_AXIS)
    )
