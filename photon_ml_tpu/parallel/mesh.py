"""Device mesh + sharding helpers: the distributed runtime layer.

This replaces the reference's Spark wrappers (SURVEY.md §2.1 / L1:
RDDLike/BroadcastLike, treeAggregate, broadcast, partitioner-aware joins)
with JAX sharding primitives:

- ``treeAggregate`` of gradient accumulators  -> jit over a batch sharded on
  the DATA axis; ``jnp.sum``/``rmatvec`` reductions lower to ICI all-reduces.
- coefficient ``broadcast``                   -> replicated NamedSharding.
- entity-partitioned random effects (P5)     -> entity blocks sharded on dim 0
  (each device owns an entity range); the vmapped solver is embarrassingly
  parallel across lanes.
- huge-d coefficient vectors                  -> shard the FEATURE axis on a
  second mesh dim ("model"); margins become partial dots + psum, gradients
  reduce-scatter (the analogue of scaling "hundreds of billions of
  coefficients", README.md:56).

Multi-host: `jax.distributed.initialize()` (parallel/multihost.py) + the same
code — collectives ride ICI within a slice and DCN across slices. Placement
helpers route through ``multihost.put_global``: single-process they are plain
``device_put``; multi-process each process contributes its local block (its
per-host row range / entity range) and the result is one globally-sharded
``jax.Array``. In multi-process mode every process must contribute equal
local shapes (pad per-host shares to ``multihost.equal_host_share``), and
only DATA-axis sharding is supported — model-axis sharding across processes
would need per-host coefficient slices and is rejected explicitly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.features import FeatureMatrix, LabeledBatch, pad_batch
from .multihost import put_global

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    n_data: Optional[int] = None, n_model: int = 1, devices=None
) -> Mesh:
    """Build a (data[, model]) mesh over available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = len(devices) // n_model
    use = n_data * n_model
    arr = np.asarray(devices[:use]).reshape(n_data, n_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def data_parallel_mesh(n: Optional[int] = None, devices=None) -> Mesh:
    return make_mesh(n_data=n, n_model=1, devices=devices)


def pad_rows_for_mesh(batch: LabeledBatch, mesh: Mesh) -> LabeledBatch:
    """Zero-weight-pad the batch so the row count divides the data axis
    (multi-process: the LOCAL row count must divide the local share of the
    data axis)."""
    n_data = mesh.shape[DATA_AXIS]
    if jax.process_count() > 1:
        n_data = max(n_data // jax.process_count(), 1)
    n = batch.n_rows
    target = ((n + n_data - 1) // n_data) * n_data
    return pad_batch(batch, target)


def shard_batch(
    batch: LabeledBatch, mesh: Mesh, shard_features_dim: bool = False
) -> LabeledBatch:
    """Place a batch on the mesh: rows sharded over the data axis; feature
    columns optionally sharded over the model axis (dense layout only)."""
    if getattr(batch.features, "layout", None) == "coo":
        raise NotImplementedError(
            "shard_batch does not support the column-sorted COO layout (its "
            "nnz axis is column-major, not row-partitionable); for a "
            "mesh-sharded huge-d batch build layout='tiled' "
            "(parallel.sparse.tiled_sparse_batch)"
        )
    batch = pad_rows_for_mesh(batch, mesh)
    row_spec = P(DATA_AXIS)
    put1 = lambda a: put_global(a, mesh, row_spec)
    f = batch.features
    if f.is_dense:
        if shard_features_dim:
            _reject_multiprocess_model_axis()
        spec = P(DATA_AXIS, MODEL_AXIS if shard_features_dim else None)
        feats = FeatureMatrix(dim=f.dim, dense=put_global(f.dense, mesh, spec))
    else:
        if jax.process_count() > 1:
            raise NotImplementedError(
                "multi-process ELL sharding is not supported: the ELL width "
                "is the max nnz of the LOCAL rows, so per-host shapes (and "
                "the compiled programs) would disagree; use a dense layout "
                "(d <= 4096) for multi-process runs"
            )
        spec = P(DATA_AXIS, None)
        feats = FeatureMatrix(
            dim=f.dim,
            idx=put_global(f.idx, mesh, spec),
            val=put_global(f.val, mesh, spec),
        )
    return LabeledBatch(
        features=feats,
        labels=put1(batch.labels),
        offsets=put1(batch.offsets),
        weights=put1(batch.weights),
    )


def replicate(tree, mesh: Mesh):
    """Replicated placement (the reference's coefficient broadcast, P4).
    Multi-process: every process must hold the full (identical) array."""
    return jax.tree_util.tree_map(lambda a: put_global(a, mesh, P()), tree)


def _reject_multiprocess_model_axis():
    if jax.process_count() > 1:
        raise NotImplementedError(
            "model-axis sharding across processes is not supported yet: "
            "callers pass full arrays, but each process may only contribute "
            "its own model-axis slice; multi-process runs shard the data "
            "axis only"
        )


def shard_coefficients(w: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Shard a coefficient vector over the model axis (huge-d regime)."""
    _reject_multiprocess_model_axis()
    return put_global(w, mesh, P(MODEL_AXIS))


def shard_entity_blocks(blocks, mesh: Mesh):
    """Shard EntityBlocks on the entity dim over the data axis (P5)."""
    n_data = mesh.shape[DATA_AXIS]
    E = blocks.features.shape[0]
    if E % n_data != 0:
        raise ValueError(
            f"entity count {E} must divide the data axis ({n_data}); "
            f"build the dataset with pad_entities_to_multiple={n_data}"
        )

    def put(a):
        spec = P(*([DATA_AXIS] + [None] * (a.ndim - 1)))
        return put_global(a, mesh, spec)

    return jax.tree_util.tree_map(put, blocks)
