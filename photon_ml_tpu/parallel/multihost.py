"""Multi-host (multi-process) runtime scaffolding.

The reference's cluster dimension is Spark executors + treeAggregate
(GameEstimator.scala:703 treeAggregateDepth); here it is JAX multi-process:
``jax.distributed.initialize`` connects P processes (one per host), each
process reads ITS OWN row range of the input (per-host IO, the analogue of
executors reading their HDFS splits), builds process-local arrays, and
assembles them into globally-sharded ``jax.Array``s with
``jax.make_array_from_process_local_data``. The jitted objective is unchanged
— XLA collectives ride ICI within a slice and DCN across slices.

Single-process behavior is identical to before: every helper degrades to the
local path when ``jax.process_count() == 1``.

A two-process CPU smoke test lives in ``tests/test_multihost.py`` (each
process gets 4 virtual CPU devices -> a global 8-device mesh); run it
directly with::

    python -m pytest tests/test_multihost.py -q
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """``jax.distributed.initialize`` entry path (no-op when single-process
    args are absent and no cluster env is configured).

    With no arguments, auto-detection (SLURM/TPU metadata/env vars) applies;
    explicit args support the 'coordinator=HOST:PORT,process=I,n=P' CLI spec.
    """
    # must not touch the XLA backend before initialize (jax.process_count()
    # would); is_initialized only reads coordination-service state. Older
    # jax (< 0.5) has no is_initialized — fall back to the client handle.
    _is_init = getattr(jax.distributed, "is_initialized", None)
    if _is_init is not None:
        if _is_init():
            return
    else:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def initialize_from_spec(spec: str) -> None:
    """Parse 'coordinator=HOST:PORT,process=I,n=P' and initialize."""
    parts = dict(p.split("=", 1) for p in spec.split(",") if p)
    unknown = set(parts) - {"coordinator", "process", "n"}
    if unknown:
        raise ValueError(
            f"unknown --distributed keys {sorted(unknown)}; "
            "expected coordinator=HOST:PORT,process=I,n=P"
        )
    initialize(
        coordinator_address=parts.get("coordinator"),
        num_processes=int(parts["n"]) if "n" in parts else None,
        process_id=int(parts["process"]) if "process" in parts else None,
    )


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    """True on process 0 — the only process that writes models/summaries
    (the reference's driver-writes-to-HDFS role)."""
    return jax.process_index() == 0


def host_row_range(
    n_rows: int, index: Optional[int] = None, count: Optional[int] = None
) -> Tuple[int, int]:
    """This process's contiguous [start, stop) slice of a global row count
    (per-host input split; balanced to within one row)."""
    i = process_index() if index is None else index
    p = process_count() if count is None else count
    base, rem = divmod(n_rows, p)
    start = i * base + min(i, rem)
    stop = start + base + (1 if i < rem else 0)
    return start, stop


def put_global(local: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """Assemble a globally-sharded array from per-process local data.

    Single-process: plain ``device_put``. Multi-process: the local block is
    this process's slice along the sharded dims
    (``jax.make_array_from_process_local_data``).
    """
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(local, sharding)
    return jax.make_array_from_process_local_data(sharding, np.asarray(local))


def host_local_rows(arr: jax.Array) -> np.ndarray:
    """This process's contiguous block of a dim-0-sharded global array, as
    host numpy (the inverse of :func:`put_global` for the local slice).

    The streamed+sharded routing uses this to hand each host ITS rows /
    entities of a global array for host-resident streaming: addressable
    shards are concatenated in dim-0 index order, so the result is exactly
    the local block this process contributed. Replicated (or single-process)
    arrays come back whole."""
    shards = sorted(
        arr.addressable_shards, key=lambda s: (s.index[0].start or 0)
    )
    parts = []
    seen = set()
    for s in shards:
        key = (s.index[0].start or 0, s.index[0].stop)
        if key in seen:  # replicated over other axes: one copy per block
            continue
        seen.add(key)
        parts.append(jax.device_get(s.data))
    return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)


def equal_host_share(n_rows: int, count: Optional[int] = None) -> int:
    """The common per-host row count every process pads its share to:
    ``ceil(n_rows / P)``. All hosts must contribute equal local shapes to
    ``make_array_from_process_local_data``; ``host_row_range`` splits to
    within one row, so hosts pad their slice to this size (zero-weight rows,
    invisible to the objectives)."""
    p = process_count() if count is None else count
    return -(-n_rows // p)


def allgather_object(obj):
    """Gather one picklable object per process; returns the process-ordered
    list on every process (single-process: ``[obj]``).

    The payload rides the device collective fabric (ICI/DCN) via
    ``multihost_utils.process_allgather`` — two rounds: sizes, then
    max-size-padded uint8 payloads. Meant for *planning metadata* (entity
    tables, shape agreements — the analogue of the reference collecting
    (entityId -> count) to the driver, RandomEffectDatasetPartitioner.scala:
    117-180), NOT for bulk row data, which stays in globally-sharded arrays.
    """
    if jax.process_count() == 1:
        return [obj]
    import pickle

    from jax.experimental import multihost_utils

    from ..robust import distributed as robust_dist

    # bounded-time rendezvous before the blocking collective: if any peer is
    # dead this raises a typed DistributedTimeoutError within the armed
    # budget instead of hanging in process_allgather forever (no-op unarmed)
    robust_dist.guard_collective("allgather_object")
    payload = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    sizes = multihost_utils.process_allgather(
        np.asarray([payload.size], np.int64)
    ).reshape(-1)
    padded = np.zeros(int(sizes.max()), np.uint8)
    padded[: payload.size] = payload
    gathered = multihost_utils.process_allgather(padded)
    return [
        pickle.loads(gathered[i, : int(sizes[i])].tobytes())
        for i in range(jax.process_count())
    ]


def broadcast_object(obj):
    """One-to-all broadcast of a picklable object FROM the coordinator
    (process 0); non-coordinators' ``obj`` is ignored. Unlike
    :func:`allgather_object` (p padded copies per host), this ships exactly
    one copy — use it for coordinator-owned payloads like checkpointed
    models. Single-process: returns ``obj`` unchanged."""
    if jax.process_count() == 1:
        return obj
    import pickle

    from jax.experimental import multihost_utils

    from ..robust import distributed as robust_dist

    robust_dist.guard_collective("broadcast_object")
    payload = (
        np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
        if jax.process_index() == 0
        else np.zeros(0, np.uint8)
    )
    size = int(
        multihost_utils.broadcast_one_to_all(
            np.asarray([payload.size], np.int64)
        )[0]
    )
    padded = np.zeros(size, np.uint8)
    padded[: payload.size] = payload[:size]
    data = multihost_utils.broadcast_one_to_all(padded)
    # broadcast_one_to_all may hand the psum result back in a promoted
    # integer dtype (uint8 -> int64 under x64); reinterpreting THAT buffer
    # as bytes interleaves zeros into the pickle stream — cast back first
    return pickle.loads(np.asarray(data).astype(np.uint8).tobytes())


@functools.lru_cache(maxsize=32)
def _replicate_fn(sharding: NamedSharding):
    # cached per sharding: jit keys on function identity, so a fresh lambda
    # per call would retrace/recompile the all-gather every invocation
    return jax.jit(lambda t: t, out_shardings=sharding)


def reshard(tree, mesh: Mesh, spec: P):
    """Device-side reshard via a cached jitted identity — no host round trip
    (multi-process: inputs may be process-local/uncommitted arrays holding
    identical values on every host, e.g. a freshly built coefficient vector;
    the jit places them under `spec` with collectives as needed)."""
    return _replicate_fn(NamedSharding(mesh, spec))(tree)


def fully_replicate(tree, mesh: Mesh):
    """Reshard a pytree of (possibly non-addressable, e.g. entity-sharded)
    global arrays to fully-replicated — an XLA all-gather — so every process
    can ``np.asarray`` the result (model saving, host-side trackers: the
    reference's collect-model-to-driver step). Single-process: identity."""
    if jax.process_count() == 1:
        return tree
    return _replicate_fn(NamedSharding(mesh, P()))(tree)


def put_global_from_full(full: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """Place an array every process holds IN FULL onto the mesh with `spec`
    (each process contributes the shards its devices own). The complement of
    ``put_global``, which takes per-process *local* blocks."""
    sharding = NamedSharding(mesh, spec)
    full = np.asarray(full)
    if jax.process_count() == 1:
        return jax.device_put(full, sharding)
    return jax.make_array_from_callback(full.shape, sharding, lambda idx: full[idx])
