// Native Avro -> columnar decoder: the host-side IO hot path.
//
// The reference's executors spend their ingest time in AvroDataReader
// (photon-client .../data/avro/AvroDataReader.scala:54-490) decoding Avro
// rows into per-shard sparse vectors. Here the equivalent hot loop — Object
// Container File blocks -> columnar arrays — is C++: a generic Avro binary
// interpreter driven by a compact "schema program" compiled on the Python
// side from the file's writer schema (photon_ml_tpu/native/__init__.py).
//
// Outputs (all grow-only buffers returned via the C ABI, freed by
// pr_free):
//   - numeric per-row columns (response/offset/weight candidates), NaN for
//     absent/null
//   - (row, string) pairs for uid / top-level id-tag columns and for
//     requested metadataMap keys
//   - per feature bag: row indices + name/term string arenas + double values
//
// Supports codecs null and deflate (raw zlib, wbits=-15) and a [start, stop)
// row window whose out-of-window blocks are skipped without inflating.
//
// Build: g++ -O3 -shared -fPIC decoder.cpp -o _photon_native.so -lz

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

#include <zlib.h>

namespace {

// schema-program opcodes (must match native/__init__.py)
enum Op {
  OP_NULL = 0,
  OP_BOOL = 1,
  OP_INT = 2,
  OP_LONG = 3,
  OP_FLOAT = 4,
  OP_DOUBLE = 5,
  OP_BYTES = 6,
  OP_STRING = 7,
  OP_RECORD = 8,
  OP_ENUM = 9,
  OP_FIXED = 10,
  OP_ARRAY = 11,
  OP_MAP = 12,
  OP_UNION = 13,
};

constexpr int32_t SINK_NONE = -1;
// sink id spaces: [0, STR_SINK_BASE) numeric per-row columns,
// [STR_SINK_BASE, BAG_SINK_BASE) per-row string columns,
// [BAG_SINK_BASE, ...) bag slots (name=base+3b, term=+1, value=+2)
constexpr int32_t STR_SINK_BASE = 500;
constexpr int32_t BAG_SINK_BASE = 1000;

struct StrPairs {           // (row, string) capture for a per-row column
  std::vector<int64_t> rows;
  std::vector<int64_t> offsets{0};
  std::string bytes;
  void push(int64_t row, const char* p, int64_t n) {
    rows.push_back(row);
    bytes.append(p, (size_t)n);
    offsets.push_back((int64_t)bytes.size());
  }
};

struct Bag {
  // one entry per feature triple; key_id indexes the interned unique keys
  std::vector<int64_t> rows;
  std::vector<int32_t> key_ids;
  std::vector<double> values;
  // interned feature keys: name + '\x01' + term (io/index_map.feature_key)
  std::unordered_map<std::string, int32_t> intern;
  std::vector<int64_t> key_offsets{0};
  std::string key_bytes;

  int32_t intern_key(const std::string& key) {
    auto it = intern.find(key);
    if (it != intern.end()) return it->second;
    int32_t id = (int32_t)intern.size();
    intern.emplace(key, id);
    key_bytes.append(key);
    key_offsets.push_back((int64_t)key_bytes.size());
    return id;
  }
};

struct Result {
  int64_t n_rows = 0;
  std::vector<std::vector<double>> num_cols;  // [sink][row]
  // presence bitmap per numeric sink: distinguishes an absent field from a
  // present-but-NaN value (the Python codec propagates NaN; without this the
  // two engines would disagree on rows carrying genuine NaNs)
  std::vector<std::vector<uint8_t>> num_present;
  std::vector<StrPairs> str_cols;
  std::vector<Bag> bags;
  std::string error;
};

struct MapKey {
  std::string key;
  int32_t str_sink;
};

struct Ctx {
  const uint8_t* p;
  const uint8_t* end;
  Result* res;
  const std::vector<MapKey>* map_keys;
  int64_t row = 0;        // current absolute output row
  int32_t cur_bag = -1;   // bag scope while decoding bag array items
  // scratch for the feature item being decoded (field order independent)
  std::string pending_key;
  bool has_name = false;
  bool has_term = false;
  double pending_value = 0.0;
  bool ok = true;

  bool fail(const char* msg) {
    if (res->error.empty()) res->error = msg;
    ok = false;
    return false;
  }
  bool need(int64_t n) {
    if (end - p < n) return fail("unexpected end of block payload");
    return true;
  }
  bool read_long(int64_t* out) {
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (p >= end) return fail("truncated varint");
      uint8_t b = *p++;
      acc |= (uint64_t)(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) return fail("varint too long");
    }
    *out = (int64_t)(acc >> 1) ^ -(int64_t)(acc & 1);
    return true;
  }
};

// c.row < 0 marks an out-of-window record being skipped: the bytes must be
// decoded (Avro has no per-record framing) but nothing may be captured.
void store_num(Ctx& c, int32_t sink, double v) {
  if (sink == SINK_NONE || c.row < 0) return;
  if (sink >= BAG_SINK_BASE) {
    c.pending_value = v;  // slot %3==2: the feature value
    return;
  }
  if (sink >= STR_SINK_BASE) return;  // numeric datum, string column: compiler
                                      // only allows int/long (handled inline)
  auto& col = c.res->num_cols[sink];
  if ((int64_t)col.size() <= c.row) col.resize(c.row + 1, NAN);
  col[c.row] = v;
  auto& pres = c.res->num_present[sink];
  if ((int64_t)pres.size() <= c.row) pres.resize(c.row + 1, 0);
  pres[c.row] = 1;
}

void store_str(Ctx& c, int32_t sink, const char* s, int64_t n) {
  if (sink == SINK_NONE || c.row < 0) return;
  if (sink < STR_SINK_BASE) return;  // string datum, numeric column: compiler
                                     // rejects; defensive no-op
  if (sink >= BAG_SINK_BASE) {
    int32_t slot = (sink - BAG_SINK_BASE) % 3;
    if (slot == 0) {
      // name arrives first in the scratch key; term appended after '\x01'
      c.pending_key.assign(s, (size_t)n);
      c.has_name = true;
    } else if (slot == 1) {
      c.pending_key.push_back('\x01');
      c.pending_key.append(s, (size_t)n);
      c.has_term = true;
    }
    return;
  }
  c.res->str_cols[sink - STR_SINK_BASE].push(c.row, s, n);
}

// On a null union branch: numeric sinks keep their NaN default; BAG string
// slots must still emit exactly one (empty) entry so the scratch key stays
// aligned, while per-row string columns keep their caller-side default
// (None/"" applied in Python).
void store_null(Ctx& c, int32_t sink, const int32_t* node) {
  if (sink == SINK_NONE) return;
  int32_t op = node[0];
  if ((op == OP_STRING || op == OP_BYTES) && sink >= BAG_SINK_BASE)
    store_str(c, sink, "", 0);
}

bool decode(Ctx& c, const int32_t* prog);

// Capture a metadataMap value into a per-row string column with Python
// str(v) parity: strings pass through, int/long format as decimal, null
// keeps the caller-side default; other value types make the whole decode
// fail so callers fall back to the Python codec.
bool capture_map_value(Ctx& c, const int32_t* val, int32_t route) {
  int32_t vop = val[0];
  if (vop == OP_STRING || vop == OP_BYTES) {
    int64_t n;
    if (!c.read_long(&n)) return false;
    if (n < 0 || !c.need(n)) return c.fail("bad map value");
    store_str(c, route, (const char*)c.p, n);
    c.p += n;
    return true;
  }
  if (vop == OP_INT || vop == OP_LONG) {
    int64_t v;
    if (!c.read_long(&v)) return false;
    char buf[24];
    int n = snprintf(buf, sizeof(buf), "%lld", (long long)v);
    store_str(c, route, buf, n);
    return true;
  }
  if (vop == OP_BOOL) {
    if (!c.need(1)) return false;
    bool v = *c.p++ != 0;
    store_str(c, route, v ? "True" : "False", v ? 4 : 5);
    return true;
  }
  if (vop == OP_NULL) return true;
  if (vop == OP_UNION) {
    int64_t idx;
    if (!c.read_long(&idx)) return false;
    const int32_t* b = val + 4;
    int32_t nb = val[3];
    if (idx < 0 || idx >= nb) return c.fail("bad union branch");
    for (int64_t k = 0; k < idx; k++) b += b[2];
    return capture_map_value(c, b, route);
  }
  return c.fail("unsupported metadataMap value type for id-tag capture");
}

// decode one datum described by the program node at `prog`
bool decode(Ctx& c, const int32_t* prog) {
  int32_t op = prog[0];
  int32_t sink = prog[1];
  switch (op) {
    case OP_NULL:
      store_null(c, sink, prog);
      return true;
    case OP_BOOL: {
      if (!c.need(1)) return false;
      store_num(c, sink, (double)(*c.p++ != 0));
      return true;
    }
    case OP_INT:
    case OP_LONG:
    case OP_ENUM: {
      int64_t v;
      if (!c.read_long(&v)) return false;
      if (op == OP_ENUM) return true;
      if (sink >= STR_SINK_BASE && sink < BAG_SINK_BASE) {
        char buf[24];
        int n = snprintf(buf, sizeof(buf), "%lld", (long long)v);
        store_str(c, sink, buf, n);
      } else {
        store_num(c, sink, (double)v);
      }
      return true;
    }
    case OP_FLOAT: {
      if (!c.need(4)) return false;
      float f;
      std::memcpy(&f, c.p, 4);
      c.p += 4;
      store_num(c, sink, (double)f);
      return true;
    }
    case OP_DOUBLE: {
      if (!c.need(8)) return false;
      double d;
      std::memcpy(&d, c.p, 8);
      c.p += 8;
      store_num(c, sink, d);
      return true;
    }
    case OP_BYTES:
    case OP_STRING: {
      int64_t n;
      if (!c.read_long(&n)) return false;
      if (n < 0 || !c.need(n)) return c.fail("bad string length");
      if (sink != SINK_NONE && sink < STR_SINK_BASE && c.row >= 0) {
        // string datum routed into a numeric column: float(str) parity
        char buf[64];
        if (n >= (int64_t)sizeof(buf))
          return c.fail("numeric string too long");
        std::memcpy(buf, c.p, (size_t)n);
        buf[n] = 0;
        char* endp = nullptr;
        double v = strtod(buf, &endp);
        while (endp && *endp == ' ') endp++;
        if (endp == buf || (endp && *endp != 0))
          return c.fail("non-numeric string in numeric column");
        store_num(c, sink, v);
      } else {
        store_str(c, sink, (const char*)c.p, n);
      }
      c.p += n;
      return true;
    }
    case OP_FIXED: {
      int64_t n = prog[3];
      if (!c.need(n)) return false;
      c.p += n;
      return true;
    }
    case OP_RECORD: {
      int32_t nfields = prog[3];
      const int32_t* f = prog + 4;
      for (int32_t i = 0; i < nfields; i++) {
        if (!decode(c, f)) return false;
        f += f[2];
      }
      return true;
    }
    case OP_ARRAY: {
      const int32_t* item = prog + 3;
      while (true) {
        int64_t count;
        if (!c.read_long(&count)) return false;
        if (count == 0) break;
        if (count < 0) {
          int64_t nbytes;
          if (!c.read_long(&nbytes)) return false;
          count = -count;
        }
        for (int64_t i = 0; i < count; i++) {
          int32_t saved_bag = c.cur_bag;
          bool is_bag = sink != SINK_NONE && sink < BAG_SINK_BASE && c.row >= 0;
          if (is_bag) {
            c.cur_bag = sink;
            c.pending_key.clear();
            c.has_name = c.has_term = false;
            c.pending_value = 0.0;
          }
          bool okay = decode(c, item);
          c.cur_bag = saved_bag;
          if (!okay) return false;
          if (is_bag) {
            // finalize the feature triple (field order independent)
            if (!c.has_name) return c.fail("feature item missing name");
            if (!c.has_term) c.pending_key.push_back('\x01');
            Bag& b = c.res->bags[sink];
            b.rows.push_back(c.row);
            b.key_ids.push_back(b.intern_key(c.pending_key));
            b.values.push_back(c.pending_value);
          }
        }
      }
      return true;
    }
    case OP_MAP: {
      const int32_t* val = prog + 3;
      while (true) {
        int64_t count;
        if (!c.read_long(&count)) return false;
        if (count == 0) break;
        if (count < 0) {
          int64_t nbytes;
          if (!c.read_long(&nbytes)) return false;
          count = -count;
        }
        for (int64_t i = 0; i < count; i++) {
          int64_t klen;
          if (!c.read_long(&klen)) return false;
          if (klen < 0 || !c.need(klen)) return c.fail("bad map key");
          const char* key = (const char*)c.p;
          c.p += klen;
          int32_t route = SINK_NONE;
          if (sink == 0 && c.map_keys) {  // the tracked metadataMap
            for (const auto& mk : *c.map_keys) {
              if ((int64_t)mk.key.size() == klen &&
                  std::memcmp(mk.key.data(), key, (size_t)klen) == 0) {
                route = mk.str_sink;
                break;
              }
            }
          }
          // value node with the routed sink: decode through a patched header
          if (route == SINK_NONE) {
            // decode and discard (sink of the value program applies; values
            // under maps are compiled with SINK_NONE)
            if (!decode(c, val)) return false;
          } else {
            if (!capture_map_value(c, val, route)) return false;
          }
        }
      }
      return true;
    }
    case OP_UNION: {
      int64_t idx;
      if (!c.read_long(&idx)) return false;
      int32_t nb = prog[3];
      if (idx < 0 || idx >= nb) return c.fail("bad union branch index");
      const int32_t* b = prog + 4;
      for (int64_t i = 0; i < idx; i++) b += b[2];
      if (b[0] == OP_NULL && sink != SINK_NONE) {
        // null branch of a sinked union: emit the union's default capture
        // typed by the union's non-null branch
        const int32_t* t = prog + 4;
        const int32_t* nonnull = nullptr;
        for (int32_t i = 0; i < nb; i++) {
          if (t[0] != OP_NULL) {
            nonnull = t;
            break;
          }
          t += t[2];
        }
        if (nonnull) store_null(c, sink, nonnull);
        return true;
      }
      // propagate the union's sink onto the branch via a patched header
      int32_t patched[3] = {b[0], sink != SINK_NONE ? sink : b[1], b[2]};
      if (b[0] == OP_RECORD || b[0] == OP_ARRAY || b[0] == OP_MAP ||
          b[0] == OP_UNION || b[0] == OP_FIXED) {
        // complex branches keep their own sinks (compiled in)
        return decode(c, b);
      }
      // primitive branch: temporary node with propagated sink
      int32_t tmp[4] = {patched[0], patched[1], 3, 0};
      const uint8_t* before = c.p;
      (void)before;
      return decode(c, tmp);
    }
  }
  return c.fail("unknown opcode");
}

bool inflate_raw(const uint8_t* src, size_t n, std::vector<uint8_t>& out) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  out.resize(n * 4 + 4096);
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = (uInt)n;
  size_t written = 0;
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    if (written == out.size()) out.resize(out.size() * 2);
    zs.next_out = out.data() + written;
    zs.avail_out = (uInt)(out.size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    written = out.size() - zs.avail_out;
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return false;
    }
  }
  inflateEnd(&zs);
  out.resize(written);
  return true;
}

}  // namespace

extern "C" {

// Decode the data blocks of one Object Container File.
//  data/file_len:   full file bytes (caller mmaps)
//  data_off:        offset of the first block (right after the header sync)
//  sync:            16-byte sync marker
//  codec:           0 = null, 1 = deflate
//  program:         int32 schema program for one record
//  n_num/n_str/n_bags: sink counts
//  map_keys/map_key_sinks/n_map_keys: metadataMap keys to capture -> str sink
//  row_start/row_stop: [start, stop) window over this file's records
//                      (pass 0, INT64_MAX for all)
// Returns an opaque Result*; check pr_error()[0] != 0 for failure.
void* pr_decode(const uint8_t* data, int64_t file_len, int64_t data_off,
                const uint8_t* sync, int32_t codec, const int32_t* program,
                int32_t n_num, int32_t n_str, int32_t n_bags,
                const char* const* map_keys, const int32_t* map_key_sinks,
                int32_t n_map_keys, int64_t row_start, int64_t row_stop) {
  auto* res = new Result();
  res->num_cols.resize(n_num);
  res->num_present.resize(n_num);
  res->str_cols.resize(n_str);
  res->bags.resize(n_bags);

  std::vector<MapKey> mks;
  for (int32_t i = 0; i < n_map_keys; i++)
    mks.push_back(MapKey{map_keys[i], map_key_sinks[i]});

  Ctx header_ctx{data + data_off, data + file_len, res, &mks};
  Ctx& hc = header_ctx;
  int64_t file_row = 0;  // record index within the file
  int64_t out_row = 0;   // output row index
  std::vector<uint8_t> scratch;

  while (hc.p < hc.end) {
    int64_t count, size;
    if (!hc.read_long(&count) || !hc.read_long(&size)) break;
    if (size < 0 || hc.end - hc.p < 16 || hc.end - hc.p - 16 < size) {
      res->error = "truncated block";
      break;
    }
    const uint8_t* payload = hc.p;
    hc.p += size;
    if (std::memcmp(hc.p, sync, 16) != 0) {
      res->error = "sync marker mismatch (corrupt file)";
      break;
    }
    hc.p += 16;
    if (file_row + count <= row_start || file_row >= row_stop) {
      file_row += count;  // whole block outside the window: never inflate
      continue;
    }

    const uint8_t* body = payload;
    int64_t body_len = size;
    if (codec == 1) {
      if (!inflate_raw(payload, (size_t)size, scratch)) {
        res->error = "deflate error";
        break;
      }
      body = scratch.data();
      body_len = (int64_t)scratch.size();
    }

    Ctx bc{body, body + body_len, res, &mks};
    for (int64_t i = 0; i < count; i++) {
      bool in_window = file_row >= row_start && file_row < row_stop;
      bc.row = in_window ? out_row : -1;  // -1 = decode bytes, capture nothing
      if (!decode(bc, program)) break;
      if (in_window) out_row++;
      file_row++;
    }
    if (!bc.ok) {
      if (res->error.empty()) res->error = "decode error";
      break;
    }
    if (file_row >= row_stop) break;
  }
  res->n_rows = out_row;
  for (auto& col : res->num_cols) col.resize((size_t)out_row, NAN);
  for (auto& pres : res->num_present) pres.resize((size_t)out_row, 0);
  return res;
}

const char* pr_error(void* r) { return ((Result*)r)->error.c_str(); }
int64_t pr_n_rows(void* r) { return ((Result*)r)->n_rows; }

const double* pr_num_col(void* r, int32_t s) {
  return ((Result*)r)->num_cols[s].data();
}

const uint8_t* pr_num_present(void* r, int32_t s) {
  return ((Result*)r)->num_present[s].data();
}

int64_t pr_str_count(void* r, int32_t s) {
  return (int64_t)((Result*)r)->str_cols[s].rows.size();
}
const int64_t* pr_str_rows(void* r, int32_t s) {
  return ((Result*)r)->str_cols[s].rows.data();
}
const int64_t* pr_str_offsets(void* r, int32_t s) {
  return ((Result*)r)->str_cols[s].offsets.data();
}
const char* pr_str_bytes(void* r, int32_t s) {
  return ((Result*)r)->str_cols[s].bytes.data();
}

int64_t pr_bag_count(void* r, int32_t b) {
  return (int64_t)((Result*)r)->bags[b].rows.size();
}
const int64_t* pr_bag_rows(void* r, int32_t b) {
  return ((Result*)r)->bags[b].rows.data();
}
const int32_t* pr_bag_key_ids(void* r, int32_t b) {
  return ((Result*)r)->bags[b].key_ids.data();
}
const double* pr_bag_values(void* r, int32_t b) {
  return ((Result*)r)->bags[b].values.data();
}
int64_t pr_bag_n_keys(void* r, int32_t b) {
  return (int64_t)((Result*)r)->bags[b].intern.size();
}
const int64_t* pr_bag_key_offsets(void* r, int32_t b) {
  return ((Result*)r)->bags[b].key_offsets.data();
}
const char* pr_bag_key_bytes(void* r, int32_t b) {
  return ((Result*)r)->bags[b].key_bytes.data();
}

void pr_free(void* r) { delete (Result*)r; }

}  // extern "C"
