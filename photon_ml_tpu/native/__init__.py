"""Native (C++) host-runtime components.

The reference's runtime is JVM-native (Spark executors + Breeze/netlib); the
TPU build's compute path is XLA, and the host runtime around it — here the
Avro ingest hot loop (AvroDataReader.scala:54-490's role) — is C++
(decoder.cpp): a generic Avro-binary interpreter driven by a compact schema
program, with block-level deflate and row-window skipping, returning columnar
arrays + interned feature keys ready for vectorized index-map lookup.

The module self-builds with g++ on first use (cached next to the source,
keyed by source mtime) and degrades cleanly: ``available()`` is False when
the toolchain or zlib is missing, and every caller falls back to the pure-
Python codec (io/avro.py).
"""

from __future__ import annotations

import ctypes
import json
import logging
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger("photon_ml_tpu")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "decoder.cpp")
_LIB_PATH = os.path.join(_DIR, "_photon_native.so")

# opcodes — must match decoder.cpp
OP_NULL, OP_BOOL, OP_INT, OP_LONG, OP_FLOAT, OP_DOUBLE = 0, 1, 2, 3, 4, 5
OP_BYTES, OP_STRING, OP_RECORD, OP_ENUM, OP_FIXED = 6, 7, 8, 9, 10
OP_ARRAY, OP_MAP, OP_UNION = 11, 12, 13

SINK_NONE = -1
STR_SINK_BASE = 500  # per-row string sinks live at 500+idx (decoder.cpp)
BAG_SINK_BASE = 1000

_build_lock = threading.Lock()
_lib = None
_lib_error: Optional[str] = None


def _build() -> Optional[ctypes.CDLL]:
    """Compile decoder.cpp -> _photon_native.so (mtime-cached)."""
    global _lib, _lib_error
    with _build_lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        try:
            if (
                not os.path.exists(_LIB_PATH)
                or os.path.getmtime(_LIB_PATH) < os.path.getmtime(_SRC)
            ):
                # per-pid temp name: concurrent first-use builds (multi-process
                # CLI) must not interleave g++ output into one file before the
                # atomic rename
                tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
                cmd = [
                    "g++", "-O3", "-Wall", "-shared", "-fPIC",
                    _SRC, "-o", tmp, "-lz",
                ]
                subprocess.run(cmd, check=True, capture_output=True, text=True)
                os.replace(tmp, _LIB_PATH)
                logger.info("built native decoder: %s", _LIB_PATH)
            lib = ctypes.CDLL(_LIB_PATH)
        except (OSError, subprocess.CalledProcessError) as e:
            detail = getattr(e, "stderr", "") or str(e)
            _lib_error = f"native decoder unavailable: {detail[:500]}"
            logger.info(_lib_error)
            return None
        _bind(lib)
        _lib = lib
        return lib


def _bind(lib: ctypes.CDLL):
    c = ctypes
    lib.pr_decode.restype = c.c_void_p
    lib.pr_decode.argtypes = [
        c.c_char_p, c.c_int64, c.c_int64,          # data, file_len, data_off
        c.c_char_p, c.c_int32,                     # sync, codec
        c.POINTER(c.c_int32),                      # program
        c.c_int32, c.c_int32, c.c_int32,           # n_num, n_str, n_bags
        c.POINTER(c.c_char_p), c.POINTER(c.c_int32), c.c_int32,  # map keys
        c.c_int64, c.c_int64,                      # row_start, row_stop
    ]
    lib.pr_error.restype = c.c_char_p
    lib.pr_error.argtypes = [c.c_void_p]
    lib.pr_n_rows.restype = c.c_int64
    lib.pr_n_rows.argtypes = [c.c_void_p]
    lib.pr_num_col.restype = c.POINTER(c.c_double)
    lib.pr_num_col.argtypes = [c.c_void_p, c.c_int32]
    lib.pr_num_present.restype = c.POINTER(c.c_uint8)
    lib.pr_num_present.argtypes = [c.c_void_p, c.c_int32]
    for name in ("pr_str_count", "pr_bag_count", "pr_bag_n_keys"):
        fn = getattr(lib, name)
        fn.restype = c.c_int64
        fn.argtypes = [c.c_void_p, c.c_int32]
    for name in ("pr_str_rows", "pr_str_offsets", "pr_bag_rows",
                 "pr_bag_key_offsets"):
        fn = getattr(lib, name)
        fn.restype = c.POINTER(c.c_int64)
        fn.argtypes = [c.c_void_p, c.c_int32]
    for name in ("pr_str_bytes", "pr_bag_key_bytes"):
        fn = getattr(lib, name)
        fn.restype = c.POINTER(c.c_char)
        fn.argtypes = [c.c_void_p, c.c_int32]
    lib.pr_bag_key_ids.restype = c.POINTER(c.c_int32)
    lib.pr_bag_key_ids.argtypes = [c.c_void_p, c.c_int32]
    lib.pr_bag_values.restype = c.POINTER(c.c_double)
    lib.pr_bag_values.argtypes = [c.c_void_p, c.c_int32]
    lib.pr_free.restype = None
    lib.pr_free.argtypes = [c.c_void_p]


def available() -> bool:
    return _build() is not None


# ---------------------------------------------------------------------------
# schema-program compiler
# ---------------------------------------------------------------------------


class ProgramError(ValueError):
    """Schema shape the native interpreter does not cover (fall back)."""


def _check_sink_type(op: int, sink: int):
    """Reject sink/type combinations the decoder cannot capture faithfully
    (the Python codec handles them via dynamic typing; callers fall back)."""
    if sink == SINK_NONE or op == OP_NULL:
        return
    if sink >= BAG_SINK_BASE:
        slot = (sink - BAG_SINK_BASE) % 3
        if slot == 2:  # value: numeric
            if op not in (OP_INT, OP_LONG, OP_FLOAT, OP_DOUBLE, OP_BOOL):
                raise ProgramError("bag value field is not numeric")
        else:  # name/term: string
            if op not in (OP_STRING, OP_BYTES):
                raise ProgramError("bag name/term field is not a string")
    elif sink >= STR_SINK_BASE:
        # per-row string column: strings, or int/long (decimal-formatted,
        # str(int) parity); float/double/bool would not match Python's str()
        if op not in (OP_STRING, OP_BYTES, OP_INT, OP_LONG):
            raise ProgramError(
                "string column backed by a non-string, non-integer field"
            )
    else:
        # numeric per-row column; strings parse via strtod (float(str) parity)
        if op not in (OP_INT, OP_LONG, OP_FLOAT, OP_DOUBLE, OP_BOOL,
                      OP_STRING, OP_BYTES):
            raise ProgramError("numeric column backed by a non-numeric field")


def compile_program(
    schema,
    env,
    num_fields: Dict[str, int],
    str_fields: Dict[str, int],
    bag_fields: Dict[str, int],
    map_field: Optional[str],
) -> List[int]:
    """Writer schema -> int32 program. Top-level record fields are routed to
    sinks by name; a bag field's item record routes name/term/value to the
    bag's slots; `map_field` marks the metadataMap (sink 0 on its MAP node).
    """
    top = env.resolve(schema)
    if not isinstance(top, dict) or top.get("type") not in ("record", "error"):
        raise ProgramError("top-level schema must be a record")

    def node(s, sink=SINK_NONE, bag: Optional[int] = None, depth=0) -> List[int]:
        if depth > 32:
            raise ProgramError("schema nesting too deep (recursive schema?)")
        s = env.resolve(s)
        if isinstance(s, dict) and s.get("type") == "union":
            s = s["types"]
        if isinstance(s, list):
            # branches inherit the union's sink so bag arrays / captured
            # primitives under ["null", X] unions still route
            branches = [node(b, sink, bag, depth + 1) for b in s]
            out = [OP_UNION, sink, 0, len(branches)]
            for b in branches:
                out.extend(b)
            out[2] = len(out)
            return out
        t = s if isinstance(s, str) else s.get("type")
        if isinstance(t, (dict, list)):
            return node(t, sink, bag, depth + 1)
        prim = {
            "null": OP_NULL, "boolean": OP_BOOL, "int": OP_INT,
            "long": OP_LONG, "float": OP_FLOAT, "double": OP_DOUBLE,
            "bytes": OP_BYTES, "string": OP_STRING,
        }
        if t in prim:
            op = prim[t]
            _check_sink_type(op, sink)
            return [op, sink, 3]
        if t == "enum":
            return [OP_ENUM, SINK_NONE, 3]
        if t == "fixed":
            return [OP_FIXED, SINK_NONE, 4, int(s["size"])]
        if t in ("record", "error"):
            fields = []
            for f in s["fields"]:
                fsink = SINK_NONE
                if bag is not None:
                    slot = {"name": 0, "term": 1, "value": 2}.get(f["name"])
                    if slot is not None:
                        fsink = BAG_SINK_BASE + 3 * bag + slot
                fields.append(node(f["type"], fsink, None, depth + 1))
            out = [OP_RECORD, sink, 0, len(s["fields"])]
            for f in fields:
                out.extend(f)
            out[2] = len(out)
            return out
        if t == "array":
            item_bag = bag
            item = node(s["items"], SINK_NONE, item_bag, depth + 1)
            out = [OP_ARRAY, sink, 0] + item
            out[2] = len(out)
            return out
        if t == "map":
            value = node(s["values"], SINK_NONE, None, depth + 1)
            out = [OP_MAP, sink, 0] + value
            out[2] = len(out)
            return out
        raise ProgramError(f"unsupported Avro type {t!r}")

    fields = []
    for f in top["fields"]:
        name = f["name"]
        if name in bag_fields:
            b = bag_fields[name]
            arr = env.resolve(f["type"])
            if isinstance(arr, dict) and isinstance(arr.get("type"), dict):
                arr = arr["type"]
            fields.append(node(f["type"], bag_fields[name], bag=b))
        elif name in num_fields:
            fields.append(node(f["type"], num_fields[name]))
        elif name in str_fields:
            fields.append(node(f["type"], str_fields[name]))
        elif map_field is not None and name == map_field:
            fields.append(node(f["type"], 0))
        else:
            fields.append(node(f["type"]))
    out = [OP_RECORD, SINK_NONE, 0, len(top["fields"])]
    for f in fields:
        out.extend(f)
    out[2] = len(out)
    return out


# ---------------------------------------------------------------------------
# columnar file decode
# ---------------------------------------------------------------------------


class Columnar:
    """Decoded columnar content of one file (numpy copies, C buffers freed)."""

    __slots__ = ("n_rows", "num_cols", "num_present", "str_cols", "bags")

    def __init__(self, n_rows, num_cols, num_present, str_cols, bags):
        self.n_rows = n_rows
        self.num_cols = num_cols      # [np.ndarray f8[n_rows]]
        self.num_present = num_present  # [np.ndarray bool[n_rows]] field seen
        self.str_cols = str_cols      # [(rows i8[k], values object[k])]
        self.bags = bags              # [(rows i8[m], key_ids i4[m], vals f8[m], keys object[u])]


def _split_strings(offsets: np.ndarray, raw: bytes) -> np.ndarray:
    out = np.empty(len(offsets) - 1, dtype=object)
    for i in range(len(offsets) - 1):
        out[i] = raw[offsets[i]:offsets[i + 1]].decode("utf-8")
    return out


def decode_file(
    path: str,
    num_fields: Dict[str, int],
    str_fields: Dict[str, int],
    bag_fields: Dict[str, int],
    map_keys: Dict[str, int],
    map_field: str = "metadataMap",
    row_range: Optional[Tuple[int, int]] = None,
    _program_cache: dict = {},
) -> Columnar:
    """Decode one container file into columnar arrays via the native lib."""
    lib = _build()
    if lib is None:
        raise RuntimeError(_lib_error or "native decoder unavailable")

    import mmap as _mmap

    from ..io.avro import MAGIC, SYNC_SIZE, SchemaEnv, _read_datum, _Reader, parse_schema

    f = open(path, "rb")
    try:
        data = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    except ValueError:
        f.close()
        raise ValueError(f"{path}: not an Avro object container file")
    except BaseException:
        f.close()  # OSError etc. would otherwise escape with f open
        raise
    with f:
        try:
            return _decode_mapped(
                lib, path, data, num_fields, str_fields, bag_fields, map_keys,
                map_field, row_range, _program_cache,
            )
        finally:
            try:
                data.close()
            except BufferError:
                # a propagating exception's traceback still holds the
                # np.frombuffer view; let GC close the map rather than
                # masking the real error with BufferError
                pass


def _prepare_mapped(lib, path, data, num_fields, str_fields, bag_fields,
                    map_keys, map_field, _program_cache):
    """Parse the container header and compile/cache the schema program;
    returns everything a (chunk) decode call needs."""
    from ..io.avro import MAGIC, SYNC_SIZE, SchemaEnv, _read_datum, _Reader, parse_schema

    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta = _read_datum(r, {"type": "map", "values": "bytes"}, SchemaEnv())
    schema_json = meta["avro.schema"].decode("utf-8")
    codec_name = meta.get("avro.codec", b"null").decode("utf-8")
    if codec_name not in ("null", "deflate"):
        raise ProgramError(f"unsupported codec {codec_name}")
    sync = r.read(SYNC_SIZE)
    data_off = r.pos

    cache_key = (schema_json, tuple(sorted(num_fields.items())),
                 tuple(sorted(str_fields.items())),
                 tuple(sorted(bag_fields.items())), map_field)
    program = _program_cache.get(cache_key)
    if program is None:
        schema, env = parse_schema(schema_json)
        # per-row string sinks live in their own id space (decoder.cpp)
        str_prog = {k: STR_SINK_BASE + v for k, v in str_fields.items()}
        program = np.asarray(
            compile_program(schema, env, num_fields, str_prog, bag_fields,
                            map_field),
            dtype=np.int32,
        )
        _program_cache[cache_key] = program

    n_num = max(num_fields.values(), default=-1) + 1
    n_str = max(
        list(str_fields.values()) + list(map_keys.values()), default=-1
    ) + 1
    n_bags = max(bag_fields.values(), default=-1) + 1

    mk_names = list(map_keys)
    mk_arr = (ctypes.c_char_p * max(len(mk_names), 1))()
    mk_sinks = (ctypes.c_int32 * max(len(mk_names), 1))()
    for i, k in enumerate(mk_names):
        mk_arr[i] = k.encode()
        mk_sinks[i] = STR_SINK_BASE + map_keys[k]
    return dict(
        data_off=data_off, sync=sync, codec=1 if codec_name == "deflate" else 0,
        program=program, n_num=n_num, n_str=n_str, n_bags=n_bags,
        mk_arr=mk_arr, mk_sinks=mk_sinks, n_mk=len(mk_names),
    )


def _run_decode(lib, path, view, data_len, prep, data_off, start, stop) -> Columnar:
    """One pr_decode call over [data_off, ...) with record window [start, stop)
    relative to data_off; builds the numpy Columnar. Releases the GIL for the
    duration of the native decode (ctypes foreign call)."""
    n_num, n_str, n_bags = prep["n_num"], prep["n_str"], prep["n_bags"]
    res = lib.pr_decode(
        view.ctypes.data_as(ctypes.c_char_p), data_len, data_off, prep["sync"],
        prep["codec"],
        prep["program"].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_num, n_str, n_bags,
        prep["mk_arr"], prep["mk_sinks"], prep["n_mk"],
        start, stop,
    )
    try:
        err = lib.pr_error(res)
        if err:
            raise ValueError(f"{path}: {err.decode()}")
        n = lib.pr_n_rows(res)
        num_cols = [
            np.ctypeslib.as_array(lib.pr_num_col(res, s), shape=(n,)).copy()
            if n else np.empty(0)
            for s in range(n_num)
        ]
        num_present = [
            np.ctypeslib.as_array(lib.pr_num_present(res, s), shape=(n,))
            .copy()
            .astype(bool)
            if n else np.empty(0, bool)
            for s in range(n_num)
        ]
        str_cols = []
        for s in range(n_str):
            k = lib.pr_str_count(res, s)
            if k == 0:
                str_cols.append((np.empty(0, np.int64), np.empty(0, object)))
                continue
            rows = np.ctypeslib.as_array(lib.pr_str_rows(res, s), shape=(k,)).copy()
            offs = np.ctypeslib.as_array(
                lib.pr_str_offsets(res, s), shape=(k + 1,)
            ).copy()
            raw = ctypes.string_at(lib.pr_str_bytes(res, s), int(offs[-1]))
            str_cols.append((rows, _split_strings(offs, raw)))
        bags = []
        for b in range(n_bags):
            m = lib.pr_bag_count(res, b)
            u = lib.pr_bag_n_keys(res, b)
            if m == 0:
                bags.append(
                    (np.empty(0, np.int64), np.empty(0, np.int32),
                     np.empty(0), np.empty(0, object))
                )
                continue
            rows = np.ctypeslib.as_array(lib.pr_bag_rows(res, b), shape=(m,)).copy()
            kid = np.ctypeslib.as_array(lib.pr_bag_key_ids(res, b), shape=(m,)).copy()
            vals = np.ctypeslib.as_array(lib.pr_bag_values(res, b), shape=(m,)).copy()
            offs = np.ctypeslib.as_array(
                lib.pr_bag_key_offsets(res, b), shape=(u + 1,)
            ).copy()
            raw = ctypes.string_at(lib.pr_bag_key_bytes(res, b), int(offs[-1]))
            bags.append((rows, kid, vals, _split_strings(offs, raw)))
        return Columnar(int(n), num_cols, num_present, str_cols, bags)
    finally:
        lib.pr_free(res)


def _decode_mapped(lib, path, data, num_fields, str_fields, bag_fields,
                   map_keys, map_field, row_range, _program_cache) -> Columnar:
    prep = _prepare_mapped(
        lib, path, data, num_fields, str_fields, bag_fields, map_keys,
        map_field, _program_cache,
    )
    start, stop = row_range if row_range is not None else (0, 2**62)
    view = np.frombuffer(data, dtype=np.uint8)  # zero-copy over the mmap
    return _run_decode(
        lib, path, view, len(data), prep, prep["data_off"], start, stop
    )


def _scan_blocks(data, data_off, path):
    """Block boundaries from the container headers alone (no decompression):
    [(block_offset, first_record_index, record_count, byte_size)]."""
    from ..io.avro import SYNC_SIZE, _Reader

    r = _Reader(data)
    r.pos = data_off
    out = []
    row = 0
    while not r.at_end():
        off = r.pos
        count = r.read_long()
        size = r.read_long()
        if count < 0 or size < 0 or r.pos + size + SYNC_SIZE > len(data):
            raise ValueError(
                f"{path}: corrupt Avro block header "
                f"(count={count}, size={size} at offset {off})"
            )
        out.append((off, row, count, size))
        row += count
        r.pos += size + SYNC_SIZE
    return out


def decode_file_chunks(
    path: str,
    num_fields: Dict[str, int],
    str_fields: Dict[str, int],
    bag_fields: Dict[str, int],
    map_keys: Dict[str, int],
    map_field: str = "metadataMap",
    row_range: Optional[Tuple[int, int]] = None,
    n_threads: Optional[int] = None,
    _program_cache: dict = {},
) -> List[Columnar]:
    """Decode one container file on a thread pool, one contiguous run of
    OCF blocks per thread (blocks are independently-deflated units; the
    reference decodes splits on every executor in parallel,
    AvroDataReader.scala:54-490 — this is the shared-memory analogue).

    The native call releases the GIL, so chunks genuinely decode in parallel.
    Returns the chunk Columnars in row order; callers stitch them exactly
    like per-file parts. n_threads defaults to PHOTON_DECODE_THREADS or the
    core count."""
    lib = _build()
    if lib is None:
        raise RuntimeError(_lib_error or "native decoder unavailable")
    if n_threads is None:
        n_threads = int(os.environ.get("PHOTON_DECODE_THREADS", 0)) or (os.cpu_count() or 1)

    import mmap as _mmap

    f = open(path, "rb")
    try:
        data = _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)
    except ValueError:
        f.close()
        raise ValueError(f"{path}: not an Avro object container file")
    except BaseException:
        f.close()  # OSError etc. would otherwise escape with f open
        raise
    with f:
        try:
            prep = _prepare_mapped(
                lib, path, data, num_fields, str_fields, bag_fields, map_keys,
                map_field, _program_cache,
            )
            start, stop = row_range if row_range is not None else (0, 2**62)
            blocks = _scan_blocks(data, prep["data_off"], path)
            # keep only blocks intersecting the window
            blocks = [
                b for b in blocks if b[1] + b[2] > start and b[1] < stop
            ]
            if not blocks or n_threads <= 1 or len(blocks) == 1:
                view = np.frombuffer(data, dtype=np.uint8)
                return [
                    _run_decode(
                        lib, path, view, len(data), prep, prep["data_off"],
                        start, stop,
                    )
                ]
            # split into <= n_threads contiguous chunks balanced by bytes
            total_bytes = sum(b[3] for b in blocks)
            target = max(total_bytes / min(n_threads, len(blocks)), 1)
            chunks = []
            cur, acc = [], 0
            for b in blocks:
                cur.append(b)
                acc += b[3]
                if acc >= target and len(chunks) < n_threads - 1:
                    chunks.append(cur)
                    cur, acc = [], 0
            if cur:
                chunks.append(cur)

            view = np.frombuffer(data, dtype=np.uint8)

            def run(chunk):
                off, first_row = chunk[0][0], chunk[0][1]
                last_row = chunk[-1][1] + chunk[-1][2]
                lo = max(start - first_row, 0)
                hi = min(stop, last_row) - first_row
                return _run_decode(
                    lib, path, view, len(data), prep, off, lo, hi
                )

            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(chunks)) as pool:
                return list(pool.map(run, chunks))
        finally:
            try:
                data.close()
            except BufferError:
                pass
