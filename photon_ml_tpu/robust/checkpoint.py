"""Crash-safe coordinate-descent checkpoints: digest manifests, keep-last-K.

The reference's fault tolerance is RDD lineage: lose an executor mid-sweep
and Spark recomputes the lost partitions from the recorded transformation
graph. A JAX process has no lineage — lose the process and the sweep is
gone. The replacement is snapshot-based: at coordinate-update boundaries
(the natural consistency points of block coordinate descent — between
updates the entire algorithm state is a handful of host-reachable values)
the :class:`CheckpointManager` persists the outer-loop state and a resumed
process replays the remaining updates bit-for-bit.

On-disk layout, one directory per checkpoint::

    <dir>/ckpt-000007/
        state.pkl        # pickled payload (models, scores, best-so-far, ...)
        MANIFEST.json    # written LAST: schema/compat keys + sha256(payload)

Both files are written via :mod:`robust.atomic` (temp + fsync + rename) and
the manifest lands only after the payload is durable, so the manifest's
existence certifies the checkpoint: restore validates the digest before
unpickling a single byte, a torn payload or manifest is skipped with a
warning, and :meth:`CheckpointManager.latest_valid` falls back to the next
older checkpoint. A checkpoint whose coordinate configuration does not match
the resuming run is REJECTED with a clear error instead of half-loading.

Counters in the obs registry: ``photon_checkpoint_saves_total``,
``photon_checkpoint_bytes_total``, ``photon_checkpoint_restore_total``, and
``photon_checkpoint_skipped_total{reason=}`` for restore fallbacks.

**Multi-process runs** use a two-phase boundary protocol (the manager is
constructed with ``process``/``n_processes`` and every process calls
:meth:`CheckpointManager.on_boundary`): phase one, each process writes its
local row shard of the summed scores (``shard-p<i>.pkl``) and confirms it
over a guarded collective with the shard's sha256; phase two, the
coordinator — and only after every shard confirmed — writes the payload and
then the manifest, which records all shard digests plus the run topology
(process count, mesh axes, plan fingerprint, padded global rows). The
manifest is still the commit point: a save torn at ANY stage (shard,
payload, or pre-manifest kill — the ``dist.commit`` fault site brackets
both phases) leaves no manifest, so restore falls back to the previous
consistent step exactly like a corrupt single-process checkpoint. Restore
validates the recorded topology through the plan layer
(:func:`plan.planner.check_checkpoint_topology`): same topology resumes
bit-exact, a legal reshape (data-axis shards re-concatenated under a
different process count with identical padded row totals) reassembles the
shards, and an unsound one raises :class:`CheckpointIncompatibleError`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import pickle
import shutil
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import faults
from .atomic import atomic_write_bytes, atomic_write_json
from .retry import io_call

logger = logging.getLogger("photon_ml_tpu")

MANIFEST_NAME = "MANIFEST.json"
PAYLOAD_NAME = "state.pkl"
SHARD_PREFIX = "shard-p"
MANIFEST_VERSION = 1
_DIR_PREFIX = "ckpt-"


class CheckpointError(Exception):
    """Base class for checkpoint restore problems."""


class CheckpointIncompatibleError(CheckpointError):
    """The newest valid checkpoint was written by a different run
    configuration; resuming from it would silently train the wrong model."""


@dataclasses.dataclass
class CheckpointSnapshot:
    """A restored coordinate-descent boundary state (the duck type
    ``CoordinateDescent.run(resume_state=...)`` consumes)."""

    iteration: int
    coordinate_index: int
    coordinate: str
    models: Dict[str, object]
    summed_scores: np.ndarray
    best_eval: Optional[object]
    best_models: Dict[str, object]
    evaluations: List
    tracker_summaries: Dict[str, str]
    manifest: dict
    path: str
    # divergence-guard regression baselines (PR 4); defaulted so snapshots
    # written before the field existed still restore
    train_losses: Dict[str, float] = dataclasses.field(default_factory=dict)


def _registry():
    from .. import obs

    return obs.current_run().registry


def _count_skip(reason: str) -> None:
    _registry().counter(
        "photon_checkpoint_skipped_total",
        "checkpoints skipped during restore, by reason",
    ).labels(reason=reason).inc()


class CheckpointManager:
    """Saves/restores coordinate-descent boundary state under one directory.

    ``every``: save on every N-th boundary notification (:meth:`on_boundary`
    counts them); ``keep_last``: checkpoints retained after rotation;
    ``fsync``: durability of the temp-write path (tests turn it off for
    speed, production leaves it on).

    ``process``/``n_processes`` select the two-phase multi-process protocol
    (every process constructs a manager over the SAME directory and calls
    :meth:`on_boundary`; shard confirmation rides ``exchange``, which
    defaults to the guarded ``multihost.allgather_object`` and is injectable
    for in-process torn-commit tests). ``topology`` is extra topology meta
    (mesh axes, plan fingerprint) stamped into every manifest alongside the
    process count and padded global row total.
    """

    def __init__(
        self,
        directory: str,
        keep_last: int = 3,
        every: int = 1,
        fsync: bool = True,
        base_meta: Optional[dict] = None,
        process: int = 0,
        n_processes: int = 1,
        topology: Optional[dict] = None,
        exchange=None,
    ):
        if keep_last < 1:
            raise ValueError(f"keep_last must be >= 1: {keep_last}")
        if every < 1:
            raise ValueError(f"every must be >= 1: {every}")
        if n_processes < 1:
            raise ValueError(f"n_processes must be >= 1: {n_processes}")
        if not 0 <= process < n_processes:
            raise ValueError(
                f"process must be in [0, {n_processes}): {process}"
            )
        self.directory = directory
        self.keep_last = keep_last
        self.every = every
        self.fsync = fsync
        self.process = int(process)
        self.n_processes = int(n_processes)
        self.topology = dict(topology or {})
        self.exchange = exchange
        # merged into every manifest this manager writes (per-save meta wins
        # on key collisions): the retrain chain stamps its day index and the
        # accepted/rejected ledger here, so any boundary checkpoint alone
        # identifies its position in the day chain
        self.base_meta = dict(base_meta or {})
        self._boundaries = 0
        os.makedirs(directory, exist_ok=True)
        steps = self._steps_on_disk()
        self._seq = (max(steps) + 1) if steps else 0

    # -- saving ---------------------------------------------------------------

    def on_boundary(self, state, meta: Optional[dict] = None) -> Optional[str]:
        """Coordinate-update boundary notification; saves every N-th one.
        ``state`` is descent's boundary state (see CDBoundaryState). The
        ``cd.boundary`` / ``cd.boundary_saved`` fault sites bracket the save
        so tests can kill either right before or right after persistence."""
        faults.check("cd.boundary")
        self._boundaries += 1
        if self._boundaries % self.every:
            return None
        path = self.save(state, meta)
        faults.check("cd.boundary_saved")
        return path

    def save(self, state, meta: Optional[dict] = None) -> str:
        """Persist one boundary state; returns the checkpoint directory.
        Multi-process managers route through the two-phase protocol."""
        if self.n_processes > 1:
            return self._save_distributed(state, meta)
        t0 = time.perf_counter()
        payload = self._payload_dict(state, np.asarray(state.summed_scores))
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        digest = hashlib.sha256(blob).hexdigest()
        name = f"{_DIR_PREFIX}{self._seq:06d}"
        ckpt_dir = os.path.join(self.directory, name)
        os.makedirs(ckpt_dir, exist_ok=True)
        io_call(
            atomic_write_bytes,
            os.path.join(ckpt_dir, PAYLOAD_NAME),
            blob,
            fsync=self.fsync,
            site="checkpoint.write",
        )
        manifest = {
            "version": MANIFEST_VERSION,
            "step": self._seq,
            "iteration": int(state.iteration),
            "coordinate_index": int(state.coordinate_index),
            "coordinate": state.coordinate,
            "coordinate_order": list(state.coordinate_order),
            "n_iterations": int(state.n_iterations),
            "payload": PAYLOAD_NAME,
            "sha256": digest,
            "bytes": len(blob),
            "created_unix": time.time(),
            "topology": self._topology_meta(
                global_rows=int(payload["summed_scores"].shape[0])
            ),
            **self.base_meta,
            **(meta or {}),
        }
        io_call(
            atomic_write_json,
            os.path.join(ckpt_dir, MANIFEST_NAME),
            manifest,
            fsync=self.fsync,
            indent=2,
            site="checkpoint.manifest",
        )
        self._seq += 1
        save_seconds = time.perf_counter() - t0
        reg = _registry()
        reg.counter(
            "photon_checkpoint_saves_total", "boundary checkpoints written"
        ).inc()
        reg.counter(
            "photon_checkpoint_bytes_total", "checkpoint payload bytes written"
        ).inc(len(blob))
        reg.histogram(
            "photon_checkpoint_save_seconds", "wall per boundary checkpoint save"
        ).observe(save_seconds)
        self._rotate()
        logger.info(
            "checkpoint %s: iter %d coordinate %s (%d bytes, %.3fs)",
            name, payload["iteration"], payload["coordinate"], len(blob),
            save_seconds,
        )
        return ckpt_dir

    @staticmethod
    def _payload_dict(state, summed_scores) -> dict:
        return {
            "iteration": int(state.iteration),
            "coordinate_index": int(state.coordinate_index),
            "coordinate": state.coordinate,
            "models": dict(state.models),
            "summed_scores": summed_scores,
            "best_eval": state.best_eval,
            "best_models": dict(state.best_models),
            "evaluations": list(state.evaluations),
            "tracker_summaries": {
                name: t.to_summary_string() for name, t in state.trackers.items()
            },
            "train_losses": {
                k: float(v)
                for k, v in (getattr(state, "train_losses", None) or {}).items()
            },
        }

    def _topology_meta(self, global_rows: int) -> dict:
        return {
            **self.topology,
            "n_processes": self.n_processes,
            "global_rows": int(global_rows),
        }

    def _local_shard(self, summed_scores) -> np.ndarray:
        """This process's rows of the summed scores. A globally sharded
        jax.Array yields the addressable rows (``host_local_rows``); a
        host-local array (replicated small runs, in-process tests) is
        already the shard."""
        try:
            import jax
        except Exception:  # photon: ignore[R4] - no-jax fallback: host array
            return np.asarray(summed_scores)
        if isinstance(summed_scores, jax.Array) and jax.process_count() > 1:
            from ..parallel import multihost

            return np.asarray(multihost.host_local_rows(summed_scores))
        return np.asarray(summed_scores)

    def _save_distributed(self, state, meta: Optional[dict] = None) -> str:
        """Two-phase consistent save across ``n_processes`` (see module
        docstring). Phase one (all processes): write the local summed-score
        shard, confirm its digest over the exchange collective. Phase two
        (coordinator): payload, then — the commit point — the manifest. The
        ``dist.commit`` fault site fires at phase-one entry and again on the
        coordinator right before the manifest, so tests can tear the save
        at either stage and watch restore fall back."""
        t0 = time.perf_counter()
        faults.check("dist.commit")
        name = f"{_DIR_PREFIX}{self._seq:06d}"
        ckpt_dir = os.path.join(self.directory, name)
        os.makedirs(ckpt_dir, exist_ok=True)
        local = self._local_shard(state.summed_scores)
        shard_blob = pickle.dumps(
            {"process": self.process, "summed_scores": local},
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        shard_name = f"{SHARD_PREFIX}{self.process}.pkl"
        io_call(
            atomic_write_bytes,
            os.path.join(ckpt_dir, shard_name),
            shard_blob,
            fsync=self.fsync,
            site="checkpoint.write",
        )
        confirm = {
            "process": self.process,
            "file": shard_name,
            "sha256": hashlib.sha256(shard_blob).hexdigest(),
            "bytes": len(shard_blob),
            "rows": int(local.shape[0]),
        }
        exchange = self.exchange
        if exchange is None:
            from ..parallel import multihost

            exchange = multihost.allgather_object
        confirms = sorted(exchange(confirm), key=lambda c: c["process"])
        # every process advances in lockstep past the exchange barrier, so
        # the NEXT boundary's directory name agrees even if this commit tears
        self._seq += 1
        if self.process != 0:
            return ckpt_dir
        payload = self._payload_dict(state, None)
        blob = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
        digest = hashlib.sha256(blob).hexdigest()
        io_call(
            atomic_write_bytes,
            os.path.join(ckpt_dir, PAYLOAD_NAME),
            blob,
            fsync=self.fsync,
            site="checkpoint.write",
        )
        # commit point: shards + payload are durable, the manifest is not —
        # a kill here is the torn save restore must survive
        faults.check("dist.commit")
        manifest = {
            "version": MANIFEST_VERSION,
            "step": self._seq - 1,
            "iteration": int(state.iteration),
            "coordinate_index": int(state.coordinate_index),
            "coordinate": state.coordinate,
            "coordinate_order": list(state.coordinate_order),
            "n_iterations": int(state.n_iterations),
            "payload": PAYLOAD_NAME,
            "sha256": digest,
            "bytes": len(blob),
            "created_unix": time.time(),
            "shards": confirms,
            "topology": self._topology_meta(
                global_rows=sum(c["rows"] for c in confirms)
            ),
            **self.base_meta,
            **(meta or {}),
        }
        io_call(
            atomic_write_json,
            os.path.join(ckpt_dir, MANIFEST_NAME),
            manifest,
            fsync=self.fsync,
            indent=2,
            site="checkpoint.manifest",
        )
        total_bytes = len(blob) + sum(c["bytes"] for c in confirms)
        save_seconds = time.perf_counter() - t0
        reg = _registry()
        reg.counter(
            "photon_checkpoint_saves_total", "boundary checkpoints written"
        ).inc()
        reg.counter(
            "photon_checkpoint_bytes_total", "checkpoint payload bytes written"
        ).inc(total_bytes)
        reg.histogram(
            "photon_checkpoint_save_seconds", "wall per boundary checkpoint save"
        ).observe(save_seconds)
        self._rotate()
        logger.info(
            "checkpoint %s: iter %d coordinate %s (%d procs, %d bytes, %.3fs)",
            name, manifest["iteration"], manifest["coordinate"],
            self.n_processes, total_bytes, save_seconds,
        )
        return ckpt_dir

    def _rotate(self) -> None:
        steps = sorted(self._steps_on_disk())
        for step in steps[: max(0, len(steps) - self.keep_last)]:
            shutil.rmtree(
                os.path.join(self.directory, f"{_DIR_PREFIX}{step:06d}"),
                ignore_errors=True,
            )

    def _steps_on_disk(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            if name.startswith(_DIR_PREFIX):
                try:
                    out.append(int(name[len(_DIR_PREFIX):]))
                except ValueError:
                    continue
        return out

    # -- restoring ------------------------------------------------------------

    def latest_valid(
        self,
        expect_coordinate_order: Optional[Sequence[str]] = None,
        expect_n_iterations: Optional[int] = None,
        expect_topology: Optional[dict] = None,
    ) -> Optional[CheckpointSnapshot]:
        """Newest checkpoint that passes manifest + digest validation,
        falling back past corrupt ones (each skip warned and counted).
        ``expect_*`` pins the run configuration: the newest VALID checkpoint
        failing those checks raises :class:`CheckpointIncompatibleError` —
        silently resuming an incompatible snapshot (or silently skipping to
        a stale compatible one) would both train the wrong model.
        ``expect_topology`` is the resuming run's topology (process count,
        mesh axes, plan fingerprint, padded global rows), judged by the plan
        layer: a mismatch with no legal reshape is a refusal, not a shape
        crash deep in the sweep."""
        for step in sorted(self._steps_on_disk(), reverse=True):
            name = f"{_DIR_PREFIX}{step:06d}"
            ckpt_dir = os.path.join(self.directory, name)
            try:
                manifest, payload = self._load_validated(ckpt_dir)
            except (OSError, ValueError, KeyError, pickle.UnpicklingError, EOFError) as e:
                logger.warning("checkpoint %s unusable (%s); falling back", name, e)
                _count_skip("corrupt")
                continue
            if (
                expect_coordinate_order is not None
                and manifest["coordinate_order"] != list(expect_coordinate_order)
            ):
                raise CheckpointIncompatibleError(
                    f"checkpoint {ckpt_dir} was written for coordinates "
                    f"{manifest['coordinate_order']}, this run trains "
                    f"{list(expect_coordinate_order)}; refusing to resume — "
                    "pass a fresh checkpoint directory"
                )
            if (
                expect_n_iterations is not None
                and manifest["n_iterations"] != expect_n_iterations
            ):
                raise CheckpointIncompatibleError(
                    f"checkpoint {ckpt_dir} was written for "
                    f"{manifest['n_iterations']} coordinate-descent "
                    f"iterations, this run uses {expect_n_iterations}; "
                    "refusing to resume — pass a fresh checkpoint directory"
                )
            if expect_topology is not None:
                from ..plan import PlanError, planner

                try:
                    planner.check_checkpoint_topology(
                        manifest.get("topology") or {}, expect_topology
                    )
                except PlanError as e:
                    raise CheckpointIncompatibleError(
                        f"checkpoint {ckpt_dir}: {e}"
                    ) from e
            _registry().counter(
                "photon_checkpoint_restore_total", "checkpoints restored"
            ).inc()
            logger.info(
                "resuming from checkpoint %s: iter %d after coordinate %s",
                name, payload["iteration"], payload["coordinate"],
            )
            return CheckpointSnapshot(
                iteration=payload["iteration"],
                coordinate_index=payload["coordinate_index"],
                coordinate=payload["coordinate"],
                models=payload["models"],
                summed_scores=payload["summed_scores"],
                best_eval=payload["best_eval"],
                best_models=payload["best_models"],
                evaluations=payload["evaluations"],
                tracker_summaries=payload["tracker_summaries"],
                manifest=manifest,
                path=ckpt_dir,
                train_losses=payload.get("train_losses", {}),
            )
        return None

    def _load_validated(self, ckpt_dir: str):
        """Manifest + digest-checked payload of one checkpoint dir; raises
        on any inconsistency (caller decides skip vs abort)."""
        with open(os.path.join(ckpt_dir, MANIFEST_NAME), encoding="utf-8") as f:
            manifest = json.load(f)
        if manifest.get("version") != MANIFEST_VERSION:
            raise ValueError(
                f"manifest version {manifest.get('version')!r} != "
                f"{MANIFEST_VERSION}"
            )
        for key in ("sha256", "payload", "coordinate_order", "n_iterations"):
            if key not in manifest:
                raise KeyError(f"manifest missing {key!r}")

        def read_payload():
            with open(os.path.join(ckpt_dir, manifest["payload"]), "rb") as f:
                return f.read()

        blob = io_call(read_payload, site="checkpoint.read")
        digest = hashlib.sha256(blob).hexdigest()
        if digest != manifest["sha256"]:
            raise ValueError(
                f"payload digest {digest[:12]}... != manifest "
                f"{manifest['sha256'][:12]}... (truncated or corrupt write)"
            )
        payload = pickle.loads(blob)
        shards = manifest.get("shards")
        if shards:
            # two-phase save: the payload carries everything except the
            # summed scores, which live in per-process shards — verify each
            # digest and re-concatenate in process order (row order is the
            # global row order, so this is also how a legal reshape under a
            # different process count reassembles)
            parts = []
            for rec in sorted(shards, key=lambda r: r["process"]):
                shard_path = os.path.join(ckpt_dir, rec["file"])

                def read_shard(path=shard_path):
                    with open(path, "rb") as f:
                        return f.read()

                sblob = io_call(read_shard, site="checkpoint.read")
                sdigest = hashlib.sha256(sblob).hexdigest()
                if sdigest != rec["sha256"]:
                    raise ValueError(
                        f"shard {rec['file']} digest {sdigest[:12]}... != "
                        f"manifest {rec['sha256'][:12]}... (torn "
                        "multi-process save)"
                    )
                parts.append(np.asarray(pickle.loads(sblob)["summed_scores"]))
            payload["summed_scores"] = np.concatenate(parts, axis=0)
        return manifest, payload

    def checkpoints(self) -> List[str]:
        """Checkpoint directories on disk, oldest first (for tests/tools)."""
        return [
            os.path.join(self.directory, f"{_DIR_PREFIX}{s:06d}")
            for s in sorted(self._steps_on_disk())
        ]
