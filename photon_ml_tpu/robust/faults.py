"""Deterministic fault injection at named sites.

The fault-tolerance claims of this package ("kill at any coordinate-update
boundary and resume reproduces the run", "transient IO errors succeed within
the retry budget") are only claims until something can actually produce
those failures on demand. This module is that something: IO boundaries and
checkpoint boundaries call :func:`check` with a site name, and an activated
injector raises either a transient :class:`InjectedIOError` (an ``OSError``
subclass, so the retry policy classifies it retryable) or a
:class:`SimulatedKill` (a ``BaseException`` subclass that no ``except
Exception`` on the way out can accidentally swallow — the closest a test can
get to ``kill -9`` without leaving the process).

Default-off and cheap when off: :func:`check` is a module-global ``None``
test, and no site maintains any state until an injector is installed. The
hot CD loop itself carries NO check calls — sites live at IO and checkpoint
boundaries only — so the zero-fetch sweep is untouched either way.

Activation:

- programmatic (tests): ``faults.configure("checkpoint.save:io:1x2")``
- environment (CLI runs): ``PHOTON_FAULTS=<spec>`` with optional
  ``PHOTON_FAULTS_SEED=<int>``; ``cli.train`` installs it at startup.

Spec grammar (comma-separated clauses)::

    SITE:KIND:WHEN
    KIND = io | kill | nan | delay[MS]
    WHEN = N      fire on the N-th call to the site (1-based)
         | NxM    fire on calls N..N+M-1 (M consecutive transient errors)
         | pF     fire on each call with probability F (seeded, so the
                  schedule is deterministic for a given seed)

``io.avro_read:io:1x2`` fails the first two Avro reads then lets the third
succeed; ``cd.boundary:kill:3:`` kills the process at the third
coordinate-update boundary.

The ``delay`` kind never raises either: it sleeps at the site (``delay`` =
50 ms, ``delay200`` = 200 ms) and returns, simulating a slow dependency
instead of a broken one. The serving plane carries two such sites —
``serving.score`` (in the microbatcher, just before the engine call: a
delay storm there is the slow-engine chaos drill that drives the admission
controller past its deadline budget) and ``serving.refresh`` (in the
snapshot watcher: a delay stalls a flip, an ``io`` error there is swallowed
and retried next poll while the live model keeps serving). With multi-model
residency (``serving/fleet.py``) the batcher checks ``serving.score`` and
then a per-model variant spelled ``serving.score.<model>`` — dynamic, so it
is deliberately NOT in the static fault inventory — which keys a chaos
storm to ONE resident model (``serving.score.jobs-us:delay200:p1``) and
proves the bulkhead: the stormed model sheds, its neighbours' batches never
feel it. The replica fleet (``serving/front.py``) adds two more sites:
``serving.route`` at every routing decision (an injected error sheds the
request with a typed ``route`` response — routing failures refuse, never
drop) and ``serving.replica`` at every replica send (an injected ``io``
error is a replica connection dying mid-request: the front marks the
replica down and resubmits its outstanding requests — same ``trace_id`` —
to the survivors, the failover drill without killing a process).

The ``nan`` kind never raises: it acts through :func:`corrupt`, which sites
holding concrete arrays call as ``tree = faults.corrupt(site, tree)``. When
the schedule fires, NaN is planted at flat index 0 of every floating-point
leaf (deterministic — the same spec corrupts the same element every run),
exercising the numerical-divergence defenses (solver rollback, coordinate
rejection) without contriving pathological input data.
``solver.value_and_grad:nan:3`` corrupts the effective offsets of the third
host-level coordinate solve; ``coordinate.scores:nan:p0.3`` corrupts each
coordinate's freshly computed scores with probability 0.3.

The continuous-training chain (``game/incremental.py``) adds two sites:
``retrain.day`` fires once per chain day before any of its work
(``retrain.day:kill:2`` is the crash-between-days drill — the ledger
resumes), and ``retrain.publish`` fires immediately before a snapshot
publication (``retrain.publish:io:1`` is the torn-publish drill — the gate
decision is already durable in the ledger, the previous snapshot keeps
serving, and the next cycle repairs the store).

The distributed liveness plane (``robust/distributed.py``) adds three
process-level sites: ``dist.heartbeat`` fires on every heartbeat record
write (``io`` starves the record so peers see staleness; ``kill`` takes
down the heartbeat thread — a process whose liveness plane died while its
compute continues), ``dist.collective`` fires exactly once per CD sweep at
the sweep-boundary barrier (``dist.collective:kill:2`` on one worker is
the kill-a-worker drill: the worker dies at its second boundary and every
survivor gets a typed ``DistributedTimeoutError`` within the collective
budget; ``delay`` holds a process out of the rendezvous instead), and
``dist.commit`` brackets the two-phase checkpoint commit (phase-one entry
on every process, plus the coordinator's pre-manifest commit point — an
``io`` or ``kill`` at either stage tears the save and restore falls back
to the previous consistent step).
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
from typing import Dict, List, Optional


class InjectedIOError(OSError):
    """Transient IO failure raised by the injector (retryable by policy)."""


class SimulatedKill(BaseException):
    """Simulated process kill. Deliberately NOT an ``Exception`` subclass:
    retry policies, event-emitter swallowing, and broad handlers must all
    let it through, exactly like a real SIGKILL would not be catchable."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    site: str
    kind: str  # "io" | "kill" | "nan" | "delay"
    at: int = 1  # first firing call index, 1-based ("NxM" / "N" forms)
    times: int = 1  # consecutive firings from ``at``
    prob: Optional[float] = None  # "pF" form: seeded per-call probability
    delay_s: float = 0.05  # "delay" kind: sleep length at the site

    def __post_init__(self):
        if self.kind not in ("io", "kill", "nan", "delay"):
            raise ValueError(
                f"fault kind must be io|kill|nan|delay[MS]: {self.kind!r}"
            )
        if self.prob is None and self.at < 1:
            raise ValueError(f"fault index is 1-based: {self.at}")
        if self.delay_s < 0:
            raise ValueError(f"fault delay must be >= 0: {self.delay_s}")


def parse_faults(spec: str) -> List[FaultSpec]:
    """Parse the ``PHOTON_FAULTS`` grammar (see module docstring)."""
    out: List[FaultSpec] = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"fault clause {clause!r}: expected SITE:KIND:WHEN "
                "(e.g. io.avro_read:io:1x2)"
            )
        site, kind, when = (p.strip() for p in parts)
        extra = {}
        if kind.startswith("delay"):
            ms = kind[len("delay"):]
            kind = "delay"
            if ms:
                extra["delay_s"] = float(ms) / 1e3
        if when.startswith("p"):
            out.append(
                FaultSpec(site=site, kind=kind, prob=float(when[1:]), **extra)
            )
        elif "x" in when:
            at, times = when.split("x", 1)
            out.append(
                FaultSpec(
                    site=site, kind=kind, at=int(at), times=int(times), **extra
                )
            )
        else:
            out.append(FaultSpec(site=site, kind=kind, at=int(when), **extra))
    return out


class FaultInjector:
    """Seeded, deterministic per-site fault schedule."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._rng: Dict[str, random.Random] = {}
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, []).append(s)

    def _schedule(self, site: str):
        """Count one call at ``site``; return (firing spec or None, call n)."""
        specs = self._by_site.get(site)
        if not specs:
            return None, 0
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            for s in specs:
                if s.prob is not None:
                    # one rng per site, seeded by (seed, site): the schedule
                    # is a pure function of the seed, not of call interleaving
                    # across sites
                    rng = self._rng.get(site)
                    if rng is None:
                        rng = random.Random(f"{self.seed}:{site}")
                        self._rng[site] = rng
                    if rng.random() < s.prob:
                        return s, n
                elif s.at <= n < s.at + s.times:
                    return s, n
        return None, n

    def _raise(self, fire: FaultSpec, site: str, n: int) -> None:
        _count_injection(site, fire.kind)
        if fire.kind == "kill":
            raise SimulatedKill(f"injected kill at site {site!r} (call {n})")
        raise InjectedIOError(f"injected IO error at site {site!r} (call {n})")

    def _sleep(self, fire: FaultSpec, site: str) -> None:
        _count_injection(site, "delay")
        time.sleep(fire.delay_s)

    def hit(self, site: str) -> None:
        """Record one call at ``site``; raise if a spec says this call fails.
        ``delay`` specs sleep instead of raising; ``nan`` specs never fire
        here — a check-only site holds no arrays to corrupt; they act
        through :meth:`corrupt`."""
        fire, n = self._schedule(site)
        if fire is None or fire.kind == "nan":
            return
        if fire.kind == "delay":
            self._sleep(fire, site)
            return
        self._raise(fire, site, n)

    def corrupt(self, site: str, tree):
        """Record one call at ``site``; return ``tree`` with NaN planted into
        its floating-point array leaves when a ``nan`` spec fires (io/kill
        specs at a corrupt site raise exactly as :meth:`hit` would, delay
        specs sleep and pass the tree through untouched)."""
        fire, n = self._schedule(site)
        if fire is None:
            return tree
        if fire.kind == "delay":
            self._sleep(fire, site)
            return tree
        if fire.kind != "nan":
            self._raise(fire, site, n)
        _count_injection(site, "nan")
        return _plant_nan(tree)

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)


def _count_injection(site: str, kind: str) -> None:
    from .. import obs

    obs.current_run().registry.counter(
        "photon_faults_injected_total", "faults raised by the injector"
    ).labels(site=site, kind=kind).inc()


def _plant_nan(tree):
    """NaN planted at flat index 0 of every floating-point array leaf —
    deterministic, so a given spec corrupts the same element on every run.
    Non-float and empty leaves pass through untouched. Device arrays are
    corrupted ON DEVICE (a pure scatter, legal under the sweep's transfer
    guard); host numpy leaves are copied, never mutated in place."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    def plant(leaf):
        if isinstance(leaf, np.ndarray):
            if leaf.size and np.issubdtype(leaf.dtype, np.floating):
                out = leaf.copy()
                out.ravel()[0] = np.nan
                return out
            return leaf
        if (
            isinstance(leaf, jax.Array)
            and leaf.size
            and jnp.issubdtype(leaf.dtype, jnp.floating)
        ):
            flat = jnp.reshape(leaf, (-1,)).at[0].set(jnp.nan)
            return jnp.reshape(flat, leaf.shape)
        return leaf

    return jax.tree_util.tree_map(plant, tree)


# the one module-global the hot path reads; None == disabled
_injector: Optional[FaultInjector] = None


def check(site: str) -> None:
    """Fault-injection hook: no-op (one ``is None`` test) unless an injector
    is installed. Call at IO / checkpoint boundaries, never in hot loops."""
    inj = _injector
    if inj is not None:
        inj.hit(site)


def corrupt(site: str, tree):
    """NaN-injection hook for sites holding concrete arrays: pass-through
    (one ``is None`` test) unless an injector with a ``nan`` spec for this
    site decides the call fires. Call where arrays are HOST-CONCRETE — never
    under a jit trace, where the host-side schedule decision would bake into
    the compiled function."""
    inj = _injector
    if inj is None:
        return tree
    return inj.corrupt(site, tree)


def active() -> bool:
    return _injector is not None


def configure(spec, seed: int = 0) -> FaultInjector:
    """Install an injector from a spec string or list of FaultSpecs."""
    global _injector
    specs = parse_faults(spec) if isinstance(spec, str) else list(spec)
    # lock-free publish by design: check()/corrupt() run on hot serving and
    # IO threads and must stay a single is-None test, so workers snapshot
    # the reference once per call (inj = _injector) and CPython reference
    # assignment is atomic — a reader sees the old or the new injector,
    # never a torn one
    # photon: thread-confined
    _injector = FaultInjector(specs, seed=seed)
    return _injector


def clear() -> None:
    global _injector
    _injector = None


def install_from_env(env=os.environ) -> Optional[FaultInjector]:
    """Install from ``PHOTON_FAULTS`` / ``PHOTON_FAULTS_SEED`` if set; clears
    any previous injector when the variable is absent (so a resumed CLI run
    without the env var starts clean)."""
    spec = env.get("PHOTON_FAULTS", "").strip()
    if not spec:
        clear()
        return None
    seed = int(env.get("PHOTON_FAULTS_SEED", "0"))
    return configure(spec, seed=seed)
