"""Deterministic fault injection at named sites.

The fault-tolerance claims of this package ("kill at any coordinate-update
boundary and resume reproduces the run", "transient IO errors succeed within
the retry budget") are only claims until something can actually produce
those failures on demand. This module is that something: IO boundaries and
checkpoint boundaries call :func:`check` with a site name, and an activated
injector raises either a transient :class:`InjectedIOError` (an ``OSError``
subclass, so the retry policy classifies it retryable) or a
:class:`SimulatedKill` (a ``BaseException`` subclass that no ``except
Exception`` on the way out can accidentally swallow — the closest a test can
get to ``kill -9`` without leaving the process).

Default-off and cheap when off: :func:`check` is a module-global ``None``
test, and no site maintains any state until an injector is installed. The
hot CD loop itself carries NO check calls — sites live at IO and checkpoint
boundaries only — so the zero-fetch sweep is untouched either way.

Activation:

- programmatic (tests): ``faults.configure("checkpoint.save:io:1x2")``
- environment (CLI runs): ``PHOTON_FAULTS=<spec>`` with optional
  ``PHOTON_FAULTS_SEED=<int>``; ``cli.train`` installs it at startup.

Spec grammar (comma-separated clauses)::

    SITE:KIND:WHEN
    KIND = io | kill
    WHEN = N      fire on the N-th call to the site (1-based)
         | NxM    fire on calls N..N+M-1 (M consecutive transient errors)
         | pF     fire on each call with probability F (seeded, so the
                  schedule is deterministic for a given seed)

``io.avro_read:io:1x2`` fails the first two Avro reads then lets the third
succeed; ``cd.boundary:kill:3:`` kills the process at the third
coordinate-update boundary.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
from typing import Dict, List, Optional


class InjectedIOError(OSError):
    """Transient IO failure raised by the injector (retryable by policy)."""


class SimulatedKill(BaseException):
    """Simulated process kill. Deliberately NOT an ``Exception`` subclass:
    retry policies, event-emitter swallowing, and broad handlers must all
    let it through, exactly like a real SIGKILL would not be catchable."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    site: str
    kind: str  # "io" | "kill"
    at: int = 1  # first firing call index, 1-based ("NxM" / "N" forms)
    times: int = 1  # consecutive firings from ``at``
    prob: Optional[float] = None  # "pF" form: seeded per-call probability

    def __post_init__(self):
        if self.kind not in ("io", "kill"):
            raise ValueError(f"fault kind must be io|kill: {self.kind!r}")
        if self.prob is None and self.at < 1:
            raise ValueError(f"fault index is 1-based: {self.at}")


def parse_faults(spec: str) -> List[FaultSpec]:
    """Parse the ``PHOTON_FAULTS`` grammar (see module docstring)."""
    out: List[FaultSpec] = []
    for clause in spec.split(","):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"fault clause {clause!r}: expected SITE:KIND:WHEN "
                "(e.g. io.avro_read:io:1x2)"
            )
        site, kind, when = (p.strip() for p in parts)
        if when.startswith("p"):
            out.append(FaultSpec(site=site, kind=kind, prob=float(when[1:])))
        elif "x" in when:
            at, times = when.split("x", 1)
            out.append(FaultSpec(site=site, kind=kind, at=int(at), times=int(times)))
        else:
            out.append(FaultSpec(site=site, kind=kind, at=int(when)))
    return out


class FaultInjector:
    """Seeded, deterministic per-site fault schedule."""

    def __init__(self, specs: List[FaultSpec], seed: int = 0):
        self.specs = list(specs)
        self.seed = seed
        self._lock = threading.Lock()
        self._calls: Dict[str, int] = {}
        self._rng: Dict[str, random.Random] = {}
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for s in self.specs:
            self._by_site.setdefault(s.site, []).append(s)

    def hit(self, site: str) -> None:
        """Record one call at ``site``; raise if a spec says this call fails."""
        specs = self._by_site.get(site)
        if not specs:
            return
        with self._lock:
            n = self._calls.get(site, 0) + 1
            self._calls[site] = n
            fire: Optional[FaultSpec] = None
            for s in specs:
                if s.prob is not None:
                    # one rng per site, seeded by (seed, site): the schedule
                    # is a pure function of the seed, not of call interleaving
                    # across sites
                    rng = self._rng.get(site)
                    if rng is None:
                        rng = random.Random(f"{self.seed}:{site}")
                        self._rng[site] = rng
                    if rng.random() < s.prob:
                        fire = s
                        break
                elif s.at <= n < s.at + s.times:
                    fire = s
                    break
        if fire is None:
            return
        _count_injection(site, fire.kind)
        if fire.kind == "kill":
            raise SimulatedKill(f"injected kill at site {site!r} (call {n})")
        raise InjectedIOError(f"injected IO error at site {site!r} (call {n})")

    def calls(self, site: str) -> int:
        with self._lock:
            return self._calls.get(site, 0)


def _count_injection(site: str, kind: str) -> None:
    from .. import obs

    obs.current_run().registry.counter(
        "photon_faults_injected_total", "faults raised by the injector"
    ).labels(site=site, kind=kind).inc()


# the one module-global the hot path reads; None == disabled
_injector: Optional[FaultInjector] = None


def check(site: str) -> None:
    """Fault-injection hook: no-op (one ``is None`` test) unless an injector
    is installed. Call at IO / checkpoint boundaries, never in hot loops."""
    inj = _injector
    if inj is not None:
        inj.hit(site)


def active() -> bool:
    return _injector is not None


def configure(spec, seed: int = 0) -> FaultInjector:
    """Install an injector from a spec string or list of FaultSpecs."""
    global _injector
    specs = parse_faults(spec) if isinstance(spec, str) else list(spec)
    _injector = FaultInjector(specs, seed=seed)
    return _injector


def clear() -> None:
    global _injector
    _injector = None


def install_from_env(env=os.environ) -> Optional[FaultInjector]:
    """Install from ``PHOTON_FAULTS`` / ``PHOTON_FAULTS_SEED`` if set; clears
    any previous injector when the variable is absent (so a resumed CLI run
    without the env var starts clean)."""
    spec = env.get("PHOTON_FAULTS", "").strip()
    if not spec:
        clear()
        return None
    seed = int(env.get("PHOTON_FAULTS_SEED", "0"))
    return configure(spec, seed=seed)
