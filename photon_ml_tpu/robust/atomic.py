"""Crash-safe file writes: write-to-temp + fsync + atomic rename.

The Spark reference never thinks about torn writes — HDFS output committers
rename a finished task directory into place. The JAX port writes files
directly, so every model / manifest / stats write is one preemption away
from a partial file that a later ``load_game_model`` happily half-parses.
This module is the single choke point that closes that hole: all durable
file creation in ``io/`` and ``robust/`` routes through :func:`atomic_write`
(enforced by lint rule R5), which guarantees a reader sees either the old
complete file or the new complete file, never a prefix.

The sequence is the classic POSIX recipe: write ``<path>.tmp.<pid>``, flush,
``os.fsync`` the file (data durable before the name flips), ``os.replace``
onto the final name (atomic within a filesystem), then best-effort fsync the
parent directory so the rename itself survives a power cut. ``fsync=False``
skips both fsyncs for callers on hot paths that only need atomicity against
crashes of THIS process, not media durability.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import IO, Iterator, Optional


def _fsync_dir(path: str) -> None:
    """Best-effort fsync of a directory (persists a rename); some platforms
    and filesystems refuse O_RDONLY dir fds — treat that as non-fatal."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(
    path: str,
    mode: str = "w",
    encoding: Optional[str] = None,
    fsync: bool = True,
) -> Iterator[IO]:
    """Context manager yielding a file object whose contents replace ``path``
    atomically on clean exit; on error the temp file is removed and ``path``
    is untouched.

    ``mode`` must be a fresh-write mode ('w', 'wb'); append modes make no
    sense under replace semantics."""
    if "a" in mode or "+" in mode or "r" in mode:
        raise ValueError(f"atomic_write needs a fresh-write mode, got {mode!r}")
    if encoding is None and "b" not in mode:
        encoding = "utf-8"
    directory = os.path.dirname(path) or "."
    tmp = os.path.join(directory, f"{os.path.basename(path)}.tmp.{os.getpid()}")
    # photon: ignore[R5] — this IS the atomic-write helper (temp then replace)
    f = open(tmp, mode, encoding=encoding)
    try:
        yield f
        f.flush()
        if fsync:
            os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(directory)
    except BaseException:
        # leave no droppings: close and remove the temp, keep ``path`` as-is
        try:
            f.close()
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise


def atomic_write_bytes(path: str, data: bytes, fsync: bool = True) -> None:
    with atomic_write(path, "wb", fsync=fsync) as f:
        f.write(data)


def atomic_write_text(path: str, text: str, fsync: bool = True) -> None:
    with atomic_write(path, "w", fsync=fsync) as f:
        f.write(text)


def atomic_write_json(path: str, doc, fsync: bool = True, **dump_kwargs) -> None:
    with atomic_write(path, "w", fsync=fsync) as f:
        json.dump(doc, f, **dump_kwargs)
        f.write("\n")
