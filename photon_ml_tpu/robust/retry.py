"""Bounded, seeded retry with exponential backoff + jitter.

The Spark reference gets task-level retry from its scheduler (four attempts
per task by default); a transient NFS hiccup re-runs the task and the job
never notices. Here every Avro read, index-map load, and model / checkpoint
write is one syscall failure away from discarding hours of training. This
module is the port of that scheduler behavior to library form: wrap the IO
call in a :class:`RetryPolicy` and transient failures are retried with
exponential backoff, while exhausted budgets re-raise the ORIGINAL error
(never a wrapper — callers' except clauses and tests keep matching).

Properties the tests pin down:

- bounded: at most ``max_attempts`` calls, then the last exception re-raises;
- classified: only ``retryable`` exception types retry — everything else
  (including :class:`robust.faults.SimulatedKill`, a BaseException)
  propagates immediately;
- seeded: jitter comes from ``random.Random(seed)``, so backoff schedules
  are reproducible in tests and across resumed runs;
- observable: every retried failure increments
  ``photon_retry_attempts_total{site=}`` in the current obs registry, so a
  flaky filesystem shows up in run_summary.json instead of only in latency.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, List, Tuple, Type

logger = logging.getLogger("photon_ml_tpu")


def _count_retry(site: str, delay: float) -> None:
    # lazy import: robust sits below obs consumers but obs itself imports
    # nothing from robust, so this is only about avoiding a module-level
    # dependency for callers that never retry
    from .. import obs

    reg = obs.current_run().registry
    reg.counter(
        "photon_retry_attempts_total",
        "IO attempts that failed and were retried, by site",
    ).labels(site=site).inc()
    reg.histogram(
        "photon_retry_backoff_seconds",
        "backoff slept before an IO retry, by site",
    ).labels(site=site).observe(delay)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: delay ``base_delay * multiplier**k``, capped at
    ``max_delay``, each delay jittered uniformly in ``[1-jitter, 1+jitter]``
    by a generator seeded per :meth:`call` (deterministic schedules)."""

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5
    seed: int = 0
    retryable: Tuple[Type[BaseException], ...] = (OSError,)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1: {self.max_attempts}")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError(f"jitter must be in [0, 1]: {self.jitter}")

    def is_retryable(self, exc: BaseException) -> bool:
        return isinstance(exc, self.retryable)

    def delays(self) -> List[float]:
        """The jittered sleep schedule between attempts (len max_attempts-1)."""
        rng = random.Random(self.seed)
        out = []
        for k in range(self.max_attempts - 1):
            d = min(self.base_delay * self.multiplier**k, self.max_delay)
            out.append(d * (1.0 + self.jitter * (2.0 * rng.random() - 1.0)))
        return out

    def call(
        self,
        fn: Callable,
        *args,
        site: str = "unlabeled",
        sleep: Callable[[float], None] = time.sleep,
        **kwargs,
    ):
        """Run ``fn(*args, **kwargs)`` under this policy. Retries only
        classified-retryable exceptions; after ``max_attempts`` failures the
        original (last) exception re-raises unchanged."""
        delays = self.delays()
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retryable as e:
                if attempt == self.max_attempts - 1:
                    raise
                _count_retry(site, delays[attempt])
                logger.warning(
                    "retryable failure at %s (attempt %d/%d): %s; retrying "
                    "in %.3fs",
                    site, attempt + 1, self.max_attempts, e, delays[attempt],
                )
                sleep(delays[attempt])
        raise AssertionError("unreachable")  # pragma: no cover

    def wrap(self, site: str, sleep: Callable[[float], None] = time.sleep):
        """Decorator form: ``@policy.wrap("io.avro_read")``."""

        def deco(fn):
            def inner(*args, **kwargs):
                return self.call(fn, *args, site=site, sleep=sleep, **kwargs)

            inner.__name__ = getattr(fn, "__name__", "wrapped")
            inner.__doc__ = fn.__doc__
            return inner

        return deco


# The shared default for library IO sites. Module-level so the CLI (or a
# test) can swap one policy for every site at once; sites that need a
# different budget construct their own.
DEFAULT_IO_POLICY = RetryPolicy()


def io_call(fn: Callable, *args, site: str, **kwargs):
    """``DEFAULT_IO_POLICY.call`` with the fault-injection hook folded in:
    the injector fires BEFORE the real call, so an injected transient error
    exercises the same retry path a real one would."""
    from . import faults

    def attempt():
        faults.check(site)
        return fn(*args, **kwargs)

    return DEFAULT_IO_POLICY.call(attempt, site=site)
