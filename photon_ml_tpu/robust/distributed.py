"""Distributed fault tolerance: heartbeats, bounded-time collectives.

The reference survives executor loss because Spark's scheduler notices a
dead executor (missed heartbeats), fails the stage within a bounded time,
and re-runs lost tasks from lineage. A JAX multi-process run has none of
that by default: one dead or stalled process leaves every peer blocked
inside the next collective *forever* — no error, no exit code, no
postmortem. This module is the liveness layer the multi-process substrate
was missing:

- **Heartbeat plane** — each process runs a :class:`HeartbeatWriter` daemon
  thread that writes a monotonic liveness record (``heartbeat-p<i>.json``,
  a strictly increasing ``seq`` plus a wall stamp) into the shared run
  directory via :mod:`robust.atomic`, so a reader never sees a torn record.
  Peers read ages with :func:`heartbeat_ages` (exported as the
  ``photon_dist_heartbeat_age_seconds{process=}`` gauge) and
  :func:`check_peers` raises a typed :class:`PeerLostError` for a peer
  whose record is stale or absent. The plane is pure host-side file IO —
  it never touches a device, so the zero-fetch sweep is unaffected.

- **Bounded-time collectives** — :func:`barrier_with_timeout` rendezvouses
  all processes through the jax coordination service with a deadline: a
  dead peer turns the infinite hang into a typed
  :class:`DistributedTimeoutError` within the configured budget, decorated
  with whatever the heartbeat plane knows about which peer died.
  :func:`configure_collectives` arms a process-wide budget;
  :func:`guard_collective` is the pre-collective rendezvous
  ``parallel/multihost.py`` runs before its object collectives (if every
  process reaches the barrier, the collective that follows has all its
  participants), and ``game/descent.py`` calls :func:`sweep_barrier` at
  every CD sweep boundary so a mid-sweep death is detected at the next
  boundary. On timeout ``cli train`` dumps a ``peer_lost`` flight-recorder
  postmortem and exits nonzero — bounded-time failure instead of a hang.

Fault sites (see :mod:`robust.faults`): ``dist.heartbeat`` fires on every
heartbeat write (``io`` starves the record so peers see staleness, ``kill``
takes down the heartbeat thread — the closest simulation of a process whose
liveness plane died), and ``dist.collective`` fires at sweep-boundary
barrier entry only (``delay`` holds one process out of the rendezvous past
the budget, ``kill`` is the kill-a-worker drill — the peer dies, the
survivor times out). The two-phase checkpoint commit has its own
``dist.commit`` site in :mod:`robust.checkpoint`.

Single-process behavior is identical to before: every entry point degrades
to a no-op when the process count is 1 (the fault site still fires, so the
semantics stay unit-testable without a cluster).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

from . import faults
from .atomic import atomic_write_json

logger = logging.getLogger("photon_ml_tpu")

HEARTBEAT_PREFIX = "heartbeat-p"


class DistributedError(RuntimeError):
    """Base class for distributed liveness failures."""


class PeerLostError(DistributedError):
    """A peer process's heartbeat is stale or absent — it is presumed dead
    (or wedged), and collectives involving it will not complete."""


class DistributedTimeoutError(DistributedError):
    """A collective rendezvous did not complete within the configured
    budget — at least one peer never arrived. Raised instead of hanging."""


def _registry():
    from .. import obs

    return obs.current_run().registry


# -- the heartbeat plane ------------------------------------------------------


def heartbeat_path(run_dir: str, process: int) -> str:
    return os.path.join(run_dir, f"{HEARTBEAT_PREFIX}{int(process)}.json")


def write_heartbeat(
    run_dir: str, process: int, seq: int, fsync: bool = False
) -> str:
    """Write one liveness record (atomic: temp + rename, never torn).

    ``seq`` is the writer's monotonic beat counter — a reader can detect a
    wedged writer by the seq not advancing even when clocks disagree; the
    ``unix`` stamp is what :func:`heartbeat_ages` measures against (same
    host in the drills; NTP-synced hosts in a real fleet)."""
    faults.check("dist.heartbeat")
    path = heartbeat_path(run_dir, process)
    atomic_write_json(
        path,
        {
            "process": int(process),
            "seq": int(seq),
            "unix": time.time(),
            "pid": os.getpid(),
        },
        fsync=fsync,
    )
    return path


def read_heartbeats(run_dir: str) -> Dict[int, dict]:
    """All liveness records under ``run_dir``, by process index. A torn or
    unreadable record is skipped (the atomic writer makes that unreachable
    except mid-crash; a skipped record simply reads as a missing peer)."""
    out: Dict[int, dict] = {}
    if not os.path.isdir(run_dir):
        return out
    for name in os.listdir(run_dir):
        if not name.startswith(HEARTBEAT_PREFIX) or not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(run_dir, name), encoding="utf-8") as f:
                rec = json.load(f)
            out[int(rec["process"])] = rec
        except (OSError, ValueError, KeyError, TypeError):
            continue
    return out


def heartbeat_ages(
    run_dir: str, now: Optional[float] = None, record_metric: bool = True
) -> Dict[int, float]:
    """Seconds since each process's last beat, by process index; also sets
    the ``photon_dist_heartbeat_age_seconds{process=}`` gauge."""
    now = time.time() if now is None else now
    ages = {
        p: max(0.0, now - float(rec.get("unix", 0.0)))
        for p, rec in read_heartbeats(run_dir).items()
    }
    if record_metric and ages:
        gauge = _registry().gauge(
            "photon_dist_heartbeat_age_seconds",
            "seconds since each process's last liveness beat",
        )
        for p, age in ages.items():
            gauge.labels(process=str(p)).set(age)
    return ages


def stale_peers(
    run_dir: str,
    n_processes: int,
    stale_after_s: float,
    self_process: Optional[int] = None,
    now: Optional[float] = None,
) -> List[int]:
    """Peer process indices whose heartbeat is older than ``stale_after_s``
    or absent entirely (never started, or records unreadable)."""
    ages = heartbeat_ages(run_dir, now=now)
    return [
        p
        for p in range(int(n_processes))
        if p != self_process and ages.get(p, float("inf")) > stale_after_s
    ]


def check_peers(
    run_dir: str,
    n_processes: int,
    stale_after_s: float,
    self_process: Optional[int] = None,
    now: Optional[float] = None,
) -> None:
    """Raise :class:`PeerLostError` naming every stale/absent peer."""
    stale = stale_peers(
        run_dir, n_processes, stale_after_s, self_process=self_process, now=now
    )
    if stale:
        ages = heartbeat_ages(run_dir, now=now, record_metric=False)
        detail = ", ".join(
            f"p{p}={ages[p]:.1f}s" if p in ages else f"p{p}=never"
            for p in stale
        )
        raise PeerLostError(
            f"peer process(es) {stale} presumed lost: last heartbeat older "
            f"than {stale_after_s:.1f}s ({detail}) under {run_dir}"
        )


class HeartbeatWriter:
    """Daemon thread beating every ``interval_s`` into ``run_dir``.

    A failed beat (transient FS error, or an injected ``dist.heartbeat:io``)
    is swallowed and counted — the next beat repairs the record; only a
    ``dist.heartbeat:kill`` (a :class:`~robust.faults.SimulatedKill`, a
    ``BaseException``) takes the thread down, which is exactly the
    starved-liveness-plane drill: the process keeps computing but its peers
    stop hearing from it."""

    def __init__(
        self,
        run_dir: str,
        process: int,
        interval_s: float = 1.0,
        fsync: bool = False,
    ):
        if interval_s <= 0:
            raise ValueError(f"heartbeat interval must be > 0: {interval_s}")
        self.run_dir = run_dir
        self.process = int(process)
        self.interval_s = float(interval_s)
        self.fsync = fsync
        self.seq = 0
        os.makedirs(run_dir, exist_ok=True)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"photon-heartbeat-p{self.process}",
            daemon=True,
        )

    def start(self) -> "HeartbeatWriter":
        self.beat()  # first record lands before any peer could check
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def beat(self) -> None:
        """One synchronous liveness write (the thread loop's body)."""
        # main writes once in start() BEFORE the thread exists; after the
        # handoff only the beat thread touches it
        self.seq = self.seq + 1  # photon: thread-confined
        write_heartbeat(
            self.run_dir, self.process, self.seq, fsync=self.fsync
        )

    def _run(self) -> None:
        from .. import obs

        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception:
                # transient: the record simply ages one more interval
                obs.swallowed_error("dist.heartbeat")


# -- bounded-time collectives -------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CollectiveGuard:
    """The armed collective-timeout configuration (process-wide)."""

    timeout_s: float
    run_dir: Optional[str] = None  # heartbeat dir, for timeout diagnosis
    stale_after_s: float = 10.0


_guard: Optional[CollectiveGuard] = None
_barrier_lock = threading.Lock()
_barrier_seq: Dict[str, int] = {}


def configure_collectives(
    timeout_s: float,
    run_dir: Optional[str] = None,
    stale_after_s: float = 10.0,
) -> None:
    """Arm the process-wide collective budget (``cli train`` does this for
    distributed runs; ``timeout_s <= 0`` disarms). Every process must arm
    the same budget — the barrier ids are call-ordered, so configuration
    itself needs no collective."""
    global _guard
    if timeout_s and timeout_s > 0:
        _guard = CollectiveGuard(
            timeout_s=float(timeout_s),
            run_dir=run_dir,
            stale_after_s=float(stale_after_s),
        )
    else:
        _guard = None


def clear_collectives() -> None:
    """Disarm (and reset barrier sequencing — test isolation)."""
    global _guard
    _guard = None
    with _barrier_lock:
        _barrier_seq.clear()


def collective_timeout() -> Optional[float]:
    g = _guard
    return g.timeout_s if g is not None else None


def _process_count() -> int:
    """Process count without requiring an initialized backend (unit tests
    with no distributed runtime see 1)."""
    try:
        import jax

        return jax.process_count()
    except Exception:  # photon: ignore[R4] - no-jax fallback, single process
        return 1


def _coordination_client():
    """The jax distributed-runtime client, or None when the coordination
    service is not up (single-process, or pre-initialize)."""
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None)
    except Exception:  # photon: ignore[R4] - no-jax fallback, no client
        return None


def _next_barrier_id(name: str) -> str:
    # barrier ids must be unique per use and identical across processes:
    # calls are SPMD-ordered, so a per-name counter agrees everywhere
    with _barrier_lock:
        n = _barrier_seq.get(name, 0) + 1
        _barrier_seq[name] = n
    return f"photon:{name}:{n}"


def barrier_with_timeout(
    name: str,
    timeout_s: Optional[float] = None,
    fault_site: Optional[str] = "dist.collective",
) -> None:
    """Rendezvous all processes within ``timeout_s`` (defaults to the armed
    budget) or raise :class:`DistributedTimeoutError`.

    The fault site fires before the rendezvous — ``dist.collective:delay``
    holds THIS process out of the barrier (its peers time out if the delay
    exceeds their budget), ``dist.collective:kill`` dies at the boundary.
    Single-process: the site still fires, the rendezvous is a no-op."""
    if fault_site:
        faults.check(fault_site)
    g = _guard
    budget = timeout_s if timeout_s is not None else (
        g.timeout_s if g is not None else None
    )
    if _process_count() == 1:
        return
    if budget is None:
        return  # unarmed: collectives keep their historical blocking shape
    client = _coordination_client()
    if client is None or not hasattr(client, "wait_at_barrier"):
        logger.warning(
            "collective budget armed but no coordination client; barrier "
            "%s degraded to a no-op", name,
        )
        return
    barrier_id = _next_barrier_id(name)
    t0 = time.perf_counter()
    try:
        client.wait_at_barrier(barrier_id, int(budget * 1000))
    except Exception as e:
        # DEADLINE_EXCEEDED is the barrier running out its budget; the other
        # markers are the coordination service noticing the dead peer first
        # (missed service heartbeats / closed connection) and aborting the
        # barrier early. Both mean the same thing to the caller: a peer is
        # gone and the collective will never complete. Anything else (a
        # mis-addressed coordinator, an auth failure) re-raises untranslated.
        text = str(e).upper()
        liveness = (
            "DEADLINE", "TIMED OUT", "TIMEOUT", "UNAVAILABLE", "DISCONNECT",
            "ABORTED", "SHUT DOWN", "SHUTTING DOWN", "HEARTBEAT",
            "BARRIER FAILED",
        )
        if not any(marker in text for marker in liveness):
            raise
        waited = time.perf_counter() - t0
        detail = ""
        if g is not None and g.run_dir:
            try:
                stale = stale_peers(
                    g.run_dir, _process_count(), g.stale_after_s
                )
                if stale:
                    detail = f"; heartbeat-stale peers: {stale}"
            except Exception:
                from .. import obs

                obs.swallowed_error("dist.timeout_diagnosis")
        _registry().counter(
            "photon_dist_collective_timeouts_total",
            "guarded collectives that hit the budget instead of hanging",
        ).labels(barrier=name).inc()
        raise DistributedTimeoutError(
            f"collective barrier {barrier_id!r} timed out after "
            f"{waited:.1f}s (budget {budget:.1f}s): a peer process never "
            f"arrived{detail}"
        ) from e


def guard_collective(name: str) -> None:
    """Pre-collective rendezvous: called by the object collectives in
    ``parallel/multihost.py``. If every process reaches this barrier within
    the budget, the collective that follows has all its participants; a dead
    peer surfaces here as a typed timeout instead of an unbounded hang.
    No-op unless a budget is armed (and never fires the fault site — the
    drill schedules kills at sweep boundaries, where the count is exactly
    the sweep index)."""
    if _guard is None:
        return
    barrier_with_timeout(f"pre:{name}", fault_site=None)


def sweep_barrier(iteration: int) -> None:
    """The CD sweep-boundary liveness rendezvous (``game/descent.py``).
    Fires the ``dist.collective`` fault site exactly once per sweep — the
    kill-a-worker drill's deterministic schedule — then rendezvouses under
    the armed budget. No-op (beyond the site) when unarmed or
    single-process."""
    if _guard is None and _process_count() == 1:
        faults.check("dist.collective")
        return
    barrier_with_timeout(f"cd.sweep.{int(iteration)}")
