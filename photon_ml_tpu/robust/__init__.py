"""Fault tolerance for photon-ml-tpu training runs.

The Spark reference inherits crash safety from its platform: RDD lineage
recomputes lost partitions, the scheduler retries failed tasks, and HDFS
output committers rename finished work into place. The JAX port runs as one
process writing ordinary files, so this package rebuilds those three
guarantees in library form:

- :mod:`robust.atomic` — write-temp + fsync + atomic-rename file creation
  (the output-committer property: readers never see a torn file);
- :mod:`robust.retry` — seeded, bounded exponential-backoff retry around IO
  sites (the task-retry property), observable via
  ``photon_retry_attempts_total{site=}``;
- :mod:`robust.checkpoint` — coordinate-update-boundary snapshots of the
  coordinate-descent outer loop with digest-bearing manifests and
  keep-last-K rotation (the lineage property: kill the process anywhere and
  ``--resume`` replays the remaining updates);
- :mod:`robust.faults` — a deterministic, seeded fault injector (default
  off, env-activated) that makes the first three testable: injected IO
  errors exercise the retry budget, simulated kills exercise resume;
- :mod:`robust.distributed` — multi-process liveness (the scheduler
  property): per-process heartbeat records, stale-peer detection
  (:class:`PeerLostError`), and bounded-time collective barriers that turn
  a dead peer into a typed :class:`DistributedTimeoutError` within a
  configured budget instead of an infinite hang; checkpoints become
  cross-process consistent via the two-phase protocol in
  :mod:`robust.checkpoint`.

``cli.train --checkpoint-dir D --checkpoint-every N`` / ``--resume`` wire
this end to end; ``--collective-timeout`` / ``--heartbeat-interval`` arm
the distributed liveness plane.
"""

from .atomic import (
    atomic_write,
    atomic_write_bytes,
    atomic_write_json,
    atomic_write_text,
)
from .checkpoint import (
    CheckpointError,
    CheckpointIncompatibleError,
    CheckpointManager,
    CheckpointSnapshot,
)
from .distributed import (
    DistributedError,
    DistributedTimeoutError,
    HeartbeatWriter,
    PeerLostError,
    barrier_with_timeout,
    check_peers,
    clear_collectives,
    configure_collectives,
    heartbeat_ages,
    read_heartbeats,
    write_heartbeat,
)
from .faults import (
    FaultInjector,
    FaultSpec,
    InjectedIOError,
    SimulatedKill,
    parse_faults,
)
from .retry import DEFAULT_IO_POLICY, RetryPolicy, io_call

__all__ = [
    "CheckpointError",
    "CheckpointIncompatibleError",
    "CheckpointManager",
    "CheckpointSnapshot",
    "DEFAULT_IO_POLICY",
    "DistributedError",
    "DistributedTimeoutError",
    "FaultInjector",
    "FaultSpec",
    "HeartbeatWriter",
    "InjectedIOError",
    "PeerLostError",
    "RetryPolicy",
    "SimulatedKill",
    "atomic_write",
    "atomic_write_bytes",
    "atomic_write_json",
    "atomic_write_text",
    "barrier_with_timeout",
    "check_peers",
    "clear_collectives",
    "configure_collectives",
    "heartbeat_ages",
    "io_call",
    "parse_faults",
    "read_heartbeats",
    "write_heartbeat",
]
