"""Legacy single-GLM training driver.

Reference: photon-client .../Driver.scala:92-561 (§3.3) — the staged non-GAME
pipeline: INIT -> PREPROCESSED (read + validate + feature summary) ->
TRAINED (lambda grid with warm start) -> VALIDATED (metrics per lambda, best
model selection), with box-constrained optimization (GLMSuite constraint map)
and text + Avro model output (IOUtils.writeModelsInText).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from ..estimators.model_training import select_best_model, train_glm_grid
from ..evaluation.suite import build_suite
from ..game.problem import GLMOptimizationConfig
from ..io import read_avro_dataset, read_libsvm, save_glm
from ..io.data import FeatureShardConfig
from ..io.validators import VALIDATE_FULL, validate_dataset
from ..ops.normalization import build_normalization
from ..ops.regularization import RegularizationContext
from ..optimize import OptimizerConfig, OptimizerType
from ..utils.logging import setup_logging
from ..utils.stats import compute_feature_statistics
from .params import (
    add_common_io_args,
    build_shard_configs,
    parse_input_columns,
    resolve_input_paths,
)

logger = logging.getLogger("photon_ml_tpu")

STAGES = ["INIT", "PREPROCESSED", "TRAINED", "VALIDATED"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu legacy GLM training driver")
    add_common_io_args(p)
    p.add_argument("--validation-data", default=None)
    p.add_argument("--input-format", default="AVRO", choices=["AVRO", "LIBSVM"])
    p.add_argument("--task", default="logistic_regression")
    p.add_argument("--optimizer", default="LBFGS", choices=[t.value for t in OptimizerType])
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--max-iterations", type=int, default=100)
    p.add_argument("--regularization-type", default="NONE")
    p.add_argument("--elastic-net-alpha", type=float, default=1.0)
    p.add_argument("--regularization-weights", default="0", help="pipe-separated grid")
    p.add_argument(
        "--normalization",
        default="NONE",
        choices=["NONE", "STANDARDIZATION", "SCALE_WITH_STANDARD_DEVIATION", "SCALE_WITH_MAX_MAGNITUDE"],
    )
    p.add_argument("--evaluators", default="")
    p.add_argument(
        "--constraint-map",
        default=None,
        help='JSON map feature-key -> [lower, upper] box constraints',
    )
    p.add_argument(
        "--validate-data", default=VALIDATE_FULL,
        choices=[
            "VALIDATE_FULL", "VALIDATE_SAMPLE", "VALIDATE_QUARANTINE", "DISABLED",
        ],
    )
    p.add_argument(
        "--seed", type=int, default=0,
        help="run seed for seeded subsampling (VALIDATE_SAMPLE row draws)",
    )
    p.add_argument("--variance-type", default="NONE", choices=["NONE", "SIMPLE", "FULL"])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--log-level", default="INFO")
    return p


def run(argv: Optional[List[str]] = None):
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level)
    stage = "INIT"

    # ---- PREPROCESS ----------------------------------------------------------
    if args.input_format == "LIBSVM":
        raw = read_libsvm(args.input_data)
        index_maps = None
        shard = "global"
        validation = read_libsvm(args.validation_data, dim=raw.shard_dims["global"] - 1) if args.validation_data else None
    else:
        shards = build_shard_configs(args)
        shard = next(iter(shards))
        raw, index_maps = read_avro_dataset(
            resolve_input_paths(args), shards,
            response_column=args.response_column,
            columns=parse_input_columns(args),
        )
        validation = None
        if args.validation_data:
            validation, _ = read_avro_dataset(
                args.validation_data, shards, index_maps=index_maps,
                response_column=args.response_column,
                columns=parse_input_columns(args),
            )
    validate_dataset(raw, args.task, args.validate_data, rng_seed=args.seed)
    stats = compute_feature_statistics(raw, shard)
    stage = "PREPROCESSED"
    logger.info("stage %s: %d rows, %d features", stage, raw.n_rows, raw.shard_dims[shard])

    # ---- TRAIN ---------------------------------------------------------------
    batch = raw.to_batch(shard)
    norm = None
    if args.normalization != "NONE":
        intercept = None
        if index_maps is not None:
            intercept = index_maps[shard].intercept_index
        elif args.input_format == "LIBSVM":
            intercept = raw.shard_dims[shard] - 1  # read_libsvm appends intercept last
        norm = build_normalization(
            args.normalization, stats["mean"], stats["variance"],
            stats["max_magnitude"], intercept_index=intercept,
            dtype=batch.labels.dtype,
        )

    box = None
    if args.constraint_map and index_maps is not None:
        with open(args.constraint_map) as f:
            cmap = json.load(f)
        d = raw.shard_dims[shard]
        lower = np.full(d, -np.inf)
        upper = np.full(d, np.inf)
        imap = index_maps[shard]
        for key, (lo, hi) in cmap.items():
            idx = imap.get_index(key)
            if idx >= 0:
                lower[idx], upper[idx] = lo, hi
        box = (jnp.asarray(lower, batch.labels.dtype), jnp.asarray(upper, batch.labels.dtype))

    cfg = GLMOptimizationConfig(
        optimizer=OptimizerConfig(
            optimizer_type=OptimizerType(args.optimizer),
            tolerance=args.tolerance,
            max_iterations=args.max_iterations,
            box_constraints=box,
        ),
        regularization=RegularizationContext(
            args.regularization_type, args.elastic_net_alpha
        ),
        variance_type=args.variance_type,
    )
    weights = [float(w) for w in args.regularization_weights.split("|")]
    trained = train_glm_grid(batch, args.task, cfg, weights, normalization=norm)
    stage = "TRAINED"
    logger.info("stage %s: %d models", stage, len(trained))

    # ---- VALIDATE ------------------------------------------------------------
    best = trained[-1]
    if validation is not None:
        specs = [e for e in args.evaluators.split(",") if e] or _default_evaluators(args.task)
        suite = build_suite(specs, validation.labels, validation.weights)
        vbatch = validation.to_batch(shard)
        best, _ = select_best_model(trained, vbatch, suite)
        stage = "VALIDATED"
        logger.info("stage %s: best lambda=%s metrics=%s", stage, best.reg_weight, best.validation_metrics)

    # ---- OUTPUT --------------------------------------------------------------
    os.makedirs(args.output_dir, exist_ok=True)
    summary = {
        "stage": stage,
        "models": [
            {
                "reg_weight": t.reg_weight,
                "iterations": int(np.asarray(t.solver_result.iterations)),
                "convergence_reason": int(np.asarray(t.solver_result.reason)),
                "loss": float(np.asarray(t.solver_result.loss)),
                "metrics": t.validation_metrics,
            }
            for t in trained
        ],
        "best_reg_weight": best.reg_weight,
    }
    with open(os.path.join(args.output_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=float)
    for t in trained:
        sub = os.path.join(args.output_dir, f"lambda-{t.reg_weight}")
        os.makedirs(sub, exist_ok=True)
        # text model output (IOUtils.writeModelsInText format: key\tvalue)
        means = np.asarray(t.model.coefficients.means)
        with open(os.path.join(sub, "model.txt"), "w") as f:
            for i, v in enumerate(means):
                key = index_maps[shard].get_feature_name(i) if index_maps else str(i)
                f.write(f"{key}\t{v}\n")
        if index_maps is not None:
            save_glm(os.path.join(sub, "model.avro"), t.model, index_maps[shard])
    logger.info("wrote %d models to %s", len(trained), args.output_dir)
    return summary


def _default_evaluators(task: str) -> List[str]:
    t = task.lower()
    if t in ("logistic_regression", "smoothed_hinge_loss_linear_svm"):
        return ["AUC"]
    if t == "poisson_regression":
        return ["POISSON_LOSS"]
    return ["RMSE"]


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
