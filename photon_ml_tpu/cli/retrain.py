"""Continuous-training driver: the day-chained incremental retrain loop.

Walks a time-partitioned feed (``<input-data>/yyyy/MM/dd`` day directories,
DateRange.scala semantics) one day at a time, warm-starting each day from
the last ACCEPTED model with prior-centered L2, gating every candidate
behind the no-degrade promotion check, and publishing accepted models into
a serving root that a running ``cli serve`` flips in mid-traffic
(``game/incremental.py`` holds the chain; this driver only feeds it).

Usage:
  python -m photon_ml_tpu.cli.retrain \\
    --input-data feed/ --input-data-date-range 20260101-20260107 \\
    --validation-data val.avro --feature-index-dir index/ \\
    --task logistic_regression \\
    --feature-shard name=globalShard,bags=features \\
    --coordinate name=global,shard=globalShard,reg.type=L2,reg.weights=1 \\
    --evaluators AUC,AUC:userId \\
    --output-dir chain/ --serving-root serving/

The chain is durable: rerunning the same command resumes — decided days are
skipped via the ledger in ``<output-dir>/chain-state.json``, a day killed
mid-CD resumes from its newest boundary checkpoint (``--checkpoint-every``),
and a torn publish is repaired before any new work. ``PHOTON_FAULTS``
drills: ``retrain.day:kill:N`` (crash between days), ``retrain.publish:io:N``
(torn publish), plus every site the per-day training already carries.

The feature index is PINNED for the whole chain (``--feature-index-dir`` is
required): per-day index growth would silently re-map day k's priors under
day k+1 — the exact mis-alignment ``check_prior_compatibility`` refuses on
the warm-start path.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Dict, List, Optional

from .. import obs
from ..estimators.game_estimator import GameEstimator
from ..game import incremental
from ..io import read_avro_dataset
from ..robust import atomic_write_json, faults
from ..utils.logging import setup_logging
from .params import (
    add_common_io_args,
    build_shard_configs,
    check_retrain_composition,
    parse_coordinate,
    parse_input_columns,
)

logger = logging.getLogger("photon_ml_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu continuous-training driver")
    add_common_io_args(p)
    p.add_argument(
        "--validation-data",
        required=True,
        help="held-out validation Avro; the no-degrade gate scores candidate "
        "AND live on this same set",
    )
    p.add_argument("--task", default="logistic_regression")
    p.add_argument(
        "--coordinate",
        action="append",
        default=[],
        help="coordinate configuration spec (repeatable, ordered)",
    )
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument(
        "--evaluators",
        default="",
        help="comma-separated evaluator specs the promotion gate checks "
        "(e.g. AUC,AUC:userId: per-group specs gate per-cohort quality)",
    )
    p.add_argument(
        "--gate-margin",
        type=float,
        default=0.0,
        help="tolerated per-metric degradation before the gate refuses "
        "(in each metric's own direction; 0 = strict no-degrade)",
    )
    p.add_argument(
        "--validate-data",
        default="disabled",
        choices=["full", "sample", "quarantine", "disabled"],
        help="per-day input validation; 'quarantine' zero-weights offending "
        "rows so a poisoned day costs its update, not the chain",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--output-dir",
        required=True,
        help="chain directory: chain-state.json ledger, models/day-*, "
        "checkpoints/",
    )
    p.add_argument(
        "--serving-root",
        default=None,
        help="publish accepted models here (serving.refresh layout); a "
        "running `cli serve --serving-root` on the same path flips them "
        "in mid-traffic",
    )
    p.add_argument(
        "--snapshot-prefix",
        default="retrain",
        help="published snapshots are named <prefix>-<yyyyMMdd>",
    )
    p.add_argument(
        "--checkpoint-every",
        type=int,
        default=0,
        help="snapshot each day's CD outer-loop state every N coordinate-"
        "update boundaries under <output-dir>/checkpoints/day-*; a day "
        "killed mid-CD resumes from the newest valid one. 0 disables",
    )
    p.add_argument("--checkpoint-keep", type=int, default=3)
    p.add_argument(
        "--distributed",
        default=None,
        help="UNSUPPORTED with retrain — refused up front (the day chain is "
        "a host-local control loop); present so the refusal is typed "
        "rather than an unknown-flag error",
    )
    p.add_argument(
        "--trial-lanes",
        type=int,
        default=1,
        help="UNSUPPORTED with retrain — refused up front (warm-start "
        "regularize-by-prior has no per-lane prior operand)",
    )
    p.add_argument("--log-file", default=None)
    p.add_argument("--log-level", default="INFO")
    p.add_argument(
        "--metrics-out",
        default=None,
        help="directory for run telemetry (metrics.jsonl + metrics.prom); "
        "the retrain counters (photon_retrain_days_total{outcome}, "
        "photon_retrain_rejected_total{reason}, "
        "photon_retrain_published_total) land here",
    )
    p.add_argument(
        "--status-port",
        type=int,
        default=None,
        help="serve live /metrics, /healthz and /statusz (with a `retrain` "
        "block: day index, outcomes, rejection reasons) while the chain "
        "runs (0 = ephemeral port)",
    )
    return p


def _day_range(args):
    from ..utils.dates import DateRange, DaysRange

    if args.input_data_date_range and args.input_data_days_ago:
        raise SystemExit(
            "--input-data-date-range and --input-data-days-ago are exclusive"
        )
    if args.input_data_date_range:
        return DateRange.from_string(args.input_data_date_range)
    if args.input_data_days_ago:
        return DaysRange.from_string(args.input_data_days_ago).to_date_range()
    raise SystemExit(
        "retrain walks a day-partitioned feed: pass --input-data-date-range "
        "yyyyMMdd-yyyyMMdd (or --input-data-days-ago) over "
        "<input-data>/yyyy/MM/dd day directories"
    )


def run(argv: Optional[List[str]] = None) -> Dict:
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, args.log_file)
    faults.install_from_env()

    from ..utils.compile_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    coord_specs = args.coordinate or [
        "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1"
    ]
    coords = [parse_coordinate(s) for s in coord_specs]
    # refuse the illegal compositions before any expensive setup
    check_retrain_composition(
        bool(args.distributed),
        args.trial_lanes,
        [cc.name for cc in coords if cc.hbm_budget_mb],
    )

    if not args.feature_index_dir:
        # the chain's one index discipline: day k+1's prior must live in the
        # same feature space day k's model was saved in
        raise SystemExit(
            "retrain requires --feature-index-dir: the feature index is "
            "pinned for the whole chain (a per-day index would re-map day "
            "k's priors under day k+1)"
        )

    rng = _day_range(args)

    shards = build_shard_configs(args)
    id_tags = [t for t in args.id_tags.split(",") if t]
    for cc in coords:
        if cc.is_random_effect and cc.random_effect_type not in id_tags:
            id_tags.append(cc.random_effect_type)
    input_columns = parse_input_columns(args)

    from ..io.index_map import load_partitioned

    index_maps = {s: load_partitioned(args.feature_index_dir, s) for s in shards}

    from ..utils.dates import DateRange, input_paths_within_date_range

    def _read_day(day):
        paths = input_paths_within_date_range(
            args.input_data, DateRange(day, day)
        )
        raw, _ = read_avro_dataset(
            paths,
            shards,
            index_maps=index_maps,
            id_tag_columns=id_tags,
            response_column=args.response_column,
            columns=input_columns,
        )
        if args.validate_data != "disabled":
            from ..io import validators

            mode = {
                "full": validators.VALIDATE_FULL,
                "sample": validators.VALIDATE_SAMPLE,
                "quarantine": validators.VALIDATE_QUARANTINE,
            }[args.validate_data]
            validators.validate_dataset(raw, args.task, mode, rng_seed=args.seed)
        return raw

    # (label, thunk) pairs: resume skips decided days WITHOUT reading them
    days = []
    for day in rng.days():
        label = day.strftime("%Y%m%d")
        try:
            input_paths_within_date_range(args.input_data, DateRange(day, day))
        except FileNotFoundError:
            logger.info("day %s: no data directory, skipping", label)
            continue
        days.append((label, lambda d=day: _read_day(d)))
    if not days:
        raise SystemExit(
            f"no day directories under {args.input_data} within {rng}"
        )

    validation, _ = read_avro_dataset(
        args.validation_data,
        shards,
        index_maps=index_maps,
        id_tag_columns=id_tags,
        response_column=args.response_column,
        columns=input_columns,
    )

    evaluators = [e for e in args.evaluators.split(",") if e]
    estimator = GameEstimator(
        task=args.task,
        coordinate_configs=coords,
        n_cd_iterations=args.coordinate_descent_iterations,
        evaluator_specs=evaluators,
    )

    run_t = None
    prev_run = None
    sinks = []
    status_server = None
    if args.metrics_out or args.status_port is not None:
        run_t = obs.RunTelemetry()
        if args.metrics_out:
            os.makedirs(args.metrics_out, exist_ok=True)
            sinks = [
                obs.JsonlSink(os.path.join(args.metrics_out, "metrics.jsonl")),
                obs.PrometheusSink(os.path.join(args.metrics_out, "metrics.prom")),
            ]
            for sink in sinks:
                run_t.register_listener(sink)
        prev_run = obs.set_current_run(run_t)
        if args.status_port is not None:
            status_server = obs.IntrospectionServer(run_t, port=args.status_port)
            logger.info(
                "introspection endpoints -> http://127.0.0.1:%d/{metrics,"
                "healthz,statusz}", status_server.port,
            )
    try:
        result = incremental.run_chain(
            estimator,
            days,
            validation,
            chain_dir=args.output_dir,
            serving_root=args.serving_root,
            snapshot_prefix=args.snapshot_prefix,
            evaluator_specs=evaluators or None,
            gate_margin=args.gate_margin,
            checkpoint_every=args.checkpoint_every,
            checkpoint_keep=args.checkpoint_keep,
            index_maps=index_maps,
        )
    finally:
        if status_server is not None:
            status_server.stop()
        if run_t is not None:
            run_t.close()
            obs.set_current_run(prev_run)

    summary = {
        "days": [
            {
                "day": r.day,
                "accepted": r.accepted,
                "reason": r.reason,
                "rows": r.rows,
                "published": r.published,
                "snapshot": r.snapshot,
                "metrics": r.metrics,
            }
            for r in result.ledger
        ],
        "accepted_days": sum(1 for r in result.ledger if r.accepted),
        "rejected_days": sum(1 for r in result.ledger if not r.accepted),
        "rows_touched": result.rows_touched,
        "rows_cumulative": result.rows_cumulative,
        "rows_touched_fraction": result.rows_touched_fraction,
    }
    os.makedirs(args.output_dir, exist_ok=True)
    atomic_write_json(
        os.path.join(args.output_dir, "retrain-summary.json"),
        summary, indent=2, default=float,
    )
    logger.info(
        "chain done: %d accepted / %d rejected day(s); touched %.0f%% of "
        "the rows a daily from-scratch retrain would have",
        summary["accepted_days"], summary["rejected_days"],
        100.0 * summary["rows_touched_fraction"],
    )
    return summary


def main() -> None:
    run()


if __name__ == "__main__":
    main()
