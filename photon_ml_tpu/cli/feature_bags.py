"""Name-and-term feature bags driver.

Reference: photon-client .../NameAndTermFeatureBagsDriver.scala:148-219:
extract the distinct (name, term) pairs per feature bag from the data and
write them as text files (one "name<TAB>term" per line, the NameAndTerm
STRING_DELIMITER format) for later feature-map construction.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

from ..io.avro import iter_avro_directory
from ..utils.logging import setup_logging
from .params import add_common_io_args, resolve_input_paths

logger = logging.getLogger("photon_ml_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu name-and-term feature bags driver")
    add_common_io_args(p)
    p.add_argument("--feature-bags", required=True, help="comma-separated bag columns")
    p.add_argument("--output-dir", required=True)
    p.add_argument("--log-level", default="INFO")
    return p


def _input_paths(args):
    paths = resolve_input_paths(args)
    return [paths] if isinstance(paths, str) else paths


def run(argv: Optional[List[str]] = None):
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level)
    bags = [b for b in args.feature_bags.split(",") if b]
    seen: Dict[str, Set[Tuple[str, str]]] = {b: set() for b in bags}
    for rec in (r for path in _input_paths(args) for r in iter_avro_directory(path)):
        for bag in bags:
            for f in rec.get(bag) or ():
                term = f.get("term")
                seen[bag].add((f["name"], "" if term is None else str(term)))
    os.makedirs(args.output_dir, exist_ok=True)
    for bag, pairs in seen.items():
        path = os.path.join(args.output_dir, bag)
        with open(path, "w") as out:
            for name, term in sorted(pairs):
                out.write(f"{name}\t{term}\n")
        logger.info("bag %s: %d distinct features -> %s", bag, len(pairs), path)
    return seen


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
