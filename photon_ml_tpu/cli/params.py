"""CLI parameter grammar shared by the drivers.

Reference: photon-client io/scopt/** — the scopt parsers map typed CLI args to
driver params, with a rich comma/pipe grammar for nested configs, e.g.
``--coordinate-configurations name=global,feature.shard=...,optimizer=LBFGS,
reg.weights=0.1|1|10`` (README.md:297, ScoptParserHelpers.scala). This module
re-creates that grammar on argparse.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Sequence

from ..game.problem import GLMOptimizationConfig
from ..io.data import FeatureShardConfig
from ..ops.regularization import RegularizationContext
from ..optimize import OptimizerConfig, OptimizerType
from ..estimators.game_estimator import CoordinateConfig


def parse_kv(spec: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        if not eq:
            raise ValueError(f"expected key=value in {spec!r}, got {part!r}")
        out[k.strip()] = v.strip()
    return out


def parse_feature_shard(spec: str) -> Dict[str, FeatureShardConfig]:
    """``name=globalShard,bags=features|userFeatures,intercept=true``"""
    kv = parse_kv(spec)
    name = kv.pop("name")
    bags = tuple(kv.pop("bags").split("|"))
    intercept = kv.pop("intercept", "true").lower() in ("true", "1", "yes")
    if kv:
        raise ValueError(f"unknown feature-shard keys: {sorted(kv)}")
    return {name: FeatureShardConfig(feature_bags=bags, has_intercept=intercept)}


def parse_coordinate(spec: str) -> CoordinateConfig:
    """``name=global,shard=globalShard[,re.type=userId],optimizer=LBFGS,
    tolerance=1e-7,max.iter=100,reg.type=L2,reg.alpha=0.5,reg.weights=0.1|1|10,
    down.sampling.rate=1.0,active.cap=256,active.lower.bound=1,variance=NONE,
    features.to.samples.ratio=0.5,layout=auto,feature.dtype=bfloat16,
    hbm.budget.mb=4096``

    ``feature.dtype=bfloat16``: narrow feature storage (dense/ell/coo fixed
    effects and RE entity blocks; solver state stays wide).
    ``hbm.budget.mb``: out-of-core training under an HBM cap. Random
    effects: entity blocks above the budget stay host-resident and stream
    through the chip in double-buffered slices (game/streaming.py). Fixed
    effects: the batch is partitioned into row slices that stream through
    the chip double-buffered while the solver runs on the host
    (game/fe_streaming.py; layouts auto|dense|ell, variance NONE only, no
    down-sampling). Composes with a device mesh / multi-process: each host
    streams its own shard under the per-host budget — the execution planner
    (plan/planner.py) resolves the full routing and owns every refusal."""
    kv = parse_kv(spec)
    name = kv.pop("name")
    shard = kv.pop("shard")
    re_type = kv.pop("re.type", None)
    opt = OptimizerConfig(
        optimizer_type=OptimizerType(kv.pop("optimizer", "LBFGS").upper()),
        tolerance=float(kv.pop("tolerance", 1e-7)),
        max_iterations=int(kv.pop("max.iter", 100)),
        num_corrections=int(kv.pop("num.corrections", 10)),
    )
    reg = RegularizationContext(
        reg_type=kv.pop("reg.type", "NONE"),
        elastic_net_alpha=float(kv.pop("reg.alpha", 1.0)),
    )
    weights = tuple(float(w) for w in kv.pop("reg.weights", "0").split("|"))
    cfg = GLMOptimizationConfig(
        optimizer=opt,
        regularization=reg,
        reg_weight=weights[0],
        down_sampling_rate=float(kv.pop("down.sampling.rate", 1.0)),
        variance_type=kv.pop("variance", "NONE").upper(),
    )
    layout = kv.pop("layout", "auto").lower()
    if layout not in ("auto", "dense", "ell", "sparse", "coo", "tiled"):
        raise ValueError(f"unknown layout {layout!r} in coordinate {name!r}")
    fdt_name = kv.pop("feature.dtype", "").lower()
    if fdt_name not in ("", "float32", "bfloat16"):
        raise ValueError(
            f"unknown feature.dtype {fdt_name!r} in coordinate {name!r} "
            "(expected float32|bfloat16)"
        )
    feature_dtype = None
    if fdt_name == "bfloat16":
        import jax.numpy as jnp

        feature_dtype = jnp.bfloat16
    cc = CoordinateConfig(
        name=name,
        feature_shard=shard,
        config=cfg,
        random_effect_type=re_type,
        reg_weights=weights,
        active_cap=int(kv["active.cap"]) if "active.cap" in kv else None,
        active_lower_bound=int(kv.pop("active.lower.bound", 1)),
        features_to_samples_ratio=(
            float(kv.pop("features.to.samples.ratio"))
            if "features.to.samples.ratio" in kv
            else None
        ),
        layout=layout,
        feature_dtype=feature_dtype,
        hbm_budget_mb=(
            int(kv.pop("hbm.budget.mb")) if "hbm.budget.mb" in kv else None
        ),
    )
    kv.pop("active.cap", None)
    if kv:
        raise ValueError(f"unknown coordinate keys: {sorted(kv)}")
    return cc


def add_common_io_args(p: argparse.ArgumentParser):
    p.add_argument("--input-data", required=True, help="Avro file or directory")
    p.add_argument(
        "--feature-shard",
        action="append",
        default=[],
        required=False,
        help="name=SHARD,bags=BAG|BAG,intercept=true (repeatable)",
    )
    p.add_argument(
        "--id-tags",
        default="",
        help="comma-separated id columns to extract (random-effect types)",
    )
    p.add_argument("--response-column", default="label")
    p.add_argument(
        "--input-column-names",
        default="",
        help="remap reserved columns: 'response=label,weight=importance,...' "
        "(uid/response/offset/weight/metadataMap; InputColumnsNames.scala)",
    )
    p.add_argument(
        "--input-data-date-range",
        default=None,
        help="yyyyMMdd-yyyyMMdd: read '<input-data>/yyyy/MM/dd' day dirs "
        "within the range (DateRange.scala)",
    )
    p.add_argument(
        "--input-data-days-ago",
        default=None,
        help="START-END days before today, START >= END (DaysRange.scala)",
    )
    p.add_argument(
        "--feature-index-dir",
        default=None,
        help="directory of prebuilt index stores (FeatureIndexingDriver output)",
    )
    p.add_argument(
        "--ingest-workers",
        type=parse_ingest_workers,
        default=None,
        help="decode-pool size for training ingest AND the background "
        "validation decode (the executor-fleet decode of AvroDataReader): "
        "'auto' (default) = cpu_count - 2, min 1; an explicit N >= 1 pins "
        "the pool. Output is bit-identical at any worker count.",
    )


def parse_ingest_workers(value):
    """--ingest-workers: 'auto'/'' -> None (host-sized later, cpu_count - 2
    min 1, by io/data.resolve_ingest_workers); otherwise an int >= 1."""
    if value is None or value == "" or str(value).lower() == "auto":
        return None
    try:
        w = int(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--ingest-workers expects an integer >= 1 or 'auto', got {value!r}"
        )
    if w < 1:
        raise argparse.ArgumentTypeError(
            f"--ingest-workers must be >= 1: {w}"
        )
    return w


def resolve_input_paths(args):
    """--input-data plus optional date/days range -> list of day dirs (or the
    base path unchanged); IOUtils.getInputPathsWithinDateRange semantics."""
    from ..utils.dates import DateRange, DaysRange, input_paths_within_date_range

    if args.input_data_date_range and args.input_data_days_ago:
        raise SystemExit(
            "--input-data-date-range and --input-data-days-ago are exclusive"
        )
    if args.input_data_date_range:
        rng = DateRange.from_string(args.input_data_date_range)
    elif args.input_data_days_ago:
        rng = DaysRange.from_string(args.input_data_days_ago).to_date_range()
    else:
        return args.input_data
    return input_paths_within_date_range(args.input_data, rng)


def parse_input_columns(args):
    """--input-column-names spec -> InputColumnsNames (default when empty)."""
    from ..io.columns import InputColumnsNames

    if not getattr(args, "input_column_names", ""):
        return InputColumnsNames()
    return InputColumnsNames.from_spec(args.input_column_names)


def parse_mesh_shape(spec: Optional[str]):
    """``data=4,model=2`` -> a device Mesh (None/'' -> no mesh: single-device).

    The driver-side entry to the parallel runtime: data axis shards sample
    rows and entity blocks, model axis shards the coefficient dim of
    ``layout=tiled`` coordinates (SURVEY.md §2.1 P1/P5/P13)."""
    if not spec:
        return None
    from ..parallel.mesh import make_mesh

    kv = parse_kv(spec)
    n_data = int(kv.pop("data", 1))
    n_model = int(kv.pop("model", 1))
    if kv:
        raise ValueError(f"unknown mesh keys: {sorted(kv)}")
    return make_mesh(n_data=n_data, n_model=n_model)


def parse_pipeline_depth(value) -> int:
    """``--pipeline-depth N`` -> validated sweep pipelining depth (>= 1)."""
    depth = int(value)
    if depth < 1:
        raise ValueError(f"pipeline depth must be >= 1: {depth}")
    return depth


def check_retrain_composition(
    distributed: bool, trial_lanes: int, streamed_coordinates=()
) -> None:
    """Refuse the illegal incremental-retrain compositions up front —
    delegates to the execution planner (plan/planner.py), which owns every
    composition-legality message in the support-matrix ledger."""
    from ..plan import check_retrain_composition as _check

    _check(distributed, trial_lanes, streamed_coordinates)


def build_shard_configs(args) -> Dict[str, FeatureShardConfig]:
    shards: Dict[str, FeatureShardConfig] = {}
    for spec in args.feature_shard:
        shards.update(parse_feature_shard(spec))
    if not shards:
        shards["global"] = FeatureShardConfig(feature_bags=("features",))
    return shards


def plan_host_row_split(input_paths):
    """Multi-process input planning shared by the train/score drivers:
    count rows per part file (block headers only) and split the global row
    space evenly across processes. Returns (row_range, part_counts), or
    (None, None) when single-process."""
    from ..parallel import multihost

    if multihost.process_count() <= 1:
        return None, None
    from ..io.avro import count_avro_rows, list_avro_parts

    paths = [input_paths] if isinstance(input_paths, str) else input_paths
    part_counts = {
        part: count_avro_rows(part)
        for p in paths
        for part in list_avro_parts(p)
    }
    row_range = multihost.host_row_range(sum(part_counts.values()))
    return row_range, part_counts
