"""CLI parameter grammar shared by the drivers.

Reference: photon-client io/scopt/** — the scopt parsers map typed CLI args to
driver params, with a rich comma/pipe grammar for nested configs, e.g.
``--coordinate-configurations name=global,feature.shard=...,optimizer=LBFGS,
reg.weights=0.1|1|10`` (README.md:297, ScoptParserHelpers.scala). This module
re-creates that grammar on argparse.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional, Sequence

from ..game.problem import GLMOptimizationConfig
from ..io.data import FeatureShardConfig
from ..ops.regularization import RegularizationContext
from ..optimize import OptimizerConfig, OptimizerType
from ..estimators.game_estimator import CoordinateConfig


def parse_kv(spec: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, eq, v = part.partition("=")
        if not eq:
            raise ValueError(f"expected key=value in {spec!r}, got {part!r}")
        out[k.strip()] = v.strip()
    return out


def parse_feature_shard(spec: str) -> Dict[str, FeatureShardConfig]:
    """``name=globalShard,bags=features|userFeatures,intercept=true``"""
    kv = parse_kv(spec)
    name = kv.pop("name")
    bags = tuple(kv.pop("bags").split("|"))
    intercept = kv.pop("intercept", "true").lower() in ("true", "1", "yes")
    if kv:
        raise ValueError(f"unknown feature-shard keys: {sorted(kv)}")
    return {name: FeatureShardConfig(feature_bags=bags, has_intercept=intercept)}


def parse_coordinate(spec: str) -> CoordinateConfig:
    """``name=global,shard=globalShard[,re.type=userId],optimizer=LBFGS,
    tolerance=1e-7,max.iter=100,reg.type=L2,reg.alpha=0.5,reg.weights=0.1|1|10,
    down.sampling.rate=1.0,active.cap=256,active.lower.bound=1,variance=NONE``"""
    kv = parse_kv(spec)
    name = kv.pop("name")
    shard = kv.pop("shard")
    re_type = kv.pop("re.type", None)
    opt = OptimizerConfig(
        optimizer_type=OptimizerType(kv.pop("optimizer", "LBFGS").upper()),
        tolerance=float(kv.pop("tolerance", 1e-7)),
        max_iterations=int(kv.pop("max.iter", 100)),
        num_corrections=int(kv.pop("num.corrections", 10)),
    )
    reg = RegularizationContext(
        reg_type=kv.pop("reg.type", "NONE"),
        elastic_net_alpha=float(kv.pop("reg.alpha", 1.0)),
    )
    weights = tuple(float(w) for w in kv.pop("reg.weights", "0").split("|"))
    cfg = GLMOptimizationConfig(
        optimizer=opt,
        regularization=reg,
        reg_weight=weights[0],
        down_sampling_rate=float(kv.pop("down.sampling.rate", 1.0)),
        variance_type=kv.pop("variance", "NONE").upper(),
    )
    cc = CoordinateConfig(
        name=name,
        feature_shard=shard,
        config=cfg,
        random_effect_type=re_type,
        reg_weights=weights,
        active_cap=int(kv["active.cap"]) if "active.cap" in kv else None,
        active_lower_bound=int(kv.pop("active.lower.bound", 1)),
    )
    kv.pop("active.cap", None)
    if kv:
        raise ValueError(f"unknown coordinate keys: {sorted(kv)}")
    return cc


def add_common_io_args(p: argparse.ArgumentParser):
    p.add_argument("--input-data", required=True, help="Avro file or directory")
    p.add_argument(
        "--feature-shard",
        action="append",
        default=[],
        required=False,
        help="name=SHARD,bags=BAG|BAG,intercept=true (repeatable)",
    )
    p.add_argument(
        "--id-tags",
        default="",
        help="comma-separated id columns to extract (random-effect types)",
    )
    p.add_argument("--response-column", default="label")
    p.add_argument(
        "--feature-index-dir",
        default=None,
        help="directory of prebuilt index stores (FeatureIndexingDriver output)",
    )


def build_shard_configs(args) -> Dict[str, FeatureShardConfig]:
    shards: Dict[str, FeatureShardConfig] = {}
    for spec in args.feature_shard:
        shards.update(parse_feature_shard(spec))
    if not shards:
        shards["global"] = FeatureShardConfig(feature_bags=("features",))
    return shards
