"""GAME training driver.

Reference: photon-client .../cli/game/training/GameTrainingDriver.scala:54-854
(§3.1 call stack): read+index data -> validate -> normalization -> expand
optimization configs -> GameEstimator.fit -> model selection (output mode
ALL/BEST/TUNED) -> optional GP hyperparameter tuning -> save models.

Usage:
  python -m photon_ml_tpu.cli.train \\
    --input-data train.avro --validation-data val.avro \\
    --task logistic_regression \\
    --feature-shard name=globalShard,bags=features \\
    --feature-shard name=userShard,bags=userFeatures \\
    --coordinate name=global,shard=globalShard,optimizer=TRON,reg.type=L2,reg.weights=1|10 \\
    --coordinate name=per-user,shard=userShard,re.type=userId,reg.type=L2,reg.weights=1 \\
    --evaluators AUC,LOGISTIC_LOSS --output-dir out/
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
import time
from typing import Dict, List, Optional

import numpy as np

from .. import obs
from ..estimators.game_estimator import GameEstimator, GameResult, GameTransformer
from ..io import (
    read_avro_dataset,
    read_avro_dataset_chunked,
    resolve_ingest_workers,
    save_game_model,
)
from ..io.index_map import IndexMap
from ..io.model_io import load_game_model
from ..parallel import multihost
from ..robust import CheckpointManager, atomic_write, atomic_write_json, faults
from ..robust import distributed as robust_dist
from ..ops.normalization import build_normalization
from ..tuning.rescaling import HyperparameterConfig, ParamRange
from ..tuning.tuner import get_tuner
from ..utils.futures import DaemonFuture, WorkerPool
from ..utils.logging import setup_logging
from ..utils.stats import compute_feature_statistics, save_feature_statistics
from .params import (
    add_common_io_args,
    build_shard_configs,
    parse_coordinate,
    parse_input_columns,
    parse_mesh_shape,
    parse_pipeline_depth,
    plan_host_row_split,
    resolve_input_paths,
)

logger = logging.getLogger("photon_ml_tpu")

OUTPUT_MODE_ALL = "ALL"
OUTPUT_MODE_BEST = "BEST"
OUTPUT_MODE_TUNED = "TUNED"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu game training driver")
    add_common_io_args(p)
    p.add_argument("--validation-data", default=None)
    p.add_argument("--task", default="logistic_regression")
    p.add_argument(
        "--coordinate",
        action="append",
        default=[],
        required=False,
        help="coordinate configuration spec (repeatable, ordered)",
    )
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument(
        "--validation-frequency",
        default="COORDINATE",
        choices=["COORDINATE", "SWEEP"],
        help="evaluate validation metrics after every coordinate update "
        "(reference semantics) or once per sweep (1/n_coordinates of the "
        "metric cost on long sweeps)",
    )
    p.add_argument("--evaluators", default="", help="comma-separated evaluator specs")
    p.add_argument(
        "--validate-data",
        default="disabled",
        choices=["full", "sample", "quarantine", "disabled"],
        help="input data validation (DataValidators semantics): 'full' checks "
        "every row and fails on problems, 'sample' checks ~1%% of rows "
        "(seeded by --seed), 'quarantine' zero-weights offending rows and "
        "keeps training (counted in photon_rows_quarantined_total), "
        "'disabled' skips validation",
    )
    p.add_argument(
        "--seed",
        type=int,
        default=0,
        help="run seed for seeded subsampling (e.g. SAMPLE-mode data "
        "validation draws the same rows across reruns)",
    )
    p.add_argument(
        "--no-divergence-guard",
        action="store_true",
        help="disable the coordinate-descent divergence guard (rejection of "
        "updates with non-finite scores/loss); restores the strictly "
        "zero-fetch sweep",
    )
    p.add_argument(
        "--coordinate-rejection-tolerance",
        type=float,
        default=None,
        help="additionally reject a coordinate update whose training loss "
        "regresses more than this above the coordinate's last accepted "
        "loss (default: finiteness-only rejection)",
    )
    p.add_argument(
        "--pipeline-depth",
        type=parse_pipeline_depth,
        default=1,
        help="sweep pipelining depth (pipeline.depth): 1 = serial loop "
        "(default); >= 2 overlaps host staging, device solves and "
        "validation eval across coordinates with bit-identical accepted "
        "models, ledger and checkpoints (game/pipeline.py). Composes with "
        "--distributed; the execution planner (plan/planner.py) resolves "
        "the full routing",
    )
    p.add_argument(
        "--explain-plan",
        action="store_true",
        help="dry run: resolve the execution plan (per-coordinate routing: "
        "resident vs streamed, sharded vs replicated, pipelined vs serial, "
        "slice/shard geometry) from the flags alone, pretty-print it and "
        "exit 0 WITHOUT reading data or touching a device; a refused "
        "configuration prints its PlanError and exits 1",
    )
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--output-mode",
        default=OUTPUT_MODE_BEST,
        choices=[OUTPUT_MODE_ALL, OUTPUT_MODE_BEST, OUTPUT_MODE_TUNED],
    )
    p.add_argument("--model-input-dir", default=None, help="warm-start GAME model")
    p.add_argument(
        "--incremental-training",
        action="store_true",
        help="L2-regularize toward the warm-start model's means weighted by its "
        "precisions (requires --model-input-dir)",
    )
    p.add_argument(
        "--partial-retrain-locked",
        default="",
        help="comma-separated coordinate names to lock (requires --model-input-dir)",
    )
    p.add_argument(
        "--normalization",
        default="NONE",
        choices=["NONE", "STANDARDIZATION", "SCALE_WITH_STANDARD_DEVIATION", "SCALE_WITH_MAX_MAGNITUDE"],
    )
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0)
    p.add_argument("--compute-feature-stats", action="store_true")
    p.add_argument(
        "--hyper-parameter-tuning",
        default="NONE",
        choices=["NONE", "RANDOM", "BAYESIAN"],
    )
    p.add_argument("--hyper-parameter-tuning-iter", type=int, default=10)
    p.add_argument(
        "--trial-lanes",
        type=int,
        default=1,
        help="tuning trials trained concurrently as lambda lanes of one "
        "batched solve (game/lanes.py): K candidates share each "
        "coordinate's data residency and compiled kernel. 1 = the "
        "sequential trial loop; the reference's cluster-of-trials "
        "concurrency mapped onto one chip",
    )
    p.add_argument(
        "--hyper-parameter-config",
        default=None,
        help="JSON tuning config (HyperparameterSerialization.configFromJson "
        "shape: tuning_mode + variables map); overrides the default "
        "per-coordinate log-reg-weight ranges",
    )
    p.add_argument(
        "--hyper-parameter-prior",
        default=None,
        help="JSON prior observations ({'records': [...]}) used to shrink the "
        "search range around the GP-predicted best prior candidate "
        "(ShrinkSearchRange.getBounds)",
    )
    p.add_argument(
        "--hyper-parameter-shrink-radius",
        type=float,
        default=0.25,
        help="unit-cube radius of the shrunk search range around the best "
        "prior candidate",
    )
    p.add_argument(
        "--mesh-shape",
        default="",
        help="device mesh, e.g. data=4,model=2: data axis shards rows/entities, "
        "model axis shards the coefficient dim of layout=tiled coordinates",
    )
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save the model after every coordinate-descent sweep (and each "
        "finished grid config / tuning trial); rerunning the same command "
        "resumes from the last completed unit (crash recovery for long runs)",
    )
    p.add_argument(
        "--checkpoint-every",
        type=int,
        default=0,
        help="additionally snapshot the full coordinate-descent outer-loop "
        "state every N coordinate-update boundaries under "
        "<checkpoint-dir>/cd-boundaries (crash-safe: temp+fsync+rename, "
        "digest-bearing manifest); 0 disables. Requires --checkpoint-dir",
    )
    p.add_argument(
        "--checkpoint-keep",
        type=int,
        default=3,
        help="boundary checkpoints retained (keep-last-K rotation)",
    )
    p.add_argument(
        "--resume",
        action="store_true",
        help="resume from the newest VALID boundary checkpoint under "
        "<checkpoint-dir>/cd-boundaries (corrupt ones are skipped with a "
        "warning); training continues from the coordinate update after the "
        "snapshot, bit-identical to the uninterrupted run. Requires "
        "--checkpoint-dir",
    )
    p.add_argument(
        "--distributed",
        default=None,
        help="multi-host: 'coordinator=HOST:PORT,process=I,n=P' (or 'auto' "
        "for env/cluster auto-detection); each process reads its own row "
        "range and only process 0 writes outputs",
    )
    p.add_argument(
        "--collective-timeout",
        type=float,
        default=60.0,
        help="multi-process: budget in seconds for guarded collectives and "
        "the per-sweep liveness barrier; a dead peer raises a typed "
        "DistributedTimeoutError within this budget (plus a peer_lost "
        "flight-recorder dump) instead of hanging forever. 0 disables",
    )
    p.add_argument(
        "--heartbeat-interval",
        type=float,
        default=1.0,
        help="multi-process: seconds between liveness records each process "
        "writes under <checkpoint-dir|metrics-out>/heartbeats (read back as "
        "the photon_dist_heartbeat_age_seconds{process=} gauge and to name "
        "the stale peer in timeout errors). 0 disables the heartbeat plane",
    )
    p.add_argument(
        "--heartbeat-timeout",
        type=float,
        default=10.0,
        help="multi-process: a peer whose newest heartbeat is older than "
        "this many seconds is reported as presumed lost",
    )
    p.add_argument("--log-file", default=None)
    p.add_argument("--log-level", default="INFO")
    p.add_argument(
        "--metrics-out",
        default=None,
        help="directory for machine-readable run telemetry: metrics.jsonl "
        "(one line per span / per-sweep metrics flush; non-coordinator "
        "processes write metrics.p<i>.jsonl beside it — merge with cli "
        "fleetz), metrics.prom (Prometheus text exposition), flight/ "
        "(anomaly-triggered postmortems), and run_summary.json "
        "(coordinator only: total wall time, per-coordinate iteration "
        "stats, convergence-reason histogram)",
    )
    p.add_argument(
        "--trace-out",
        default=None,
        help="write a Chrome-trace / Perfetto JSON timeline of the run here "
        "(coordinator only); per-sweep phase attribution (stage/solve/"
        "score/eval/checkpoint + overlap factor) lands in run_summary.json",
    )
    p.add_argument(
        "--status-port",
        type=int,
        default=None,
        help="serve live /metrics (Prometheus text), /healthz and /statusz "
        "(JSON: current sweep/coordinate, accepted losses, rejection "
        "counters) on this port while training (0 = ephemeral port)",
    )
    p.add_argument(
        "--report-out",
        default=None,
        help="directory for the post-hoc training report (coordinator only): "
        "report.json (machine-readable model/convergence/performance "
        "diagnostics) and report.html (self-contained, stdlib-rendered). "
        "Implies --metrics-out into the same directory when that flag is "
        "absent, so the report directory is a complete artifact set that "
        "`cli report` can rebuild from",
    )
    return p


def run(argv: Optional[List[str]] = None) -> Dict:
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, args.log_file)
    if args.explain_plan:
        # dry run: resolve and print the execution plan from the flags
        # alone — no data read, no device touched, no jax import
        return _explain_plan(args)
    # PHOTON_FAULTS / PHOTON_FAULTS_SEED: deterministic fault injection at IO
    # and checkpoint boundaries (robust.faults); absent env clears any
    # injector a previous in-process run installed
    faults.install_from_env()

    from ..utils.compile_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    if args.distributed:
        if args.distributed == "auto":
            multihost.initialize()
        else:
            multihost.initialize_from_spec(args.distributed)
        import jax  # only safe to touch after jax.distributed.initialize

        if not args.mesh_shape:
            raise SystemExit(
                "--distributed requires --mesh-shape spanning all global "
                f"devices (e.g. data={jax.device_count()}); without a mesh "
                "each process would silently train on only its own row slice"
            )
        logger.info(
            "distributed: process %d/%d, %d local / %d global devices",
            multihost.process_index(), multihost.process_count(),
            jax.local_device_count(), jax.device_count(),
        )
        # stamp span/JSONL lane identity so merged multi-process telemetry
        # stays attributable (obs cannot import jax to ask for itself)
        obs.set_process_index(multihost.process_index())

    t_run0 = time.perf_counter()
    run_t = None
    prev_run = None
    metric_sinks = []
    recorder = None
    status_server = None
    if args.report_out and not args.metrics_out:
        # the report is rebuilt from on-disk artifacts; without a metrics dir
        # the trajectories would have nothing to read, so the report dir
        # doubles as the metrics dir
        args.metrics_out = args.report_out
    telemetry_on = bool(
        args.metrics_out or args.trace_out or args.status_port is not None
    )
    flight = None
    if telemetry_on:
        from ..utils.compile_cache import install_compile_metrics_hook

        coordinator = multihost.is_coordinator()
        # every process streams its own telemetry so cli fleetz can merge
        # the fleet view; the coordinator keeps the bare filenames (all
        # single-process tooling reads those), peers suffix their lane
        suffix = "" if coordinator else f".p{multihost.process_index()}"
        run_t = obs.RunTelemetry()
        obs.record_build_info(run_t.registry)
        if args.metrics_out:
            os.makedirs(args.metrics_out, exist_ok=True)
            metric_sinks = [
                obs.JsonlSink(
                    os.path.join(args.metrics_out, f"metrics{suffix}.jsonl")
                ),
                obs.PrometheusSink(
                    os.path.join(args.metrics_out, f"metrics{suffix}.prom")
                ),
            ]
            # anomaly postmortems (solver divergence, coordinate rejection,
            # crash): last window of spans/metrics, one dump per incident
            flight = obs.FlightRecorder(
                os.path.join(args.metrics_out, f"flight{suffix}"),
                run=run_t,
            )
            metric_sinks = metric_sinks + [flight]
        if args.trace_out and coordinator:
            recorder = obs.TimelineRecorder()
            metric_sinks = metric_sinks + [recorder]
        for sink in metric_sinks:
            run_t.register_listener(sink)
        prev_run = obs.set_current_run(run_t)
        install_compile_metrics_hook()
        if args.status_port is not None and coordinator:
            status_server = obs.IntrospectionServer(run_t, port=args.status_port)
            logger.info(
                "introspection endpoints -> http://127.0.0.1:%d/{metrics,"
                "healthz,statusz}", status_server.port,
            )
        if args.metrics_out and coordinator:
            logger.info("run telemetry -> %s", args.metrics_out)
    # distributed liveness plane (robust.distributed): heartbeat records in
    # a shared directory + a process-wide collective budget, so a dead peer
    # is a bounded-time typed failure instead of a silent hang
    hb_writer = None
    if args.distributed and multihost.process_count() > 1:
        hb_root = args.checkpoint_dir or args.metrics_out
        hb_dir = os.path.join(hb_root, "heartbeats") if hb_root else None
        if hb_dir and args.heartbeat_interval > 0:
            hb_writer = robust_dist.HeartbeatWriter(
                hb_dir,
                multihost.process_index(),
                interval_s=args.heartbeat_interval,
            ).start()
        robust_dist.configure_collectives(
            args.collective_timeout,
            run_dir=hb_dir,
            stale_after_s=args.heartbeat_timeout,
        )
    try:
        summary = _run_training(args, run_t, metric_sinks, t_run0, recorder)
    except BaseException as exc:
        # crash-flush: a mid-sweep abort (including an injected
        # SimulatedKill) still leaves run_summary.json on disk with the
        # partial timeline / phase attribution collected so far, marked
        # "aborted" — the report and post-mortems read it
        if flight is not None:
            # a collective timeout / stale peer is the survivor's view of a
            # PEER's death: dump it under its own trigger kind so the fleet
            # postmortem separates "I crashed" from "my peer vanished"
            kind = (
                "peer_lost"
                if isinstance(
                    exc,
                    (
                        robust_dist.DistributedTimeoutError,
                        robust_dist.PeerLostError,
                    ),
                )
                else "crash"
            )
            try:
                flight.trigger(
                    kind, detail=f"{type(exc).__name__}: {exc}"
                )
            except Exception:
                obs.swallowed_error("cli.flightrec_crash_dump")
        if run_t is not None and multihost.is_coordinator():
            try:
                _write_run_summary(args, run_t, recorder, t_run0, aborted=True)
            except Exception:
                obs.swallowed_error("cli.run_summary_flush")
                logger.exception("could not flush partial run summary")
        raise
    finally:
        if hb_writer is not None:
            hb_writer.stop()
        robust_dist.clear_collectives()
        if status_server is not None:
            status_server.stop()
        if run_t is not None:
            # final flush: last metrics.jsonl line + the final metrics.prom
            run_t.close()
            obs.set_current_run(prev_run)
    if args.report_out and multihost.is_coordinator():
        _emit_report(args)
    return summary


def _explain_plan(args) -> Dict:
    """``--explain-plan``: resolve the ExecutionPlan from the parsed flags and
    pretty-print it, reading no data and touching no device (the planner is
    jax-free, so this works on a host with no accelerator runtime). A refused
    configuration prints its PlanError and exits 1; a resolved plan prints
    and the process exits 0 (in-process callers get the plan document)."""
    from ..plan import PlanError, resolve as resolve_plan
    from .params import parse_kv

    coord_specs = args.coordinate or [
        "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1"
    ]
    try:
        coords = [parse_coordinate(s) for s in coord_specs]
        if args.incremental_training:
            for cc in coords:
                cc.regularize_by_prior = True
        mesh = None
        if args.mesh_shape:
            kv = parse_kv(args.mesh_shape)
            mesh = {"data": int(kv.pop("data", 1)),
                    "model": int(kv.pop("model", 1))}
            if kv:
                raise SystemExit(f"unknown mesh keys: {sorted(kv)}")
        n_processes = 1
        if args.distributed and args.distributed != "auto":
            for part in args.distributed.split(","):
                k, _, v = part.partition("=")
                if k.strip() == "n":
                    n_processes = int(v)
        dims = None
        if args.feature_index_dir:
            # index maps are metadata, not training data: load them so the
            # plan carries concrete slice geometry; advisory, never fatal
            try:
                from ..io.index_map import load_partitioned

                dims = {
                    s: load_partitioned(args.feature_index_dir, s).size
                    for s in build_shard_configs(args)
                }
            except Exception:  # photon: ignore[R4] - dims only enrich the
                dims = None  # printed geometry; a dry run must never fail here
        plan = resolve_plan(
            coords,
            mesh=mesh,
            n_processes=n_processes,
            pipeline_depth=args.pipeline_depth,
            trial_lanes=int(getattr(args, "trial_lanes", 1) or 1),
            distributed=bool(args.distributed),
            partial_retrain_locked=tuple(
                c for c in args.partial_retrain_locked.split(",") if c
            ),
            normalization=args.normalization,
            dims=dims,
        )
    except PlanError as e:
        print(f"plan refused: {e}", file=sys.stderr)
        raise SystemExit(1)
    print(plan.pretty())
    return {"plan": plan.to_dict()}


def _run_training(args, run_t, metric_sinks, t_run0, recorder=None) -> Dict:
    shards = build_shard_configs(args)
    id_tags = [t for t in args.id_tags.split(",") if t]
    coord_specs = args.coordinate or [
        "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1"
    ]
    coords = [parse_coordinate(s) for s in coord_specs]
    for cc in coords:
        if cc.is_random_effect and cc.random_effect_type not in id_tags:
            id_tags.append(cc.random_effect_type)

    input_paths = resolve_input_paths(args)
    input_columns = parse_input_columns(args)
    logger.info("reading training data from %s", input_paths)
    index_maps = None
    if args.feature_index_dir:
        from ..io.index_map import load_partitioned

        index_maps = {s: load_partitioned(args.feature_index_dir, s) for s in shards}

    row_range = None
    equal_share = None
    part_counts = None
    if multihost.process_count() > 1:
        if index_maps is None:
            raise SystemExit(
                "multi-process training requires --feature-index-dir "
                "(host-local index maps would disagree across hosts)"
            )
        row_range, part_counts = plan_host_row_split(input_paths)
        total_rows = sum(part_counts.values())
        # all hosts pad their slice to a common size so every process
        # contributes equal local shapes to the global arrays
        equal_share = multihost.equal_host_share(total_rows)
        logger.info(
            "process %d reads rows [%d, %d) of %d (padded to %d)",
            multihost.process_index(), row_range[0], row_range[1], total_rows,
            equal_share,
        )
    ingest_pool = None
    if multihost.process_count() == 1:
        # pipelined pooled ingest (io/data.read_avro_dataset_chunked):
        # --ingest-workers parts decode concurrently on the shared pool
        # (sequenced back to file order, bit-identical at any count) while
        # the consumer converts each part to columnar arrays and frees it —
        # decode overlaps dataset build, peak record RSS stays bounded by
        # the queue depth, and the SAME pool later runs the background
        # validation decode instead of oversubscribing cores with a second
        # thread fleet
        n_ingest_workers = resolve_ingest_workers(args.ingest_workers)
        ingest_pool = WorkerPool(n_ingest_workers, name="photon-ingest")
        try:
            raw, index_maps = read_avro_dataset_chunked(
                input_paths,
                shards,
                index_maps=index_maps,
                id_tag_columns=id_tags,
                response_column=args.response_column,
                columns=input_columns,
                workers=n_ingest_workers,
                pool=ingest_pool,
            )
        except BaseException:
            # a failed read leaves no future behind — release the workers
            # instead of leaking idle daemon threads across in-process runs
            ingest_pool.close()
            raise
    else:
        # multi-process: row-windowed read on the main thread (collective
        # ordering across hosts must stay deterministic)
        raw, index_maps = read_avro_dataset(
            input_paths,
            shards,
            index_maps=index_maps,
            id_tag_columns=id_tags,
            response_column=args.response_column,
            columns=input_columns,
            row_range=row_range,
            part_counts=part_counts,
        )
    try:
        if row_range is not None:
            raw.global_row_start = row_range[0]
        if args.validate_data != "disabled":
            # validate BEFORE multi-process padding: pad rows are synthetic
            # zero-weight rows that would dilute the sample and trip nothing
            from ..io import validators

            mode = {
                "full": validators.VALIDATE_FULL,
                "sample": validators.VALIDATE_SAMPLE,
                "quarantine": validators.VALIDATE_QUARANTINE,
            }[args.validate_data]
            validators.validate_dataset(raw, args.task, mode, rng_seed=args.seed)
        if equal_share is not None:
            raw = raw.pad_rows(equal_share)
        logger.info(
            "training rows: %d; shard dims: %s", raw.n_rows, raw.shard_dims
        )

        validation = None
        if args.validation_data:
            def _read_validation():
                v, _ = read_avro_dataset(
                    args.validation_data,
                    shards,
                    index_maps=index_maps,
                    id_tag_columns=id_tags,
                    response_column=args.response_column,
                    columns=input_columns,
                )
                return v

            if multihost.process_count() == 1:
                # ingest overlap: decode validation on the SAME worker pool
                # the training ingest used (the native Avro decoder releases
                # the GIL) while the training datasets build and upload; the
                # estimator resolves the future only when the validation
                # context is first needed (executor-parallel decode,
                # AvroDataReader.scala:165-209). Pool workers are daemon
                # threads (vs ThreadPoolExecutor): a crash elsewhere exits
                # bounded instead of blocking on concurrent.futures' atexit
                # join of a decode that nobody will consume
                validation = ingest_pool.submit(_read_validation)
            else:
                # multi-process: keep the read on the main thread (collective
                # ordering across hosts must stay deterministic)
                validation = _read_validation()
    finally:
        if ingest_pool is not None:
            # stop accepting work; the already-queued validation decode
            # still drains. Repeated in-process train_run calls then never
            # accumulate idle worker threads
            ingest_pool.close()

    # normalization from feature statistics (GameTrainingDriver:555-571)
    if args.normalization != "NONE":
        for cc in coords:
            if not cc.is_random_effect:
                stats = compute_feature_statistics(raw, cc.feature_shard)
                cc.normalization = build_normalization(
                    args.normalization,
                    stats["mean"],
                    stats["variance"],
                    stats["max_magnitude"],
                    intercept_index=index_maps[cc.feature_shard].intercept_index,
                )

    if args.compute_feature_stats:
        for shard in shards:
            # the statistics reduce is a COLLECTIVE (cross-host allgather of
            # moment sums): every process must participate; only the
            # coordinator writes
            stats = compute_feature_statistics(raw, shard)
            if multihost.is_coordinator():
                os.makedirs(args.output_dir, exist_ok=True)
                save_feature_statistics(
                    os.path.join(args.output_dir, f"feature-stats-{shard}.avro"),
                    stats,
                    index_maps[shard],
                )

    initial_model = None
    if args.model_input_dir:
        if args.incremental_training:
            # prior-compatibility check BEFORE the load: load_game_model keys
            # coefficients off (name, term) and silently drops features the
            # current index cannot host — acceptable for plain warm-start
            # initialization, fatal for priors (a dropped feature re-centers
            # its prior at zero without saying so). Indices that merely
            # permuted remap losslessly; missing features are refused.
            from ..io.model_io import check_prior_compatibility

            compat = check_prior_compatibility(args.model_input_dir, index_maps)
            logger.info(
                "incremental prior feature-index compatibility: %s",
                ", ".join(f"{s}={v}" for s, v in sorted(compat.items())),
            )
        initial_model = load_game_model(args.model_input_dir, index_maps, task=args.task)
    if args.incremental_training:
        if initial_model is None:
            raise SystemExit("--incremental-training requires --model-input-dir")
        for cc in coords:
            cc.regularize_by_prior = True

    evaluators = [e for e in args.evaluators.split(",") if e]
    mesh = parse_mesh_shape(args.mesh_shape)

    estimator = GameEstimator(
        task=args.task,
        coordinate_configs=coords,
        n_cd_iterations=args.coordinate_descent_iterations,
        evaluator_specs=evaluators,
        partial_retrain_locked=[
            c for c in args.partial_retrain_locked.split(",") if c
        ],
        mesh=mesh,
        validation_frequency=args.validation_frequency,
        divergence_guard=not args.no_divergence_guard,
        rejection_tolerance=args.coordinate_rejection_tolerance,
        pipeline_depth=args.pipeline_depth,
    )
    if int(getattr(args, "trial_lanes", 1) or 1) > 1:
        from ..game.lanes import check_lane_composition

        # pre-empt lane-composition refusals at plan time — BEFORE any
        # dataset build or grid-config training, the same check the lane
        # path re-runs at fit_lanes time (and --explain-plan dry-runs)
        check_lane_composition(
            estimator,
            int(args.trial_lanes),
            distributed=multihost.process_count() > 1,
        )
    for sink in metric_sinks:
        # estimator lifecycle events (TrainingStart/OptimizationLog/Finish)
        # land in the same JSONL stream as spans and metric flushes
        estimator.register_listener(sink)
    if run_t is not None:
        # attach the resolved execution plan so run_summary.json and the
        # live /statusz endpoint both surface the per-coordinate routing
        run_t.execution_plan = estimator.execution_plan.to_dict()
    ckpt = None
    # datasets are reg-weight-independent: build once, lazily (an idempotent
    # rerun of a completed checkpoint must not pay the device build), and
    # share across grid configs and tuning trials
    datasets_cache: Dict[str, object] = {}

    def get_datasets():
        if "d" not in datasets_cache:
            datasets_cache["d"] = estimator.prepare_datasets(raw)
        return datasets_cache["d"]

    # fine-grained crash safety (robust.checkpoint): snapshot the CD
    # outer-loop state at coordinate-update boundaries, resume bit-exact
    cd_manager = None
    resume_snap = None
    ckpt_topology = None
    if args.checkpoint_every or args.resume:
        from ..plan import plan_fingerprint

        # the topology contract both sides of a checkpoint speak: saves
        # stamp it into the manifest, resumes judge the saved stamp through
        # plan.check_checkpoint_topology. global_rows is the PADDED total
        # (equal_host_share rows per process), so the number itself encodes
        # whether per-host shard boundaries agree across process counts
        ckpt_topology = {
            "n_processes": multihost.process_count(),
            "mesh_axes": estimator.execution_plan.mesh_axes,
            "plan_fingerprint": plan_fingerprint(estimator.execution_plan),
            "global_rows": int(raw.n_rows) * multihost.process_count(),
        }
    if args.checkpoint_every:
        if not args.checkpoint_dir:
            raise SystemExit("--checkpoint-every requires --checkpoint-dir")
        cd_manager = CheckpointManager(
            os.path.join(args.checkpoint_dir, "cd-boundaries"),
            keep_last=args.checkpoint_keep,
            every=args.checkpoint_every,
            process=multihost.process_index(),
            n_processes=multihost.process_count(),
            topology={
                "mesh_axes": ckpt_topology["mesh_axes"],
                "plan_fingerprint": ckpt_topology["plan_fingerprint"],
            },
        )
    if args.resume:
        if not args.checkpoint_dir:
            raise SystemExit("--resume requires --checkpoint-dir")
        mgr = cd_manager or CheckpointManager(
            os.path.join(args.checkpoint_dir, "cd-boundaries"),
            keep_last=args.checkpoint_keep,
        )
        # boundary checkpoints are coordinator-written; load there and
        # broadcast so non-shared filesystems resume consistently
        if multihost.is_coordinator():
            resume_snap = mgr.latest_valid(
                expect_coordinate_order=[cc.name for cc in coords],
                expect_topology=ckpt_topology,
            )
        if multihost.process_count() > 1:
            resume_snap = multihost.broadcast_object(resume_snap)
        if resume_snap is None:
            logger.info("--resume: no valid boundary checkpoint; starting fresh")

    with obs.span("train"):
        if args.checkpoint_dir:
            ckpt = _Checkpoint.open(args, coords, index_maps)
            results = ckpt.fit_grid(
                estimator, raw, validation, get_datasets, initial_model,
                cd_manager=cd_manager, resume_snapshot=resume_snap,
            )
        else:
            results = estimator.fit(
                raw, validation=validation, initial_model=initial_model,
                datasets=get_datasets(),
            )

        # optional hyperparameter auto-tuning (GameTrainingDriver:642-673)
        tuned_results: List[GameResult] = []
        if args.hyper_parameter_tuning != "NONE" and validation is not None:
            tuned_results = _run_tuning(
                args, estimator, raw, _resolve_validation(validation), coords,
                results, ckpt=ckpt, datasets_fn=get_datasets,
                resume_snap=resume_snap,
            )

    all_results = list(results) + tuned_results
    best = estimator.select_best(all_results)

    summary = {
        "task": args.task,
        "configs": [
            {
                "reg_weights": r.config,
                "metrics": None if r.evaluation is None else r.evaluation.metrics,
            }
            for r in all_results
        ],
        "best": {
            "reg_weights": best.config,
            "metrics": None if best.evaluation is None else best.evaluation.metrics,
        },
    }
    if run_t is not None and multihost.is_coordinator():
        # run_summary.json is a fleet-level document (one per run, not per
        # process); peers contribute via their metrics.p*.jsonl streams
        _write_run_summary(args, run_t, recorder, t_run0, summary=summary)
    if not multihost.is_coordinator():
        # only process 0 writes outputs (the reference's driver-to-HDFS role)
        return summary

    os.makedirs(args.output_dir, exist_ok=True)
    atomic_write_json(
        os.path.join(args.output_dir, "training-summary.json"),
        summary, indent=2, default=float,
    )

    to_save = all_results if args.output_mode == OUTPUT_MODE_ALL else [best]
    for i, r in enumerate(to_save):
        name = "best" if r is best and args.output_mode != OUTPUT_MODE_ALL else f"model-{i}"
        save_game_model(
            os.path.join(args.output_dir, "models", name),
            r.model,
            index_maps,
            metadata={"regWeights": r.config},
            sparsity_threshold=args.model_sparsity_threshold,
        )
    logger.info("saved %d model(s) to %s", len(to_save), args.output_dir)
    return summary


def _write_run_summary(args, run_t, recorder, t_run0, summary=None,
                       aborted=False) -> None:
    """Write run_summary.json (+ the Chrome trace) from the run's registry.

    Shared between the end-of-run path and the crash-flush in ``run()``: on
    a mid-sweep abort ``summary`` is None, the document carries
    ``"aborted": true``, and the timeline holds every span that closed
    before the abort."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # photon: ignore[R4] - no-jax fallback, host-only sample
        devices = ()
    # final sample so host/device watermarks are present even for runs that
    # never reached a sweep boundary
    obs.sample_memory(run_t.registry, devices=devices)
    doc = obs.build_run_summary(
        run_t.registry, total_wall_seconds=time.perf_counter() - t_run0
    )
    doc["task"] = getattr(args, "task", None) if summary is None else summary["task"]
    plan = getattr(run_t, "execution_plan", None)
    if plan is not None:
        doc["plan"] = plan
    if summary is not None:
        doc["best"] = summary["best"]
    if aborted:
        doc["aborted"] = True
    if recorder is not None:
        # drain the listener queue: on the normal path the "train" span has
        # closed by here, so the timeline holds the whole run
        doc["timeline"] = recorder.phase_attribution()
        recorder.write_chrome_trace(args.trace_out)
        logger.info("chrome trace -> %s (load at ui.perfetto.dev)",
                    args.trace_out)
    # --trace-out without --metrics-out still gets a run_summary.json
    # (the phase attribution belongs with the trace): next to the trace
    summary_dir = args.metrics_out or os.path.dirname(
        os.path.abspath(args.trace_out or "")
    )
    if args.metrics_out or args.trace_out:
        atomic_write_json(
            os.path.join(summary_dir, "run_summary.json"),
            doc, indent=2, default=float,
        )


def _emit_report(args) -> None:
    """Build report.json + report.html under --report-out.

    Reads back the artifacts just written to disk (run_summary.json,
    metrics.jsonl, training-summary.json, saved models) rather than any
    in-memory state, so a later ``cli report`` over the same directory
    reproduces report.json byte-identically."""
    from ..obs import report as report_mod

    try:
        inputs = report_mod.collect_training_inputs(
            summary_dir=args.metrics_out or (
                os.path.dirname(os.path.abspath(args.trace_out))
                if args.trace_out else None
            ),
            output_dir=args.output_dir,
            checkpoint_dir=args.checkpoint_dir,
            feature_index_dir=args.feature_index_dir,
        )
        paths = report_mod.write_report(
            report_mod.build_report(inputs), args.report_out
        )
    except Exception:
        # the report is a post-hoc convenience; a rendering bug must not
        # turn a finished (and saved) training run into a CLI failure
        obs.swallowed_error("cli.report_out")
        logger.exception("training report generation failed")
        return
    logger.info("training report -> %s", paths["html"])


# shared with io/data's chunked training-data reader (utils/futures.py);
# the old name stays as an alias for anything importing it from here
_DaemonFuture = DaemonFuture


def _resolve_validation(validation):
    """Unwrap a deferred validation dataset (Future from the background
    decode thread); already-resolved datasets pass through."""
    return validation.result() if hasattr(validation, "result") else validation


def _run_tuning(args, estimator, raw, validation, coords, prior_results,
                ckpt=None, datasets_fn=None, resume_snap=None):
    """GP/random tuning over per-coordinate log10 reg weights
    (GameEstimatorEvaluationFunction semantics: candidate <-> (log lambda,...)).

    The explicit grid results seed the tuner as observations
    (GameTrainingDriver.scala:666 `convertObservations(models)`), so the GP
    starts warm instead of re-exploring the grid. An optional JSON tuning
    config overrides the search ranges; optional prior observations shrink
    the range around the GP-predicted best (ShrinkSearchRange.getBounds).

    With ``ckpt``, each finished trial is recorded (model + metrics + unit
    vector); a resumed run replays recorded trials as observations and only
    runs the remainder. Trials always train the FULL
    --coordinate-descent-iterations (the estimator's sweep count is never
    mutated by checkpoint resume — round-3 advisor finding).
    """
    from ..tuning import Observation, prior_to_json

    tunable = [cc.name for cc in coords if cc.name not in estimator.partial_retrain_locked]
    hp = _build_tuning_config(args, tunable)
    names = [p.name for p in hp.params]
    higher_better = _higher_is_better(args.evaluators)
    sign = -1.0 if higher_better else 1.0
    results: List[GameResult] = []

    def evaluate(unit_vec):
        faults.check("tuning.trial")
        native = hp.scale_up(unit_vec)
        weights = {
            n.removesuffix(".reg_weight"): float(v) for n, v in zip(names, native)
        }
        import dataclasses as dc

        cfgs = []
        for cc in coords:
            w = weights.get(cc.name, cc.config.reg_weight)
            cfgs.append(dc.replace(cc, reg_weights=(w,)))
        est = GameEstimator(
            task=args.task,
            coordinate_configs=cfgs,
            n_cd_iterations=args.coordinate_descent_iterations,
            evaluator_specs=[e for e in args.evaluators.split(",") if e],
            partial_retrain_locked=list(estimator.partial_retrain_locked),
            mesh=estimator.mesh,
            validation_frequency=estimator.validation_frequency,
            divergence_guard=estimator.divergence_guard,
            rejection_tolerance=estimator.rejection_tolerance,
            pipeline_depth=estimator.pipeline_depth,
        )
        r = est.fit(
            raw, validation=validation,
            datasets=datasets_fn() if datasets_fn is not None else None,
        )[0]
        results.append(r)
        metric = r.evaluation.primary_metric
        # the tuner minimizes; negate higher-is-better metrics
        value = sign * metric
        if ckpt is not None:
            ckpt.record_trial(unit_vec, value, r)
        obs.current_run().registry.counter(
            "photon_tuning_trials_total", "tuning trials completed"
        ).inc()
        return value, r

    def evaluate_batch(cands):
        """Train a whole candidate batch as lambda lanes of ONE solve
        (game/lanes.py): every lane shares each coordinate's data residency
        and compiled executable, so K trials cost roughly one K-lane-wide
        solve instead of K sequential fits."""
        registry = obs.current_run().registry
        combos = []
        for unit_vec in cands:
            native = hp.scale_up(unit_vec)
            weights = {
                n.removesuffix(".reg_weight"): float(v)
                for n, v in zip(names, native)
            }
            combos.append(
                {cc.name: weights.get(cc.name, cc.config.reg_weight) for cc in coords}
            )
        est = GameEstimator(
            task=args.task,
            coordinate_configs=list(coords),
            n_cd_iterations=args.coordinate_descent_iterations,
            evaluator_specs=[e for e in args.evaluators.split(",") if e],
            partial_retrain_locked=list(estimator.partial_retrain_locked),
            mesh=estimator.mesh,
            validation_frequency=estimator.validation_frequency,
            divergence_guard=estimator.divergence_guard,
            rejection_tolerance=estimator.rejection_tolerance,
            pipeline_depth=estimator.pipeline_depth,
        )
        with obs.span("tuning.batch", phase="tuning", lanes=len(cands)) as span:
            lane_results = est.fit_lanes(
                raw, combos, validation=validation,
                datasets=datasets_fn() if datasets_fn is not None else None,
            )
        registry.histogram(
            "photon_tuning_batch_wall_seconds",
            "wall time of one lane-batched tuning trial batch",
        ).observe(span.duration_s)
        out = []
        for unit_vec, r in zip(cands, lane_results):
            # record lanes IN LANE ORDER: a mid-batch fault leaves a recorded
            # prefix whose count alone realigns the (chunking-invariant)
            # tuner candidate sequence on resume
            faults.check("tuning.trial")
            results.append(r)
            value = sign * r.evaluation.primary_metric
            if ckpt is not None:
                ckpt.record_trial(
                    unit_vec, value, r, lane=r.trackers.get("lane")
                )
            registry.counter(
                "photon_tuning_trials_total", "tuning trials completed"
            ).inc()
            out.append((value, r))
        return out

    # seed the tuner with the explicit-grid results (convertObservations);
    # skip grid points outside the search range — scale_down would clip them
    # to the cube edge and attach a far-away point's metric to it
    observations = []
    for r in prior_results or []:
        if r.evaluation is None:
            continue
        native = _native_vec(r, names)
        if any(not (p.min <= v <= p.max) for p, v in zip(hp.params, native)):
            continue
        observations.append(
            Observation(
                candidate=hp.scale_down(native),
                value=sign * r.evaluation.primary_metric,
                artifact=r,
            )
        )

    # replay checkpointed trials: reconstruct their results and re-seed the
    # tuner so only the remaining trial budget runs
    n_iter = args.hyper_parameter_tuning_iter
    if ckpt is not None:
        for rec in ckpt.completed_trials():
            r = ckpt._reconstruct(rec)
            results.append(r)
            observations.append(
                Observation(
                    candidate=np.asarray(rec["unit"]),
                    value=float(rec["value"]),
                    artifact=r,
                )
            )
        n_done = len(ckpt.completed_trials())
        if resume_snap is not None:
            # boundary manifests record the trial count at write time; a
            # lost/older checkpoint-state.json must not replay candidates the
            # manifest proves were already drawn — burn those candidates
            # (their observations are gone, but a deterministic tuner's
            # sequence stays aligned via skip=)
            from_manifest = int(resume_snap.manifest.get("tuner_trials", 0))
            if from_manifest > n_done:
                logger.warning(
                    "checkpoint manifest records %d tuning trials but state "
                    "has %d; skipping the %d lost candidates",
                    from_manifest, n_done, from_manifest - n_done,
                )
                n_done = from_manifest
        if n_done:
            logger.info("checkpoint: %d/%d tuning trials already run", n_done, n_iter)
        n_iter = max(n_iter - n_done, 0)

    trial_lanes = int(getattr(args, "trial_lanes", 1) or 1)
    if n_iter > 0:
        tuner = get_tuner(args.hyper_parameter_tuning)
        if trial_lanes > 1:
            from ..game.lanes import check_lane_composition

            check_lane_composition(
                estimator, trial_lanes,
                distributed=multihost.process_count() > 1,
            )
            tuner.search_batched(
                n_iter,
                hp.dim,
                evaluate_batch,
                trial_lanes,
                observations=observations,
                discrete_params=hp.discrete_dims(),
                seed=0,
                # resumed deterministic (Sobol) searches must continue the
                # original candidate sequence, not repeat its prefix — the
                # Sobol stream is chunking-invariant, so the trial COUNT
                # alone realigns it even across a mid-batch kill
                skip=args.hyper_parameter_tuning_iter - n_iter,
            )
        else:
            tuner.search(
                n_iter,
                hp.dim,
                evaluate,
                observations=observations,
                discrete_params=hp.discrete_dims(),
                seed=0,
                # resumed deterministic (Sobol) searches must continue the
                # original candidate sequence, not repeat its prefix
                skip=args.hyper_parameter_tuning_iter - n_iter,
            )

    # record every (grid + tuned) observation as a reusable prior file
    priors = [
        (_native_vec(r, names), r.evaluation.primary_metric)
        for r in list(prior_results or []) + results
        if r.evaluation is not None
    ]

    if multihost.is_coordinator():
        os.makedirs(args.output_dir, exist_ok=True)
        with atomic_write(
            os.path.join(args.output_dir, "hyperparameter-prior.json"), "w"
        ) as f:
            f.write(prior_to_json(names, priors))
    return results


class _Checkpoint:
    """Per-sweep crash-recovery checkpointing across reg-weight grids AND
    tuning trials (beyond the reference, which only has model-granularity
    warm start; round-3 verdict item 9).

    State (``checkpoint-state.json``, version 2, atomically replaced):
      grid           expanded combo list this run must train, in order
      completed      per finished combo: model dir + validation metrics
      current        mid-combo progress: index, completed sweeps, model dir
      tuning_trials  per finished tuning trial: unit vector, value, model dir

    Resume = rerun the same command: finished combos/trials reconstruct from
    their saved models + recorded metrics, the in-flight combo warm-starts
    from its last completed sweep, and tuning resumes with the recorded
    trials re-seeded as GP observations.

    Multi-process: only process 0 writes, and its state is AUTHORITATIVE —
    every process allgathers the state views and adopts the coordinator's
    (warned when they differ), and checkpointed models load on the
    coordinator and one-to-all broadcast. A shared filesystem is therefore
    NOT required; collective schedules stay aligned because all processes
    run the coordinator's state (round-3 advisor finding: divergent
    `remaining` counts => mismatched collective schedules, hang).

    With --validation-data, best-model tracking within the in-flight combo
    restarts at the resume point: pre-crash sweeps are no longer best-model
    candidates (the checkpoint stores last-sweep models, not the tracked
    best)."""

    def __init__(self, args, coords, index_maps, state, state_path):
        self.args = args
        self.coords = coords
        self.index_maps = index_maps
        self.state = state
        self.state_path = state_path
        self.dir = args.checkpoint_dir

    @classmethod
    def open(cls, args, coords, index_maps):

        names = [cc.name for cc in coords]
        import itertools

        combos = [
            dict(zip(names, map(float, c)))
            for c in itertools.product(*[cc.grid() for cc in coords])
        ]
        state_path = os.path.join(args.checkpoint_dir, "checkpoint-state.json")
        state = None
        if os.path.exists(state_path):
            with open(state_path) as f:
                state = json.load(f)
        if multihost.process_count() > 1:
            # the COORDINATOR's state is authoritative: it is the only writer
            # (process-0-only writes), so a non-shared filesystem leaves the
            # other processes stale or empty — broadcast process 0's view
            # instead of refusing (r3 advisor suggestion; model files are
            # broadcast the same way in _load_model). The collective schedule
            # stays aligned because every process now runs the same state.
            views = multihost.allgather_object(json.dumps(state, sort_keys=True))
            if len(set(views)) != 1:
                logger.warning(
                    "checkpoint states differ across processes (non-shared "
                    "filesystem); adopting the coordinator's state"
                )
            state = json.loads(views[0])
        if state is None:
            state = {
                "version": 2,
                "grid": combos,
                "n_cd_iterations": args.coordinate_descent_iterations,
                "completed": [],
                "current": None,
                "tuning_trials": [],
            }
        elif state.get("version") != 2:
            raise SystemExit(
                f"checkpoint at {args.checkpoint_dir} uses state version "
                f"{state.get('version')}; this build writes version 2 — pass "
                "a fresh --checkpoint-dir"
            )
        elif state.get("grid") != combos:
            raise SystemExit(
                f"checkpoint at {args.checkpoint_dir} was written for grid "
                f"{state.get('grid')}, not {combos}; pass a fresh "
                "--checkpoint-dir"
            )
        elif state.get("n_cd_iterations") != args.coordinate_descent_iterations:
            raise SystemExit(
                f"checkpoint at {args.checkpoint_dir} was written for "
                f"{state.get('n_cd_iterations')} coordinate-descent "
                "iterations; resume with the same "
                "--coordinate-descent-iterations (completed configurations "
                "trained that many sweeps), or warm-start a fresh run from "
                "the final model via --model-input-dir"
            )
        if args.validation_data:
            logger.warning(
                "--checkpoint-dir with --validation-data: on resume, "
                "best-model tracking only sees post-resume sweeps of the "
                "in-flight configuration"
            )
        os.makedirs(args.checkpoint_dir, exist_ok=True)
        return cls(args, coords, index_maps, state, state_path)

    def _write(self):

        if not multihost.is_coordinator():
            return
        atomic_write_json(self.state_path, self.state)

    def _load_model(self, model_dir):
        # model files exist only where the coordinator wrote them
        # (process-0-only writes): load there, one-to-all broadcast to the
        # others — checkpoint resume no longer requires a shared filesystem,
        # and the payload crosses the fabric exactly once
        if multihost.process_count() > 1:
            model = None
            if multihost.is_coordinator():
                model = load_game_model(
                    os.path.join(self.dir, model_dir),
                    self.index_maps,
                    task=self.args.task,
                )
            return multihost.broadcast_object(model)
        return load_game_model(
            os.path.join(self.dir, model_dir), self.index_maps, task=self.args.task
        )

    def _save_model(self, model_dir, game_model, reg_weights):

        if multihost.is_coordinator():
            save_game_model(
                os.path.join(self.dir, model_dir), game_model, self.index_maps,
                metadata={"regWeights": reg_weights},
            )

    def _reconstruct(self, rec):
        ev = None
        if rec.get("metrics"):
            from ..evaluation.suite import EvaluationResults

            ev = EvaluationResults(
                primary_name=rec["primary_name"], metrics=rec["metrics"]
            )
        return GameResult(
            model=self._load_model(rec["model_dir"]),
            config=rec["reg_weights"],
            evaluation=ev,
            trackers={},
        )

    def fit_grid(self, estimator, raw, validation, datasets_fn, initial_model,
                 cd_manager=None, resume_snapshot=None):
        """``cd_manager`` (robust.CheckpointManager) adds coordinate-update-
        boundary snapshots on top of the per-sweep model saves;
        ``resume_snapshot`` (robust.CheckpointSnapshot) resumes its combo
        mid-sweep, bit-identical. The two granularities compose: whichever
        record is further along wins, and boundary manifests carry
        ``combo_index`` / ``sweep_offset`` so a snapshot written during a
        sweep-level-resumed run still maps back to global sweep numbering."""
        import shutil

        # checkpointed grids read validation directly (recovered-metric
        # scoring): resolve any deferred decode up front
        validation = _resolve_validation(validation)

        combos = self.state["grid"]
        n_iter = self.args.coordinate_descent_iterations
        results: List[GameResult] = []
        prev = initial_model
        for rec in self.state["completed"]:
            r = self._reconstruct(rec)
            results.append(r)
            prev = r.model
        if self.state["completed"]:
            logger.info(
                "checkpoint: %d/%d configurations already trained",
                len(self.state["completed"]), len(combos),
            )

        for k in range(len(results), len(combos)):
            done = 0
            cur = self.state.get("current")
            if cur and cur.get("index") == k and cur.get("completed_sweeps", 0) > 0:
                done = int(cur["completed_sweeps"])
            snap = None
            if (
                resume_snapshot is not None
                and int(resume_snapshot.manifest.get("combo_index", -1)) == k
            ):
                snap = resume_snapshot
                # global sweep the snapshot sits in = offset of the run that
                # wrote it + its local iteration; an older sweep-level record
                # must not win over it (and vice versa)
                snap_global = int(snap.manifest.get("sweep_offset", 0)) + int(
                    snap.iteration
                )
                if snap_global < done:
                    logger.info(
                        "config %d: per-sweep record (sweep %d) is ahead of "
                        "the boundary snapshot (sweep %d); using the former",
                        k, done, snap_global,
                    )
                    snap = None
            if snap is not None:
                done = int(snap.manifest.get("sweep_offset", 0))
                logger.info(
                    "resuming config %d from boundary snapshot %s "
                    "(iter %d after coordinate %s)",
                    k, snap.path, snap.iteration, snap.coordinate,
                )
            elif done > 0:
                prev = self._load_model(cur["model_dir"])
                logger.info(
                    "resuming config %d from sweep %d/%d", k, done, n_iter
                )

            def sweep_fn(reg_weights, iteration, game_model, _k=k, _done=done):
                j = _done + iteration + 1
                model_dir = f"config-{_k:03d}-sweep-{j:04d}"
                self._save_model(model_dir, game_model, reg_weights)
                self.state["current"] = {
                    "index": _k, "completed_sweeps": j, "model_dir": model_dir,
                }
                self._write()
                prev_dir = os.path.join(
                    self.dir, f"config-{_k:03d}-sweep-{j - 1:04d}"
                )

                if multihost.is_coordinator() and os.path.isdir(prev_dir):
                    shutil.rmtree(prev_dir, ignore_errors=True)

            boundary = None
            if cd_manager is not None:
                n_trials = len(self.state.get("tuning_trials", []))

                def boundary(reg_weights, st, _k=k, _done=done, _n=n_trials):
                    # single-process: coordinator-only like _save_model
                    # (boundary snapshots live on the coordinator's
                    # filesystem and broadcast on resume). A distributed
                    # manager instead needs EVERY process at the boundary:
                    # phase one writes each process's score shard and the
                    # confirm exchange is itself a collective
                    if cd_manager.n_processes > 1 or multihost.is_coordinator():
                        cd_manager.on_boundary(
                            st,
                            meta={
                                "reg_weights": reg_weights,
                                "combo_index": _k,
                                "sweep_offset": _done,
                                "tuner_trials": _n,
                            },
                        )

            if snap is not None:
                # fine-grained resume: descent continues mid-sweep from the
                # snapshot (full per-call iteration count of the run that
                # wrote it; resume_state overrides initial models)
                r = estimator.fit(
                    raw, validation=validation, initial_model=prev,
                    checkpoint_fn=sweep_fn, datasets=datasets_fn(),
                    combos=[combos[k]],
                    n_cd_iterations=int(snap.manifest["n_iterations"]),
                    boundary_fn=boundary, resume_state=snap,
                )[0]
                self._finish_combo(k, combos, r, n_iter)
                results.append(r)
                prev = r.model
                continue

            remaining = n_iter - done
            if remaining <= 0:
                # crashed between the last sweep save and the completion
                # record: the model is fully trained, only metrics are lost —
                # recover them by scoring the validation set (same default
                # evaluator as _validation_context, so the recovered config
                # stays comparable in select_best)
                model = prev
                ev = None
                if validation is not None:
                    ev = GameTransformer(model=model, dtype=estimator.dtype).transform(
                        validation,
                        evaluator_specs=estimator.evaluator_specs or ["RMSE"],
                    )[1]
                r = GameResult(
                    model=model, config=combos[k], evaluation=ev, trackers={}
                )
            else:
                r = estimator.fit(
                    raw, validation=validation, initial_model=prev,
                    checkpoint_fn=sweep_fn, datasets=datasets_fn(),
                    combos=[combos[k]], n_cd_iterations=remaining,
                    boundary_fn=boundary,
                )[0]
            self._finish_combo(k, combos, r, n_iter)
            results.append(r)
            prev = r.model
        return results

    def _finish_combo(self, k, combos, r: GameResult, n_iter):
        """Record config ``k`` as completed: final model, metrics, state
        flip, per-sweep model cleanup."""
        import shutil

        final_dir = f"config-{k:03d}-final"
        self._save_model(final_dir, r.model, combos[k])
        self.state["completed"].append(
            {
                "reg_weights": combos[k],
                "model_dir": final_dir,
                "metrics": None if r.evaluation is None else r.evaluation.metrics,
                "primary_name": None
                if r.evaluation is None
                else r.evaluation.primary_name,
            }
        )
        self.state["current"] = None
        self._write()

        if multihost.is_coordinator():
            last = os.path.join(self.dir, f"config-{k:03d}-sweep-{n_iter:04d}")
            if os.path.isdir(last):
                shutil.rmtree(last, ignore_errors=True)

    # -- tuning trials --------------------------------------------------------

    def completed_trials(self):
        return list(self.state.get("tuning_trials", []))

    def record_trial(self, unit_vec, value, result: GameResult, lane=None):
        """``lane``: lane-batched sweeps (--trial-lanes) pass the trial's
        lane tracker so a resumed run can tell how far through a batch the
        interrupted run got — lanes record IN LANE ORDER, so the trial count
        alone realigns the Sobol/GP sequence (chunking-invariant)."""
        i = len(self.state["tuning_trials"])
        model_dir = f"tuning-{i:03d}"
        self._save_model(model_dir, result.model, result.config)
        rec = {
            "unit": [float(x) for x in np.asarray(unit_vec).ravel()],
            "value": float(value),
            "reg_weights": result.config,
            "model_dir": model_dir,
            "metrics": None
            if result.evaluation is None
            else result.evaluation.metrics,
            "primary_name": None
            if result.evaluation is None
            else result.evaluation.primary_name,
        }
        if lane is not None:
            rec["lane"] = {
                "index": int(lane.get("index", 0)),
                "n_lanes": int(lane.get("n_lanes", 1)),
            }
        self.state["tuning_trials"].append(rec)
        self._write()


def _native_vec(result: GameResult, names: List[str]) -> np.ndarray:
    """GameResult -> native hyperparameter vector ordered by `names`
    (vectorizeParams semantics; names are '<coordinate>.reg_weight')."""
    return np.asarray(
        [result.config.get(n.removesuffix(".reg_weight"), 1.0) for n in names]
    )


def _build_tuning_config(args, tunable: List[str]) -> HyperparameterConfig:
    """Default per-coordinate log-λ ranges, optionally overridden by a JSON
    tuning config and shrunk around prior observations."""
    from ..tuning import config_from_json, get_bounds

    if args.hyper_parameter_config:
        with open(args.hyper_parameter_config) as f:
            _, hp = config_from_json(f.read())
        tunable_names = {f"{n}.reg_weight" for n in tunable}
        bad = [p.name for p in hp.params if p.name not in tunable_names]
        if bad:
            raise SystemExit(
                f"--hyper-parameter-config variables {bad} do not name tunable "
                f"coordinates; expected names among {sorted(tunable_names)}"
            )
    else:
        hp = HyperparameterConfig(
            params=[
                ParamRange(name=f"{n}.reg_weight", min=1e-4, max=1e4, transform="LOG")
                for n in tunable
            ]
        )
    if args.hyper_parameter_prior:
        import dataclasses as dc

        with open(args.hyper_parameter_prior) as f:
            lower, upper = get_bounds(
                hp,
                f.read(),
                radius=args.hyper_parameter_shrink_radius,
                higher_is_better=_higher_is_better(args.evaluators),
            )
        hp = HyperparameterConfig(
            params=[
                dc.replace(p, min=float(lo), max=float(hi))
                for p, lo, hi in zip(hp.params, lower, upper)
            ]
        )
    return hp


def _higher_is_better(evaluators: str) -> bool:
    from ..evaluation.evaluators import build_evaluator

    specs = [e for e in evaluators.split(",") if e]
    if not specs:
        return False
    return build_evaluator(specs[0]).higher_is_better


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
