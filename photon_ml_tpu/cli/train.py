"""GAME training driver.

Reference: photon-client .../cli/game/training/GameTrainingDriver.scala:54-854
(§3.1 call stack): read+index data -> validate -> normalization -> expand
optimization configs -> GameEstimator.fit -> model selection (output mode
ALL/BEST/TUNED) -> optional GP hyperparameter tuning -> save models.

Usage:
  python -m photon_ml_tpu.cli.train \\
    --input-data train.avro --validation-data val.avro \\
    --task logistic_regression \\
    --feature-shard name=globalShard,bags=features \\
    --feature-shard name=userShard,bags=userFeatures \\
    --coordinate name=global,shard=globalShard,optimizer=TRON,reg.type=L2,reg.weights=1|10 \\
    --coordinate name=per-user,shard=userShard,re.type=userId,reg.type=L2,reg.weights=1 \\
    --evaluators AUC,LOGISTIC_LOSS --output-dir out/
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from ..estimators.game_estimator import GameEstimator, GameResult
from ..io import read_avro_dataset, save_game_model
from ..io.index_map import IndexMap
from ..io.model_io import load_game_model
from ..ops.normalization import build_normalization
from ..tuning.rescaling import HyperparameterConfig, ParamRange
from ..tuning.tuner import get_tuner
from ..utils.logging import setup_logging
from ..utils.stats import compute_feature_statistics, save_feature_statistics
from .params import (
    add_common_io_args,
    build_shard_configs,
    parse_coordinate,
    parse_input_columns,
    parse_mesh_shape,
    resolve_input_paths,
)

logger = logging.getLogger("photon_ml_tpu")

OUTPUT_MODE_ALL = "ALL"
OUTPUT_MODE_BEST = "BEST"
OUTPUT_MODE_TUNED = "TUNED"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu game training driver")
    add_common_io_args(p)
    p.add_argument("--validation-data", default=None)
    p.add_argument("--task", default="logistic_regression")
    p.add_argument(
        "--coordinate",
        action="append",
        default=[],
        required=False,
        help="coordinate configuration spec (repeatable, ordered)",
    )
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--evaluators", default="", help="comma-separated evaluator specs")
    p.add_argument("--output-dir", required=True)
    p.add_argument(
        "--output-mode",
        default=OUTPUT_MODE_BEST,
        choices=[OUTPUT_MODE_ALL, OUTPUT_MODE_BEST, OUTPUT_MODE_TUNED],
    )
    p.add_argument("--model-input-dir", default=None, help="warm-start GAME model")
    p.add_argument(
        "--incremental-training",
        action="store_true",
        help="L2-regularize toward the warm-start model's means weighted by its "
        "precisions (requires --model-input-dir)",
    )
    p.add_argument(
        "--partial-retrain-locked",
        default="",
        help="comma-separated coordinate names to lock (requires --model-input-dir)",
    )
    p.add_argument(
        "--normalization",
        default="NONE",
        choices=["NONE", "STANDARDIZATION", "SCALE_WITH_STANDARD_DEVIATION", "SCALE_WITH_MAX_MAGNITUDE"],
    )
    p.add_argument("--model-sparsity-threshold", type=float, default=0.0)
    p.add_argument("--compute-feature-stats", action="store_true")
    p.add_argument(
        "--hyper-parameter-tuning",
        default="NONE",
        choices=["NONE", "RANDOM", "BAYESIAN"],
    )
    p.add_argument("--hyper-parameter-tuning-iter", type=int, default=10)
    p.add_argument(
        "--hyper-parameter-config",
        default=None,
        help="JSON tuning config (HyperparameterSerialization.configFromJson "
        "shape: tuning_mode + variables map); overrides the default "
        "per-coordinate log-reg-weight ranges",
    )
    p.add_argument(
        "--hyper-parameter-prior",
        default=None,
        help="JSON prior observations ({'records': [...]}) used to shrink the "
        "search range around the GP-predicted best prior candidate "
        "(ShrinkSearchRange.getBounds)",
    )
    p.add_argument(
        "--hyper-parameter-shrink-radius",
        type=float,
        default=0.25,
        help="unit-cube radius of the shrunk search range around the best "
        "prior candidate",
    )
    p.add_argument(
        "--mesh-shape",
        default="",
        help="device mesh, e.g. data=4,model=2: data axis shards rows/entities, "
        "model axis shards the coefficient dim of layout=tiled coordinates",
    )
    p.add_argument(
        "--checkpoint-dir",
        default=None,
        help="save the model after every coordinate-descent sweep; rerunning "
        "the same single-config command resumes from the last completed "
        "sweep (crash recovery for long runs)",
    )
    p.add_argument(
        "--distributed",
        default=None,
        help="multi-host: 'coordinator=HOST:PORT,process=I,n=P' (or 'auto' "
        "for env/cluster auto-detection); each process reads its own row "
        "range and only process 0 writes outputs",
    )
    p.add_argument("--log-file", default=None)
    p.add_argument("--log-level", default="INFO")
    return p


def run(argv: Optional[List[str]] = None) -> Dict:
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, args.log_file)

    from ..parallel import multihost

    if args.distributed:
        if args.distributed == "auto":
            multihost.initialize()
        else:
            multihost.initialize_from_spec(args.distributed)
        import jax  # only safe to touch after jax.distributed.initialize

        if not args.mesh_shape:
            raise SystemExit(
                "--distributed requires --mesh-shape spanning all global "
                f"devices (e.g. data={jax.device_count()}); without a mesh "
                "each process would silently train on only its own row slice"
            )
        logger.info(
            "distributed: process %d/%d, %d local / %d global devices",
            multihost.process_index(), multihost.process_count(),
            jax.local_device_count(), jax.device_count(),
        )

    shards = build_shard_configs(args)
    id_tags = [t for t in args.id_tags.split(",") if t]
    coord_specs = args.coordinate or [
        "name=global,shard=global,optimizer=LBFGS,reg.type=L2,reg.weights=1"
    ]
    coords = [parse_coordinate(s) for s in coord_specs]
    for cc in coords:
        if cc.is_random_effect and cc.random_effect_type not in id_tags:
            id_tags.append(cc.random_effect_type)

    input_paths = resolve_input_paths(args)
    input_columns = parse_input_columns(args)
    logger.info("reading training data from %s", input_paths)
    index_maps = None
    if args.feature_index_dir:
        from ..io.index_map import load_partitioned

        index_maps = {s: load_partitioned(args.feature_index_dir, s) for s in shards}

    row_range = None
    equal_share = None
    part_counts = None
    if multihost.process_count() > 1:
        if any(cc.is_random_effect for cc in coords):
            raise SystemExit(
                "multi-process training currently covers fixed-effect "
                "coordinates (data-parallel gradients across hosts); "
                "random-effect entity planning is single-process"
            )
        if any(getattr(cc, "layout", None) == "tiled" for cc in coords):
            raise SystemExit(
                "layout=tiled (model-axis sharding) is single-process only; "
                "multi-process runs shard the data axis"
            )
        if index_maps is None:
            raise SystemExit(
                "multi-process training requires --feature-index-dir "
                "(host-local index maps would disagree across hosts)"
            )
        if args.normalization != "NONE":
            raise SystemExit(
                "multi-process training does not support --normalization yet "
                "(statistics would be computed from host-local rows only)"
            )
        if args.compute_feature_stats:
            raise SystemExit(
                "--compute-feature-stats is single-process only (it would "
                "summarize the coordinator's row slice as if it were global)"
            )
        from ..io.avro import count_avro_rows, list_avro_parts

        paths = [input_paths] if isinstance(input_paths, str) else input_paths
        part_counts = {
            part: count_avro_rows(part)
            for p in paths
            for part in list_avro_parts(p)
        }
        total_rows = sum(part_counts.values())
        row_range = multihost.host_row_range(total_rows)
        # all hosts pad their slice to a common size so every process
        # contributes equal local shapes to the global arrays
        equal_share = multihost.equal_host_share(total_rows)
        logger.info(
            "process %d reads rows [%d, %d) of %d (padded to %d)",
            multihost.process_index(), row_range[0], row_range[1], total_rows,
            equal_share,
        )
    raw, index_maps = read_avro_dataset(
        input_paths,
        shards,
        index_maps=index_maps,
        id_tag_columns=id_tags,
        response_column=args.response_column,
        columns=input_columns,
        row_range=row_range,
        part_counts=part_counts,
    )
    if equal_share is not None:
        raw = raw.pad_rows(equal_share)
    logger.info("training rows: %d; shard dims: %s", raw.n_rows, raw.shard_dims)

    validation = None
    if args.validation_data:
        validation, _ = read_avro_dataset(
            args.validation_data,
            shards,
            index_maps=index_maps,
            id_tag_columns=id_tags,
            response_column=args.response_column,
            columns=input_columns,
        )

    # normalization from feature statistics (GameTrainingDriver:555-571)
    if args.normalization != "NONE":
        for cc in coords:
            if not cc.is_random_effect:
                stats = compute_feature_statistics(raw, cc.feature_shard)
                cc.normalization = build_normalization(
                    args.normalization,
                    stats["mean"],
                    stats["variance"],
                    stats["max_magnitude"],
                    intercept_index=index_maps[cc.feature_shard].intercept_index,
                )

    if args.compute_feature_stats and multihost.is_coordinator():
        os.makedirs(args.output_dir, exist_ok=True)
        for shard in shards:
            save_feature_statistics(
                os.path.join(args.output_dir, f"feature-stats-{shard}.avro"),
                compute_feature_statistics(raw, shard),
                index_maps[shard],
            )

    initial_model = None
    if args.model_input_dir:
        initial_model = load_game_model(args.model_input_dir, index_maps, task=args.task)
    if args.incremental_training:
        if initial_model is None:
            raise SystemExit("--incremental-training requires --model-input-dir")
        for cc in coords:
            cc.regularize_by_prior = True

    evaluators = [e for e in args.evaluators.split(",") if e]
    mesh = parse_mesh_shape(args.mesh_shape)

    n_cd_iterations = args.coordinate_descent_iterations
    checkpoint_fn = None
    if args.checkpoint_dir:
        initial_model, n_cd_iterations, checkpoint_fn = _setup_checkpointing(
            args, coords, index_maps, initial_model, n_cd_iterations
        )

    estimator = GameEstimator(
        task=args.task,
        coordinate_configs=coords,
        n_cd_iterations=n_cd_iterations,
        evaluator_specs=evaluators,
        partial_retrain_locked=[
            c for c in args.partial_retrain_locked.split(",") if c
        ],
        mesh=mesh,
    )
    results = estimator.fit(
        raw, validation=validation, initial_model=initial_model,
        checkpoint_fn=checkpoint_fn,
    )

    # optional hyperparameter auto-tuning (GameTrainingDriver:642-673)
    tuned_results: List[GameResult] = []
    if args.hyper_parameter_tuning != "NONE" and validation is not None:
        tuned_results = _run_tuning(args, estimator, raw, validation, coords, results)

    all_results = list(results) + tuned_results
    best = estimator.select_best(all_results)

    summary = {
        "task": args.task,
        "configs": [
            {
                "reg_weights": r.config,
                "metrics": None if r.evaluation is None else r.evaluation.metrics,
            }
            for r in all_results
        ],
        "best": {
            "reg_weights": best.config,
            "metrics": None if best.evaluation is None else best.evaluation.metrics,
        },
    }
    if not multihost.is_coordinator():
        # only process 0 writes outputs (the reference's driver-to-HDFS role)
        return summary

    os.makedirs(args.output_dir, exist_ok=True)
    with open(os.path.join(args.output_dir, "training-summary.json"), "w") as f:
        json.dump(summary, f, indent=2, default=float)

    to_save = all_results if args.output_mode == OUTPUT_MODE_ALL else [best]
    for i, r in enumerate(to_save):
        name = "best" if r is best and args.output_mode != OUTPUT_MODE_ALL else f"model-{i}"
        save_game_model(
            os.path.join(args.output_dir, "models", name),
            r.model,
            index_maps,
            metadata={"regWeights": r.config},
            sparsity_threshold=args.model_sparsity_threshold,
        )
    logger.info("saved %d model(s) to %s", len(to_save), args.output_dir)
    return summary


def _run_tuning(args, estimator, raw, validation, coords, prior_results):
    """GP/random tuning over per-coordinate log10 reg weights
    (GameEstimatorEvaluationFunction semantics: candidate <-> (log lambda,...)).

    The explicit grid results seed the tuner as observations
    (GameTrainingDriver.scala:666 `convertObservations(models)`), so the GP
    starts warm instead of re-exploring the grid. An optional JSON tuning
    config overrides the search ranges; optional prior observations shrink
    the range around the GP-predicted best (ShrinkSearchRange.getBounds).
    """
    from ..tuning import Observation, prior_to_json

    tunable = [cc.name for cc in coords if cc.name not in estimator.partial_retrain_locked]
    hp = _build_tuning_config(args, tunable)
    names = [p.name for p in hp.params]
    higher_better = _higher_is_better(args.evaluators)
    sign = -1.0 if higher_better else 1.0
    results: List[GameResult] = []

    def evaluate(unit_vec):
        native = hp.scale_up(unit_vec)
        weights = {
            n.removesuffix(".reg_weight"): float(v) for n, v in zip(names, native)
        }
        import dataclasses as dc

        cfgs = []
        for cc in coords:
            w = weights.get(cc.name, cc.config.reg_weight)
            cfgs.append(dc.replace(cc, reg_weights=(w,)))
        est = GameEstimator(
            task=args.task,
            coordinate_configs=cfgs,
            n_cd_iterations=args.coordinate_descent_iterations,
            evaluator_specs=[e for e in args.evaluators.split(",") if e],
            partial_retrain_locked=list(estimator.partial_retrain_locked),
            mesh=estimator.mesh,
        )
        r = est.fit(raw, validation=validation)[0]
        results.append(r)
        metric = r.evaluation.primary_metric
        # the tuner minimizes; negate higher-is-better metrics
        return sign * metric, r

    # seed the tuner with the explicit-grid results (convertObservations);
    # skip grid points outside the search range — scale_down would clip them
    # to the cube edge and attach a far-away point's metric to it
    observations = []
    for r in prior_results or []:
        if r.evaluation is None:
            continue
        native = _native_vec(r, names)
        if any(not (p.min <= v <= p.max) for p, v in zip(hp.params, native)):
            continue
        observations.append(
            Observation(
                candidate=hp.scale_down(native),
                value=sign * r.evaluation.primary_metric,
                artifact=r,
            )
        )

    tuner = get_tuner(args.hyper_parameter_tuning)
    tuner.search(
        args.hyper_parameter_tuning_iter,
        hp.dim,
        evaluate,
        observations=observations,
        discrete_params=hp.discrete_dims(),
        seed=0,
    )

    # record every (grid + tuned) observation as a reusable prior file
    priors = [
        (_native_vec(r, names), r.evaluation.primary_metric)
        for r in list(prior_results or []) + results
        if r.evaluation is not None
    ]
    from ..parallel import multihost

    if multihost.is_coordinator():
        os.makedirs(args.output_dir, exist_ok=True)
        with open(os.path.join(args.output_dir, "hyperparameter-prior.json"), "w") as f:
            f.write(prior_to_json(names, priors))
    return results


def _setup_checkpointing(args, coords, index_maps, initial_model, n_iterations):
    """Per-sweep checkpointing (crash recovery beyond the reference's
    model-granularity warm start): after every completed CD sweep the model
    lands in --checkpoint-dir/model-<k> and the state record flips to it
    ATOMICALLY (a crash mid-save leaves the state pointing at the previous
    intact model). Rerunning the same command warm-starts from the last
    completed sweep and trains only the remainder. Restricted to
    single-configuration runs (grids would need per-config state).

    With --validation-data, best-model tracking restarts at the resume point:
    pre-crash sweeps are no longer best-model candidates (the checkpoint
    stores last-sweep models, not the tracked best)."""
    grid_size = 1
    for cc in coords:
        grid_size *= max(len(cc.grid()), 1)
    if grid_size != 1:
        raise SystemExit(
            "--checkpoint-dir requires a single configuration (no reg-weight "
            "grids); tune weights first, then run the long job checkpointed"
        )
    if args.validation_data:
        logger.warning(
            "--checkpoint-dir with --validation-data: on resume, best-model "
            "tracking only sees post-resume sweeps (pre-crash candidates are "
            "not checkpointed)"
        )
    from ..parallel import multihost

    ckpt_dir = args.checkpoint_dir
    state_path = os.path.join(ckpt_dir, "checkpoint-state.json")
    expected = {cc.name: float(cc.grid()[0]) for cc in coords}

    completed = 0
    if os.path.exists(state_path):
        with open(state_path) as f:
            state = json.load(f)
        if state.get("reg_weights") != expected:
            raise SystemExit(
                f"checkpoint at {ckpt_dir} was written for config "
                f"{state.get('reg_weights')}, not {expected}; pass a fresh "
                "--checkpoint-dir"
            )
        completed = int(state.get("completed_sweeps", 0))
        if completed >= n_iterations:
            raise SystemExit(
                f"checkpoint at {ckpt_dir} already records {completed}/"
                f"{n_iterations} completed sweeps; the final model is in "
                f"{os.path.join(ckpt_dir, state.get('model_dir', 'model'))} "
                "(loadable via --model-input-dir). Pass a fresh "
                "--checkpoint-dir or more --coordinate-descent-iterations "
                "to train further."
            )
        if completed > 0:
            initial_model = load_game_model(
                os.path.join(ckpt_dir, state["model_dir"]), index_maps,
                task=args.task,
            )
            logger.info(
                "resuming from checkpoint: %d/%d sweeps done", completed,
                n_iterations,
            )
    remaining = n_iterations - completed

    def checkpoint_fn(reg_weights, iteration, game_model):
        if not multihost.is_coordinator():
            return
        k = completed + iteration + 1
        model_dir = f"model-{k:04d}"
        save_game_model(
            os.path.join(ckpt_dir, model_dir), game_model, index_maps,
            metadata={"regWeights": reg_weights},
        )
        with open(state_path + ".tmp", "w") as f:
            json.dump(
                {
                    "reg_weights": expected,
                    "completed_sweeps": k,
                    "model_dir": model_dir,
                },
                f,
            )
        os.replace(state_path + ".tmp", state_path)  # atomic flip
        # previous sweep's model is now unreferenced
        prev = os.path.join(ckpt_dir, f"model-{k - 1:04d}")
        if os.path.isdir(prev):
            import shutil

            shutil.rmtree(prev, ignore_errors=True)

    os.makedirs(ckpt_dir, exist_ok=True)
    return initial_model, remaining, checkpoint_fn


def _native_vec(result: GameResult, names: List[str]) -> np.ndarray:
    """GameResult -> native hyperparameter vector ordered by `names`
    (vectorizeParams semantics; names are '<coordinate>.reg_weight')."""
    return np.asarray(
        [result.config.get(n.removesuffix(".reg_weight"), 1.0) for n in names]
    )


def _build_tuning_config(args, tunable: List[str]) -> HyperparameterConfig:
    """Default per-coordinate log-λ ranges, optionally overridden by a JSON
    tuning config and shrunk around prior observations."""
    from ..tuning import config_from_json, get_bounds

    if args.hyper_parameter_config:
        with open(args.hyper_parameter_config) as f:
            _, hp = config_from_json(f.read())
        tunable_names = {f"{n}.reg_weight" for n in tunable}
        bad = [p.name for p in hp.params if p.name not in tunable_names]
        if bad:
            raise SystemExit(
                f"--hyper-parameter-config variables {bad} do not name tunable "
                f"coordinates; expected names among {sorted(tunable_names)}"
            )
    else:
        hp = HyperparameterConfig(
            params=[
                ParamRange(name=f"{n}.reg_weight", min=1e-4, max=1e4, transform="LOG")
                for n in tunable
            ]
        )
    if args.hyper_parameter_prior:
        import dataclasses as dc

        with open(args.hyper_parameter_prior) as f:
            lower, upper = get_bounds(
                hp,
                f.read(),
                radius=args.hyper_parameter_shrink_radius,
                higher_is_better=_higher_is_better(args.evaluators),
            )
        hp = HyperparameterConfig(
            params=[
                dc.replace(p, min=float(lo), max=float(hi))
                for p, lo, hi in zip(hp.params, lower, upper)
            ]
        )
    return hp


def _higher_is_better(evaluators: str) -> bool:
    from ..evaluation.evaluators import build_evaluator

    specs = [e for e in evaluators.split(",") if e]
    if not specs:
        return False
    return build_evaluator(specs[0]).higher_is_better


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
