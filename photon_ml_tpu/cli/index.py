"""Feature indexing driver: build partitioned immutable index stores.

Reference: photon-client .../index/FeatureIndexingDriver.scala:168-298 (§3.5):
extract distinct (name, term) per shard from data -> write hash-partitioned
off-heap stores (PalDB there; flat binary stores here) consumed at read time.
"""

from __future__ import annotations

import argparse
import logging
import sys
from typing import List, Optional

from ..io.avro import iter_avro_directory
from ..io.data import build_index_maps
from ..io.index_map import save_partitioned
from ..utils.logging import setup_logging
from .params import add_common_io_args, build_shard_configs, resolve_input_paths

logger = logging.getLogger("photon_ml_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu feature indexing driver")
    add_common_io_args(p)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--num-partitions", type=int, default=1)
    p.add_argument("--log-level", default="INFO")
    return p


def _input_paths(args):
    paths = resolve_input_paths(args)
    return [paths] if isinstance(paths, str) else paths


def run(argv: Optional[List[str]] = None):
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level)
    shards = build_shard_configs(args)
    records = [
        r
        for path in _input_paths(args)
        for r in iter_avro_directory(path)
    ]
    index_maps = build_index_maps(records, shards)
    for shard, imap in index_maps.items():
        save_partitioned(imap, args.output_dir, args.num_partitions, shard)
        logger.info("shard %s: %d features indexed", shard, len(imap))
    return index_maps


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
