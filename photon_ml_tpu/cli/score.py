"""GAME scoring driver.

Reference: photon-client .../cli/game/scoring/GameScoringDriver.scala:25-284
(§3.2): read data -> load GAME model -> GameTransformer.transform -> optional
evaluation -> write ScoringResultAvro records.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional

import numpy as np

from ..io import read_avro_dataset
from ..io.avro import write_avro_file
from ..io.index_map import load_partitioned
from ..io.model_io import load_game_model
from ..io.schemas import SCORING_RESULT_AVRO
from ..utils.logging import setup_logging
from .params import (
    add_common_io_args,
    build_shard_configs,
    parse_input_columns,
    plan_host_row_split,
    resolve_input_paths,
)

logger = logging.getLogger("photon_ml_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu game scoring driver")
    add_common_io_args(p)
    p.add_argument("--model-input-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default=None, help="override model task type")
    p.add_argument("--evaluators", default="")
    p.add_argument("--model-id", default="", help="modelId stamped on score records")
    p.add_argument(
        "--distributed",
        default=None,
        help="multi-host: 'coordinator=HOST:PORT,process=I,n=P' (or 'auto'); "
        "each process scores its own row range and writes its own part file; "
        "evaluation metrics are computed globally on process 0",
    )
    p.add_argument("--log-file", default=None)
    p.add_argument("--log-level", default="INFO")
    return p


def run(argv: Optional[List[str]] = None):
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, args.log_file)

    from ..utils.compile_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    from ..parallel import multihost

    if args.distributed:
        if args.distributed == "auto":
            multihost.initialize()
        else:
            multihost.initialize_from_spec(args.distributed)

    shards = build_shard_configs(args)
    id_tags = [t for t in args.id_tags.split(",") if t]

    index_maps = None
    if args.feature_index_dir:
        index_maps = {s: load_partitioned(args.feature_index_dir, s) for s in shards}
    input_paths = resolve_input_paths(args)

    # distributed scoring is embarrassingly parallel (GameScoringDriver.scala:
    # 25-284 scores per executor partition): each process reads and scores its
    # own row range — no cross-host exchange until evaluation
    if multihost.process_count() > 1 and index_maps is None:
        raise SystemExit(
            "multi-process scoring requires --feature-index-dir "
            "(host-local index maps would disagree across hosts)"
        )
    row_range, part_counts = plan_host_row_split(input_paths)
    if row_range is not None:
        logger.info(
            "process %d scores rows [%d, %d)",
            multihost.process_index(), row_range[0], row_range[1],
        )
    raw, index_maps = read_avro_dataset(
        input_paths,
        shards,
        index_maps=index_maps,
        id_tag_columns=id_tags,
        response_column=args.response_column,
        columns=parse_input_columns(args),
        row_range=row_range,
        part_counts=part_counts,
    )
    model = load_game_model(args.model_input_dir, index_maps, task=args.task)
    # random-effect types must be available as id tags
    missing = [
        m.random_effect_type
        for m in model.models.values()
        if hasattr(m, "random_effect_type") and m.random_effect_type not in raw.id_tags
    ]
    if missing:
        raise SystemExit(
            f"model needs id tags {missing}; pass --id-tags {','.join(missing)}"
        )

    # the same compiled score assembly the resident service keeps warm
    # (serving/engine.py) — batch and resident scores are bitwise-identical
    from ..serving.engine import ScoreEngine

    evaluators = [e for e in args.evaluators.split(",") if e]
    multiprocess = multihost.process_count() > 1
    scores = ScoreEngine.from_model(model).score_dataset(raw)
    evaluation = None
    # multi-process: score locally, evaluate globally below
    if evaluators and not multiprocess:
        from ..evaluation.suite import build_suite

        suite = build_suite(evaluators, raw.labels, raw.weights, id_tags=raw.id_tags)
        evaluation = suite.evaluate(scores)

    if multiprocess and evaluators:
        # global metrics need every host's (score, label, weight, tags):
        # allgather the scored columns — bytes-per-row, not features — and
        # evaluate the full set identically on every process
        parts = multihost.allgather_object(
            (scores, raw.labels, raw.weights,
             {t: raw.id_tags[t] for t in raw.id_tags})
        )
        all_scores = np.concatenate([p[0] for p in parts])
        all_labels = np.concatenate([p[1] for p in parts])
        all_weights = np.concatenate([p[2] for p in parts])
        all_tags = {
            t: np.concatenate([p[3][t] for p in parts]) for t in raw.id_tags
        }
        from ..evaluation.suite import build_suite

        suite = build_suite(evaluators, all_labels, all_weights, id_tags=all_tags)
        evaluation = suite.evaluate(all_scores)

    os.makedirs(args.output_dir, exist_ok=True)

    def records():
        for i in range(raw.n_rows):
            yield {
                "uid": None if raw.uids is None or raw.uids[i] is None else str(raw.uids[i]),
                "label": float(raw.labels[i]),
                "modelId": args.model_id,
                "predictionScore": float(scores[i]),
                "weight": float(raw.weights[i]),
                "metadataMap": None,
            }

    part_name = (
        f"scores-part-{multihost.process_index():04d}.avro"
        if multiprocess
        else "scores.avro"
    )
    write_avro_file(
        os.path.join(args.output_dir, part_name), SCORING_RESULT_AVRO, records()
    )
    if evaluation is not None and multihost.is_coordinator():
        with open(os.path.join(args.output_dir, "evaluation.json"), "w") as f:
            json.dump(evaluation.metrics, f, indent=2, default=float)
        logger.info("evaluation: %s", evaluation.metrics)
    logger.info("wrote %d scores to %s", raw.n_rows, args.output_dir)
    return scores, evaluation


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
