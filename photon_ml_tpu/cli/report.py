"""Post-hoc run-report builder.

Rebuilds the training report (report.json + self-contained report.html) from
a directory of run artifacts — run_summary.json, metrics.jsonl,
training-summary.json, saved models, feature-index metadata, boundary
checkpoint MANIFESTs, bench --progress-out JSONL, flight-recorder
postmortems (flight-<kind>-<seq>.json). No jax, no accelerator
stack: the whole path is jax-free (lint rule R8), so this runs on a dev box
against artifacts rsynced off a training host.

Usage:
  python -m photon_ml_tpu.cli.report ARTIFACTS_DIR [--out DIR]
      [--bench-baseline OLD.json --bench-candidate NEW.json] [--top-k N]

``cli train --report-out`` emits the same report at end of run through the
same discover/build code path, which is what makes the rebuild identical.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import List, Optional

from ..obs import report as report_mod
from ..utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu run-report builder")
    p.add_argument(
        "artifacts_dir",
        help="directory walked for run artifacts (run_summary.json, "
        "metrics.jsonl, saved models, checkpoint manifests, ...)",
    )
    p.add_argument(
        "--out",
        default=None,
        help="output directory for report.json + report.html "
        "(default: <artifacts-dir>/report)",
    )
    p.add_argument(
        "--top-k",
        type=int,
        default=20,
        help="features per coordinate in the top-|weight| table",
    )
    p.add_argument(
        "--bench-baseline",
        default=None,
        help="BENCH json record to diff --bench-candidate against "
        "(per-series deltas land in the report's bench section)",
    )
    p.add_argument(
        "--bench-candidate",
        default=None,
        help="BENCH json record measured by this run (requires "
        "--bench-baseline)",
    )
    p.add_argument("--log-level", default="INFO")
    return p


def run(argv: Optional[List[str]] = None) -> dict:
    import os

    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, None)
    if bool(args.bench_baseline) != bool(args.bench_candidate):
        raise SystemExit(
            "--bench-baseline and --bench-candidate must be given together"
        )

    inputs = report_mod.discover(args.artifacts_dir)
    if (
        inputs.run_summary is None
        and inputs.training_summary is None
        and not inputs.model_dirs
    ):
        raise SystemExit(
            f"no run artifacts found under {args.artifacts_dir} (expected at "
            "least one of run_summary.json / training-summary.json / a saved "
            "model directory)"
        )
    doc = report_mod.build_report(inputs, top_k=args.top_k)
    if args.bench_baseline:
        with open(args.bench_baseline, encoding="utf-8") as f:
            old = json.load(f)
        with open(args.bench_candidate, encoding="utf-8") as f:
            new = json.load(f)
        doc["bench"]["diff"] = report_mod.bench_diff(old, new)

    out_dir = args.out or os.path.join(args.artifacts_dir, "report")
    paths = report_mod.write_report(doc, out_dir)
    logger.info("report -> %s (html: %s)", paths["json"], paths["html"])
    return doc


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
