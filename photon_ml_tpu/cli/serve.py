"""Resident GLMix scoring service driver.

The serving-side complement of the batch scorer (``cli/score.py``): open a
published mmap snapshot (or publish one first from an Avro GAME model dir),
keep the score kernels warm, microbatch requests, and flip to newly
published snapshots without dropping traffic (see ``serving/``).

Typical flow::

    # one-time (and per retrain): flatten the Avro model into a snapshot
    python -m photon_ml_tpu.cli.serve --serving-root out/serving \
        --publish-model out/models/best --feature-index-dir out/index \
        --snapshot-name v1 --publish-only

    # resident server over an AF_UNIX socket
    python -m photon_ml_tpu.cli.serve --serving-root out/serving \
        --socket /tmp/photon-serve.sock --metrics-out out/serving-metrics

    # ... or a TCP listener, with a 50ms deadline budget on every request
    python -m photon_ml_tpu.cli.serve --serving-root out/serving \
        --listen 127.0.0.1:8473 --default-deadline-ms 50

    # multi-model residency: one process, one bulkhead per model, routed
    # by the request protocol's model= field (per-market GAME model sets)
    python -m photon_ml_tpu.cli.serve --models jobs-us=out/serving-us \
        --models jobs-emea=out/serving-emea --default-model jobs-us \
        --listen 127.0.0.1:8473

    # ... or discover the resident set from one fleet root (each subdir a
    # serving root or bare store): --fleet-root out/fleet

    # the replica front: N `cli serve --listen` replicas behind one address,
    # least-loaded routing + /healthz draining + mid-request failover
    python -m photon_ml_tpu.cli.serve --front 127.0.0.1:8473 \
        --front 127.0.0.1:8474 --listen 127.0.0.1:9000

Overload posture: the admission controller sheds requests that cannot meet
their deadline budget (``--default-deadline-ms``, or per-request
``deadline_ms`` on the socket) or that meet a full pending queue
(``--max-pending``); ``--overload-shed-threshold`` wires the shed rate into
``/healthz`` so a balancer can route around a saturated replica — the
``--front`` process polls exactly that endpoint (``--front-healthz``).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading
from typing import List, Optional

from ..io.index_map import load_partitioned
from ..utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu")


def check_socket_front(socket_path, listen) -> None:
    """One socket front per server process: AF_UNIX or TCP, not both."""
    if socket_path and listen:
        raise ValueError(
            "pass at most one of --socket / --listen (one socket front per "
            "server process)"
        )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu resident scoring service")
    p.add_argument(
        "--serving-root",
        default=None,
        help="published-snapshot root (CURRENT + snapshots/); enables "
        "zero-downtime refresh when new snapshots are published",
    )
    p.add_argument(
        "--store-dir",
        default=None,
        help="serve one fixed mmap store directly (no refresh watching)",
    )
    p.add_argument(
        "--models",
        action="append",
        default=None,
        metavar="NAME=PATH",
        help="resident model NAME served from PATH (a serving root or a "
        "bare store dir); repeat for multi-model residency — each model "
        "gets its own bulkhead (batcher + refresh watcher) and requests "
        "route by the protocol's model= field",
    )
    p.add_argument(
        "--fleet-root",
        default=None,
        help="directory whose subdirectories are the resident models "
        "(each a serving root or bare store dir) — shorthand for one "
        "--models entry per subdir",
    )
    p.add_argument(
        "--default-model",
        default=None,
        help="model served to requests that carry no model= field "
        "(default: the single resident model, or 'default')",
    )
    p.add_argument(
        "--front",
        action="append",
        default=None,
        metavar="HOST:PORT",
        help="run as the least-loaded replica front instead of a scoring "
        "server: repeat once per replica --listen address; requests route "
        "to the live replica with the fewest in flight and fail over "
        "(same trace_id) when a replica dies mid-request",
    )
    p.add_argument(
        "--front-healthz",
        action="append",
        default=None,
        metavar="HOST:PORT",
        help="per-replica introspection address (parallel to --front): the "
        "front drains a replica whose /healthz answers 503",
    )
    p.add_argument(
        "--front-connections",
        type=int,
        default=1,
        metavar="K",
        help="connections the front opens to each replica (default 1): the "
        "JSON-lines protocol answers in order per connection, so K is the "
        "front's concurrency into one replica — raise it so the replica's "
        "microbatcher sees enough in-flight requests to fill batches",
    )
    p.add_argument(
        "--publish-model",
        default=None,
        help="Avro GAME model dir to flatten + publish into --serving-root "
        "before serving (requires --feature-index-dir)",
    )
    p.add_argument("--feature-index-dir", default=None)
    p.add_argument("--snapshot-name", default="v1")
    p.add_argument("--task", default=None, help="override model task type")
    p.add_argument(
        "--publish-only",
        action="store_true",
        help="publish the snapshot and exit without serving",
    )
    p.add_argument("--socket", default=None, help="AF_UNIX socket path to serve on")
    p.add_argument(
        "--listen",
        default=None,
        help="TCP host:port to serve on (same JSON-lines protocol as "
        "--socket; port 0 binds ephemeral)",
    )
    p.add_argument("--max-batch", type=int, default=256)
    p.add_argument("--max-latency-ms", type=float, default=2.0)
    p.add_argument(
        "--max-pending",
        type=int,
        default=1024,
        help="admission queue bound; submits against a full queue are shed "
        "with reason queue_full",
    )
    p.add_argument(
        "--default-deadline-ms",
        type=float,
        default=None,
        help="deadline budget applied to requests that don't carry their own "
        "deadline_ms; requests that cannot meet it are shed immediately",
    )
    p.add_argument(
        "--overload-shed-threshold",
        type=float,
        default=None,
        help="sheds/second above which /healthz answers 503 "
        '{"status": "overloaded"} (needs --status-port)',
    )
    p.add_argument("--poll-seconds", type=float, default=0.2)
    p.add_argument(
        "--replica-id",
        default=None,
        help="this replica's identity in an N-replica serving fleet; stamped "
        "on every metric line, span and response trace, and used as the "
        "process lane in fleet-merged timelines (an integer id also sets "
        "the obs process index)",
    )
    p.add_argument(
        "--slow-request-ms",
        type=float,
        default=None,
        help="log a warning (with trace_id and per-stage breakdown) and "
        "count photon_serving_slow_requests_total for completed requests "
        "slower than this threshold",
    )
    p.add_argument(
        "--metrics-out",
        default=None,
        help="directory for telemetry: the Prometheus exposition and the "
        "metrics.jsonl span/metric stream (fleet-mergeable via cli fleetz), "
        "plus flight-recorder postmortems under flight/",
    )
    p.add_argument(
        "--status-port",
        type=int,
        default=None,
        help="serve live /metrics, /healthz and /statusz (request QPS, "
        "latency p50/p95/p99, live snapshot name) on this port while "
        "resident (0 = ephemeral port)",
    )
    p.add_argument("--log-file", default=None)
    p.add_argument("--log-level", default="INFO")
    return p


def run(argv: Optional[List[str]] = None, stop_event=None):
    args = build_parser().parse_args(argv)
    check_socket_front(args.socket, args.listen)
    setup_logging(args.log_level, args.log_file)
    from ..utils.compile_cache import enable_persistent_compilation_cache

    enable_persistent_compilation_cache()

    from .. import obs, serving
    from ..robust import faults

    # PHOTON_FAULTS reaches the serving sites (serving.score /
    # serving.refresh) the same way it reaches training: the chaos drills
    # run against the real CLI entrypoint
    faults.install_from_env()

    if args.publish_model:
        if not args.serving_root:
            raise SystemExit("--publish-model requires --serving-root")
        if not args.feature_index_dir:
            raise SystemExit("--publish-model requires --feature-index-dir")
        shards = serving.discover_shards(args.publish_model)
        index_maps = {
            s: load_partitioned(args.feature_index_dir, s) for s in shards
        }
        path = serving.publish_snapshot(
            args.serving_root,
            args.snapshot_name,
            model_dir=args.publish_model,
            index_maps=index_maps,
            task=args.task,
        )
        logger.info("published snapshot %s", path)
        if args.publish_only:
            return None

    modes = (
        args.serving_root,
        args.store_dir,
        args.models,
        args.fleet_root,
        args.front,
    )
    if sum(bool(m) for m in modes) != 1:
        raise SystemExit(
            "pass exactly one of --serving-root / --store-dir / --models / "
            "--fleet-root / --front"
        )
    if args.front and not (args.socket or args.listen):
        raise SystemExit(
            "--front needs --socket or --listen (the fleet's one client "
            "address)"
        )
    if args.front_healthz and (
        not args.front or len(args.front_healthz) != len(args.front)
    ):
        raise SystemExit("--front-healthz entries must parallel --front")
    model_pairs = None
    if args.models:
        # kept as (name, path) PAIRS, not a dict: a duplicate NAME must
        # reach plan.check_fleet_composition's typed refusal, not be
        # silently last-writer-wins'd by dict construction
        model_pairs = []
        for spec in args.models:
            name, sep, path = spec.partition("=")
            if not sep or not name or not path:
                raise SystemExit(f"--models takes NAME=PATH (got {spec!r})")
            model_pairs.append((name, path))

    # fleet identity BEFORE any sink/span exists, so every line carries it
    if args.replica_id is not None:
        obs.set_replica_id(args.replica_id)
        try:
            # an integer replica id doubles as the trace/JSONL process lane
            obs.set_process_index(int(args.replica_id))
        except ValueError:
            pass  # non-numeric replica names keep lane 0; the replica
            # label still disambiguates fleet-merged series

    run_ctx = obs.RunTelemetry()
    obs.record_build_info(run_ctx.registry)
    flight = None
    if args.metrics_out:
        os.makedirs(args.metrics_out, exist_ok=True)
        run_ctx.register_listener(
            obs.PrometheusSink(os.path.join(args.metrics_out, "metrics.prom"))
        )
        # the JSONL stream is what cli fleetz merges and stitches: every
        # span (serving.request + per-stage) and the final metrics snapshot
        run_ctx.register_listener(
            obs.JsonlSink(os.path.join(args.metrics_out, "metrics.jsonl"))
        )
        # anomaly-triggered postmortems: a shed-rate spike past
        # --overload-shed-threshold dumps the last window of spans/metrics
        flight = obs.FlightRecorder(
            os.path.join(args.metrics_out, "flight"),
            run=run_ctx,
            shed_rate_threshold=args.overload_shed_threshold,
        )
        run_ctx.register_listener(flight)
    with obs.use_run(run_ctx):
        if args.front:
            front = serving.LeastLoadedFront(
                args.front,
                healthz=args.front_healthz,
                connections_per_replica=args.front_connections,
            )
            logger.info(
                "replica front over %s (socket=%s listen=%s)",
                args.front, args.socket, args.listen,
            )
            try:
                serving.serve_front_socket(
                    front,
                    path=args.socket,
                    listen=args.listen,
                    stop_event=stop_event,
                    on_bound=lambda b: logger.info("front bound: %s", b),
                )
            finally:
                front.close()
                run_ctx.close()
            return None
        admission = dict(
            max_pending=args.max_pending,
            default_deadline_ms=args.default_deadline_ms,
            overload_shed_threshold=args.overload_shed_threshold,
            slow_request_ms=args.slow_request_ms,
        )
        if args.serving_root:
            server = serving.ScoringServer(
                serving_root=args.serving_root,
                max_batch=args.max_batch,
                max_latency_ms=args.max_latency_ms,
                poll_seconds=args.poll_seconds,
                status_port=args.status_port,
                **admission,
            )
        elif args.store_dir:
            server = serving.ScoringServer(
                store=serving.ModelStore.open(args.store_dir),
                max_batch=args.max_batch,
                max_latency_ms=args.max_latency_ms,
                status_port=args.status_port,
                **admission,
            )
        else:
            server = serving.ScoringServer(
                models=model_pairs,
                fleet_root=args.fleet_root,
                default_model=args.default_model,
                max_batch=args.max_batch,
                max_latency_ms=args.max_latency_ms,
                poll_seconds=args.poll_seconds,
                status_port=args.status_port,
                **admission,
            )
        logger.info(
            "serving snapshots %s (socket=%s listen=%s)",
            server.snapshot_names, args.socket, args.listen,
        )
        if server.status_port is not None:
            logger.info(
                "introspection endpoints -> http://127.0.0.1:%d/{metrics,"
                "healthz,statusz}", server.status_port,
            )
        try:
            if args.socket or args.listen:
                serving.serve_socket(
                    server,
                    path=args.socket,
                    listen=args.listen,
                    stop_event=stop_event,
                    on_bound=lambda b: logger.info("socket front bound: %s", b),
                )
            elif stop_event is not None:
                stop_event.wait()
            else:
                threading.Event().wait()  # resident until killed
        finally:
            server.close()
            run_ctx.close()  # final flush: the p50/p95/p99 exposition
    return None


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
