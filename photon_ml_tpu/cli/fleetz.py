"""Fleet observability front: merge per-process telemetry into one view.

A ``--config scale`` training run (or an N-replica serving fleet) leaves K
per-process ``metrics*.jsonl`` streams on disk and/or K live ``/metrics``
endpoints. This driver folds them into ONE exposition — counters summed,
gauges kept per process under ``process=``/``replica=`` labels, histogram
buckets merged, summaries recombined exactly — and stitches the K span
streams into a single Chrome-trace timeline aligned on the shared wall
clock (see ``obs.fleet``).

One-shot merge (prints the fleet exposition)::

    python -m photon_ml_tpu.cli.fleetz out/metrics

Artifact mode (fleet.prom + fleet_trace.json + fleet_summary.json)::

    python -m photon_ml_tpu.cli.fleetz out/metrics --out out/fleet

Live aggregator front over running processes (the harness scrapes this one
endpoint instead of K)::

    python -m photon_ml_tpu.cli.fleetz \
        --scrape http://127.0.0.1:9601 --scrape http://127.0.0.1:9602 \
        --serve-port 9700

This module is jax-free by design (lint R8): the aggregator must run on a
host with no accelerator runtime — a monitoring sidecar, a laptop reading
artifacts off a finished run.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading
from typing import List, Optional

from ..obs import fleet
from ..robust.atomic import atomic_write_json, atomic_write_text
from ..utils.logging import setup_logging

logger = logging.getLogger("photon_ml_tpu")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("photon-ml-tpu fleet telemetry aggregator")
    p.add_argument(
        "paths",
        nargs="*",
        help="metrics.jsonl files and/or telemetry directories (a directory "
        "contributes every metrics*.jsonl inside it — the per-process "
        "layout cli train writes)",
    )
    p.add_argument(
        "--scrape",
        action="append",
        default=[],
        metavar="URL",
        help="live /metrics endpoint to scrape and merge (repeatable; one "
        "per process or serving replica)",
    )
    p.add_argument(
        "--out",
        default=None,
        help="write fleet.prom (merged exposition), fleet_trace.json "
        "(stitched Chrome trace) and fleet_summary.json (fleet statusz "
        "document) into this directory",
    )
    p.add_argument(
        "--serve-port",
        type=int,
        default=None,
        help="stay resident and serve the merged /metrics, /statusz and "
        "/healthz on this port (0 = ephemeral); live targets are "
        "re-scraped on every GET",
    )
    p.add_argument(
        "--scrape-timeout",
        type=float,
        default=2.0,
        help="per-target scrape timeout in seconds",
    )
    p.add_argument("--log-file", default=None)
    p.add_argument("--log-level", default="INFO")
    return p


def run(argv: Optional[List[str]] = None, stop_event=None):
    args = build_parser().parse_args(argv)
    setup_logging(args.log_level, args.log_file)
    if not args.paths and not args.scrape:
        raise SystemExit(
            "nothing to aggregate: pass metrics.jsonl paths/directories "
            "and/or --scrape URLs"
        )

    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        raise SystemExit(f"no such file or directory: {', '.join(missing)}")

    agg = fleet.FleetAggregator(
        targets=args.scrape, timeout_s=args.scrape_timeout
    )
    streams = fleet.discover_streams(args.paths)
    if args.paths and not streams:
        raise SystemExit(
            f"no metrics*.jsonl streams found under: {', '.join(args.paths)}"
        )
    agg.add_streams(streams)
    if args.scrape:
        n = agg.scrape_once()
        logger.info("scraped %d/%d live targets", n, len(args.scrape))

    doc = None
    if args.out:
        os.makedirs(args.out, exist_ok=True)
        atomic_write_text(os.path.join(args.out, "fleet.prom"), agg.render())
        trace = fleet.stitch_spans(streams)
        atomic_write_json(
            os.path.join(args.out, "fleet_trace.json"), trace, default=str
        )
        doc = agg.statusz()
        atomic_write_json(
            os.path.join(args.out, "fleet_summary.json"),
            doc, indent=2, default=str,
        )
        n_spans = sum(len(s.spans) for s in streams)
        logger.info(
            "fleet artifacts -> %s (%d stream(s), %d span(s) stitched)",
            args.out, len(streams), n_spans,
        )

    if args.serve_port is not None:
        front = fleet.FleetServer(agg, port=args.serve_port)
        logger.info(
            "fleet aggregator front -> http://127.0.0.1:%d/{metrics,"
            "statusz,healthz}", front.port,
        )
        try:
            if stop_event is not None:
                stop_event.wait()
            else:
                threading.Event().wait()  # resident until killed
        finally:
            front.stop()
        return front.port

    if not args.out:
        # one-shot mode: the merged exposition on stdout, exactly what a
        # scrape of the resident front would return
        sys.stdout.write(agg.render())
        return None
    return doc


def main():
    run(sys.argv[1:])


if __name__ == "__main__":
    main()
