"""GameEstimator: the fit() API over GAME coordinate configurations.

Reference: photon-api .../estimators/GameEstimator.scala:53-705 —
fit(data, validationData, optimizationConfigurations) prepares per-coordinate
datasets once, builds the validation evaluation suite, then runs coordinate
descent once per optimization configuration, warm-starting each run from the
previous configuration's model (:356-374), returning one GameResult per
configuration. Regularization-weight grids expand as a cartesian product over
coordinates (GameTrainingDriver.prepareGameOptConfigs:623-632).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..evaluation.suite import EvaluationResults, build_suite
from ..game.coordinate import (
    Coordinate,
    FixedEffectCoordinate,
    ModelCoordinate,
    RandomEffectCoordinate,
)
from ..game.data import (
    build_fixed_effect_dataset,
    build_random_effect_dataset,
)
from ..game.descent import CoordinateDescent, ValidationContext
from ..game.problem import GLMOptimizationConfig
from ..io.data import RawDataset
from ..models.game import GameModel
from ..ops.normalization import NormalizationContext
from .. import plan as execution_plan
from ..utils.events import (
    EventEmitter,
    OptimizationLogEvent,
    TrainingFinishEvent,
    TrainingStartEvent,
)
from ..utils.timed import timed

logger = logging.getLogger("photon_ml_tpu")


@dataclasses.dataclass
class CoordinateConfig:
    """One coordinate's dataset + optimization definition (the reference's
    CoordinateConfiguration: dataset config + optimization config + reg grid)."""

    name: str
    feature_shard: str
    config: GLMOptimizationConfig
    random_effect_type: Optional[str] = None  # None => fixed effect
    reg_weights: Sequence[float] = ()  # grid; empty -> [config.reg_weight]
    active_cap: Optional[int] = None
    active_lower_bound: int = 1
    # Pearson feature selection: keep ceil(ratio * n_rows) features per entity
    # (numFeaturesToSamplesRatioUpperBound, RandomEffectDataset.scala:553-565)
    features_to_samples_ratio: Optional[float] = None
    # fixed-effect batch layout: auto|dense|ell|coo|tiled ('tiled' shards the
    # coefficient dim over the estimator mesh's model axis — the huge-d path)
    layout: str = "auto"
    # optional narrower storage type for the dense feature matrix only (e.g.
    # jnp.bfloat16: halves the HBM traffic of the bandwidth-bound objective
    # sweeps; labels/offsets/weights/solver state stay in estimator dtype)
    feature_dtype: Optional[object] = None
    normalization: Optional[NormalizationContext] = None
    # incremental training: L2-regularize toward the warm-start model
    # ("Regularize by Previous Model During Warm-Start Training")
    regularize_by_prior: bool = False
    # out-of-core coordinates: when the coordinate's device data would exceed
    # this device-memory budget, keep it host-resident and stream
    # double-buffered slices through the chip (the reference's DISK_ONLY
    # spill scale path). Random effects stream entity slices
    # (game/streaming.py); fixed effects stream row slices
    # (game/fe_streaming.py — layouts auto|dense|ell, variance NONE, no
    # down-sampling). Composes with a mesh / multi-process: each host
    # streams its own shard under the per-host budget (plan/planner.py).
    hbm_budget_mb: Optional[int] = None

    @property
    def is_random_effect(self) -> bool:
        return self.random_effect_type is not None

    def grid(self) -> Sequence[float]:
        return tuple(self.reg_weights) or (self.config.reg_weight,)


@dataclasses.dataclass
class GameResult:
    model: GameModel
    config: Dict[str, float]  # coordinate -> reg weight
    evaluation: Optional[EvaluationResults]
    trackers: Dict[str, object]


class GameEstimator(EventEmitter):
    """Emits TrainingStart/OptimizationLog/TrainingFinish events to registered
    listeners (EventEmitter.scala semantics; the reference's telemetry hook)."""

    def __init__(
        self,
        task: str,
        coordinate_configs: Sequence[CoordinateConfig],
        n_cd_iterations: int = 1,
        evaluator_specs: Sequence[str] = (),
        dtype=jnp.float32,
        partial_retrain_locked: Sequence[str] = (),
        entity_pad_multiple: int = 1,
        mesh=None,
        validation_frequency: str = "COORDINATE",
        divergence_guard: bool = True,
        rejection_tolerance: Optional[float] = None,
        pipeline_depth: int = 1,
    ):
        super().__init__()
        if not coordinate_configs:
            raise ValueError("need at least one coordinate configuration")
        names = [c.name for c in coordinate_configs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate coordinate names: {names}")
        self.task = task
        self.coordinate_configs = list(coordinate_configs)
        self.n_cd_iterations = n_cd_iterations
        self.evaluator_specs = list(evaluator_specs)
        self.dtype = dtype
        self.partial_retrain_locked = set(partial_retrain_locked)
        self.mesh = mesh
        self.validation_frequency = validation_frequency
        # numerical-divergence defense knobs, passed straight through to
        # CoordinateDescent (see game/descent.py for semantics)
        self.divergence_guard = divergence_guard
        self.rejection_tolerance = rejection_tolerance
        # sweep pipelining depth (game/pipeline.py): 1 = serial; >= 2 runs
        # eval on a background lane and lets the streamed paths prefetch
        if pipeline_depth < 1:
            raise ValueError(f"pipeline_depth must be >= 1: {pipeline_depth}")
        self.pipeline_depth = int(pipeline_depth)
        if mesh is not None and entity_pad_multiple == 1:
            # entity blocks shard over the data axis: pad to its size
            from ..parallel.mesh import DATA_AXIS

            entity_pad_multiple = mesh.shape[DATA_AXIS]
        self.entity_pad_multiple = entity_pad_multiple
        unknown = self.partial_retrain_locked - set(names)
        if unknown:
            raise ValueError(f"locked coordinates not in configs: {sorted(unknown)}")
        # ALL composition legality (layout x dtype x mesh x streaming x
        # pipelining) is the execution planner's: one resolve up front
        # replaces the per-knob checks that used to live here, and the
        # resolved plan stays introspectable for --explain-plan /
        # run_summary.json (plan/planner.py). Refusals raise PlanError (a
        # ValueError) with the ledger-pinned messages.
        # Notes the planner's routing table encodes:
        # - normalization works on tiled: GLMProblem pads the stats vectors
        #   to the mesh-padded dim with identity entries (the reference
        #   algebra is layout-agnostic, ValueAndGradientAggregator.scala)
        # - variance=FULL is supported on tiled via the chunked sharded
        #   X^T diag(c) X path (parallel/sparse.py xtcx) up to
        #   ops.glm.MAX_FULL_VARIANCE_DIM; the dim ceiling is checked at
        #   train time when d is known
        import jax

        self.execution_plan = execution_plan.resolve(
            self.coordinate_configs,
            mesh=mesh,
            n_processes=jax.process_count(),
            pipeline_depth=self.pipeline_depth,
            partial_retrain_locked=tuple(self.partial_retrain_locked),
        )

    # -- dataset preparation -------------------------------------------------

    def _prepare_datasets(self, raw: RawDataset):
        import jax

        multiprocess = jax.process_count() > 1
        # re-checked here (not just at __init__) because process topology can
        # be initialized between estimator construction and the first fit
        execution_plan.check_multiprocess_mesh(jax.process_count(), self.mesh)
        datasets = {}
        for cc in self.coordinate_configs:
            with timed(f"prepare dataset {cc.name}"):
                if cc.is_random_effect:
                    if multiprocess:
                        # entity planning across hosts + device-side shuffle
                        # (game/data_mp.py; the reference's partitioner+
                        # partitionBy pipeline)
                        from ..game.data_mp import build_random_effect_dataset_global

                        ds = build_random_effect_dataset_global(
                            raw,
                            cc.name,
                            cc.feature_shard,
                            cc.random_effect_type,
                            mesh=self.mesh,
                            active_cap=cc.active_cap,
                            active_lower_bound=cc.active_lower_bound,
                            dtype=self.dtype,
                            pad_entities_to_multiple=self.entity_pad_multiple,
                            features_to_samples_ratio=cc.features_to_samples_ratio,
                            feature_dtype=cc.feature_dtype,
                            hbm_budget_bytes=(
                                cc.hbm_budget_mb * (1 << 20)
                                if cc.hbm_budget_mb is not None
                                else None
                            ),
                        )
                        datasets[cc.name] = ds
                        continue
                    ds = build_random_effect_dataset(
                        raw,
                        cc.name,
                        cc.feature_shard,
                        cc.random_effect_type,
                        active_cap=cc.active_cap,
                        active_lower_bound=cc.active_lower_bound,
                        dtype=self.dtype,
                        pad_entities_to_multiple=self.entity_pad_multiple,
                        features_to_samples_ratio=cc.features_to_samples_ratio,
                        feature_dtype=cc.feature_dtype,
                        hbm_budget_bytes=(
                            cc.hbm_budget_mb * (1 << 20)
                            if cc.hbm_budget_mb is not None
                            else None
                        ),
                    )
                    if self.mesh is not None and not ds.streamed:
                        # streamed blocks are host-resident by design: they
                        # stream through the chip in slices, so there is
                        # nothing to place on the mesh
                        from ..parallel.mesh import shard_entity_blocks

                        ds = dataclasses.replace(
                            ds, blocks=shard_entity_blocks(ds.blocks, self.mesh)
                        )
                    datasets[cc.name] = ds
                else:
                    ds = build_fixed_effect_dataset(
                        raw,
                        cc.name,
                        cc.feature_shard,
                        dtype=self.dtype,
                        layout=cc.layout,
                        mesh=self.mesh,
                        feature_dtype=cc.feature_dtype,
                        hbm_budget_bytes=(
                            cc.hbm_budget_mb * (1 << 20)
                            if cc.hbm_budget_mb is not None
                            else None
                        ),
                    )
                    if ds.streamed:
                        datasets[cc.name] = ds
                        continue
                    if self.mesh is not None and cc.layout != "tiled":
                        from ..parallel.mesh import shard_batch

                        ds = dataclasses.replace(
                            ds, batch=shard_batch(ds.batch, self.mesh)
                        )
                    if multiprocess:
                        # multi-process sample space is the padded GLOBAL row
                        # space: scores/residuals stay [N_global], no trimming
                        ds = dataclasses.replace(ds, true_n_rows=ds.batch.n_rows)
                    datasets[cc.name] = ds
        return datasets

    def _validation_context(
        self, val_raw: RawDataset
    ) -> Tuple[ValidationContext, Dict[str, object]]:
        suite = build_suite(
            self.evaluator_specs or ["RMSE"],
            val_raw.labels,
            val_raw.weights,
            id_tags=val_raw.id_tags,
        )
        # per-coordinate validation scoring closures
        from ..game.data import _rows_to_ell  # host helper

        score_fns = {}
        for cc in self.coordinate_configs:
            rows, cols, vals = val_raw.shard_coo[cc.feature_shard]
            if cc.is_random_effect:
                idx, val = _rows_to_ell(rows, cols, vals, val_raw.n_rows)
                ids = val_raw.id_tags[cc.random_effect_type]
                idx_j = jnp.asarray(idx)
                val_j = jnp.asarray(val, self.dtype)

                def fn(model, _ids=ids, _idx=idx_j, _val=val_j):
                    erow = jnp.asarray(model.rows_for(_ids).astype(np.int32))
                    return model.score_ell_rows(erow, _idx, _val)

            else:
                batch = val_raw.to_batch(cc.feature_shard, dtype=self.dtype)

                def fn(model, _batch=batch):
                    return _batch.features.matvec(model.model.coefficients.means)

            score_fns[cc.name] = fn
        return (
            ValidationContext(suite=suite, score_fns=score_fns, offsets=val_raw.offsets),
            score_fns,
        )

    def _make_coordinates(
        self,
        datasets,
        reg_weights: Mapping[str, float],
        initial_models: Mapping[str, object],
    ) -> Dict[str, Coordinate]:
        coords: Dict[str, Coordinate] = {}
        for cc in self.coordinate_configs:
            cfg = cc.config.with_reg_weight(reg_weights[cc.name])
            prior = initial_models.get(cc.name) if cc.regularize_by_prior else None
            if cc.is_random_effect:
                inner: Coordinate = RandomEffectCoordinate(
                    dataset=datasets[cc.name],
                    task=self.task,
                    config=cfg,
                    prior_model=prior,
                )
            else:
                inner = FixedEffectCoordinate(
                    dataset=datasets[cc.name],
                    task=self.task,
                    config=cfg,
                    normalization=cc.normalization,
                    prior_model=prior,
                )
            if cc.name in self.partial_retrain_locked:
                locked = initial_models.get(cc.name)
                if locked is None:
                    raise ValueError(
                        f"locked coordinate {cc.name} needs a pretrained model"
                    )
                coords[cc.name] = ModelCoordinate(inner=inner, locked_model=locked)
            else:
                coords[cc.name] = inner
        return coords

    # -- fit -------------------------------------------------------------------

    def prepare_datasets(self, raw: RawDataset):
        """Build per-coordinate datasets once; pass the result to ``fit`` via
        ``datasets=`` to train several configurations (checkpointed grids,
        tuning trials) without rebuilding."""
        return self._prepare_datasets(raw)

    def fit(
        self,
        raw: RawDataset,
        validation: Optional[RawDataset] = None,
        initial_model: Optional[GameModel] = None,
        checkpoint_fn: Optional[object] = None,
        datasets: Optional[Dict[str, object]] = None,
        combos: Optional[Sequence[Mapping[str, float]]] = None,
        n_cd_iterations: Optional[int] = None,
        boundary_fn: Optional[object] = None,
        resume_state: Optional[object] = None,
    ) -> List[GameResult]:
        """``checkpoint_fn(reg_weights, iteration, game_model)`` runs after
        each completed coordinate-descent sweep of each configuration.

        ``boundary_fn(reg_weights, state)`` runs after EVERY coordinate
        update of every configuration (``state`` is descent's
        CDBoundaryState) — the fine-grained crash-safety hook
        (robust.CheckpointManager). ``resume_state`` (a
        robust.CheckpointSnapshot) resumes the FIRST combo in ``combos``
        mid-run; callers resuming a grid pass the remaining combos
        explicitly, snapshot matching the first.

        ``datasets``: pre-built datasets from :meth:`prepare_datasets`.
        ``combos``: explicit list of per-coordinate reg-weight dicts to train
        instead of the full cartesian grid (checkpoint resume trains the
        remaining combos one at a time). ``n_cd_iterations`` overrides the
        estimator's sweep count for THIS call (resuming a partly-trained
        configuration).

        ``validation`` may be a RawDataset, or a deferred one — a
        ``concurrent.futures.Future`` or zero-arg callable resolving to a
        RawDataset. A deferred validation is resolved only AFTER the training
        datasets are built, so a background decode thread (the CLI's ingest
        overlap; the native Avro decoder releases the GIL) runs concurrently
        with dataset preparation and device uploads."""
        if datasets is None:
            datasets = self._prepare_datasets(raw)
        if validation is not None:
            if hasattr(validation, "result"):
                validation = validation.result()
            elif callable(validation):
                validation = validation()
        validation_ctx = None
        if validation is not None:
            # evaluator_specs default to RMSE inside _validation_context
            validation_ctx, _ = self._validation_context(validation)

        # cartesian product of per-coordinate reg-weight grids
        grids = [cc.grid() for cc in self.coordinate_configs]
        names = [cc.name for cc in self.coordinate_configs]
        if combos is None:
            combos = [
                dict(zip(names, combo)) for combo in itertools.product(*grids)
            ]
        n_iterations = (
            self.n_cd_iterations if n_cd_iterations is None else n_cd_iterations
        )
        results: List[GameResult] = []
        prev_models: Dict[str, object] = dict(
            (initial_model.models if initial_model else {})
        )
        import time as _time

        self.send_event(TrainingStartEvent(time=_time.time()))
        for combo_index, reg_weights in enumerate(combos):
            reg_weights = dict(reg_weights)
            coords = self._make_coordinates(datasets, reg_weights, prev_models)
            cd_ckpt = None
            if checkpoint_fn is not None:
                task = self.task
                cd_ckpt = lambda it, models, _w=reg_weights: checkpoint_fn(
                    _w, it, GameModel(models=models, task=task)
                )
            cd_boundary = None
            if boundary_fn is not None:
                cd_boundary = lambda st, _w=reg_weights: boundary_fn(_w, st)
            cd = CoordinateDescent(
                coords, n_iterations=n_iterations,
                validation=validation_ctx, checkpoint_fn=cd_ckpt,
                validation_frequency=self.validation_frequency,
                boundary_fn=cd_boundary,
                # a snapshot describes one in-flight configuration — the
                # first combo of a resumed call; later combos start fresh
                resume_state=resume_state if combo_index == 0 else None,
                divergence_guard=self.divergence_guard,
                rejection_tolerance=self.rejection_tolerance,
                pipeline_depth=self.pipeline_depth,
            )
            with timed(f"train config {reg_weights}", logging.INFO):
                out = cd.run(initial_models=prev_models)
            results.append(
                GameResult(
                    model=out.model,
                    config=reg_weights,
                    evaluation=out.best_evaluation,
                    trackers=out.trackers,
                )
            )
            self.send_event(
                OptimizationLogEvent(
                    reg_weights=reg_weights,
                    trackers=out.trackers,
                    metrics=(
                        None
                        if out.best_evaluation is None
                        else dict(out.best_evaluation.metrics)
                    ),
                )
            )
            # warm start next config from this one (GameEstimator.scala:356-374)
            prev_models = dict(out.model.models)
        self.send_event(TrainingFinishEvent(time=_time.time()))
        return results

    def fit_lanes(
        self,
        raw: RawDataset,
        combos: Sequence[Mapping[str, float]],
        validation: Optional[RawDataset] = None,
        datasets: Optional[Dict[str, object]] = None,
        n_cd_iterations: Optional[int] = None,
    ) -> List[GameResult]:
        """Train ``len(combos)`` reg-weight configurations as lambda LANES of
        one batched coordinate-descent run (game/lanes.py): every lane shares
        each coordinate's data residency and compiled solver, the per-lane
        reg weight rides as a vector operand. Returns one GameResult per
        combo, in order — the batched counterpart of calling :meth:`fit`
        once per combo. See game.lanes.check_lane_composition for the
        compositions this path refuses."""
        from ..game.lanes import fit_lanes as _fit_lanes

        return _fit_lanes(
            self,
            raw,
            combos,
            validation=validation,
            datasets=datasets,
            n_cd_iterations=n_cd_iterations,
        )

    def select_best(self, results: Sequence[GameResult]) -> GameResult:
        """Best result by primary validation metric (falls back to the last)."""
        with_eval = [r for r in results if r.evaluation is not None]
        if not with_eval:
            return results[-1]
        suite_primary = build_suite(
            self.evaluator_specs or ["RMSE"], np.zeros(1)
        ).primary
        best = with_eval[0]
        for r in with_eval[1:]:
            if suite_primary.better(
                r.evaluation.primary_metric, best.evaluation.primary_metric
            ):
                best = r
        return best


@dataclasses.dataclass
class GameTransformer:
    """Scoring twin of the estimator (GameTransformer.scala:39-318):
    model + dataset -> summed per-coordinate scores (+offsets), optional eval."""

    model: GameModel
    dtype: object = jnp.float32

    def transform(
        self, raw: RawDataset, evaluator_specs: Sequence[str] = ()
    ) -> Tuple[np.ndarray, Optional[EvaluationResults]]:
        # one score assembly for the whole repo: the serving engine's compiled
        # kernels (serving/engine.py), so batch and resident scoring cannot
        # drift (tests/test_serving.py pins bitwise parity)
        from ..serving.engine import ScoreEngine

        total = ScoreEngine.from_model(self.model, dtype=self.dtype).score_dataset(raw)

        evaluation = None
        if evaluator_specs:
            suite = build_suite(
                evaluator_specs, raw.labels, raw.weights, id_tags=raw.id_tags
            )
            evaluation = suite.evaluate(total)
        return total, evaluation
