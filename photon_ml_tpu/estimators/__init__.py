from .model_training import TrainedModel, select_best_model, train_glm_grid

__all__ = ["TrainedModel", "train_glm_grid", "select_best_model"]
