from .game_estimator import (
    CoordinateConfig,
    GameEstimator,
    GameResult,
    GameTransformer,
)
from .model_training import TrainedModel, select_best_model, train_glm_grid

__all__ = [
    "CoordinateConfig",
    "GameEstimator",
    "GameResult",
    "GameTransformer",
    "TrainedModel",
    "train_glm_grid",
    "select_best_model",
]
