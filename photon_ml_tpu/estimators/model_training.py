"""Non-GAME GLM training over a regularization-weight grid with warm starts.

Reference: photon-api .../ModelTraining.trainGeneralizedLinearModel
(ModelTraining.scala:53-228): for each lambda in the grid (ascending),
warm-start from the previous lambda's coefficients, then select the best model
by a validation metric (legacy Driver's validate stage, Driver.scala:451).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..evaluation.suite import EvaluationSuite
from ..game.problem import GLMOptimizationConfig, GLMProblem
from ..models.glm import GeneralizedLinearModel
from ..ops.features import LabeledBatch
from ..ops.normalization import NormalizationContext
from ..optimize import SolverResult


@dataclasses.dataclass
class TrainedModel:
    reg_weight: float
    model: GeneralizedLinearModel
    solver_result: SolverResult
    validation_metrics: Optional[Dict[str, float]] = None


def train_glm_grid(
    batch: LabeledBatch,
    task: str,
    base_config: GLMOptimizationConfig,
    reg_weights: Sequence[float],
    normalization: Optional[NormalizationContext] = None,
    warm_start: bool = True,
    initial_model: Optional[GeneralizedLinearModel] = None,
) -> List[TrainedModel]:
    """Train one model per regularization weight, warm-starting along the grid."""
    out: List[TrainedModel] = []
    prev = initial_model
    for lam in sorted(reg_weights):
        problem = GLMProblem(
            task=task,
            config=base_config.with_reg_weight(lam),
            normalization=normalization,
        )
        model, result = problem.run(batch, initial_model=prev if warm_start else initial_model)
        out.append(TrainedModel(reg_weight=lam, model=model, solver_result=result))
        prev = model
    return out


def select_best_model(
    trained: Sequence[TrainedModel],
    validation_batch: LabeledBatch,
    suite: EvaluationSuite,
) -> Tuple[TrainedModel, List[TrainedModel]]:
    """Evaluate every model on the validation batch; pick by primary metric
    (legacy Driver model selection, Driver.scala:416)."""
    best: Optional[TrainedModel] = None
    best_value: float = float("nan")
    for tm in trained:
        scores = tm.model.score(validation_batch)
        results = suite.evaluate(jnp.asarray(scores))
        tm.validation_metrics = results.metrics
        v = results.primary_metric
        if best is None or suite.primary.better(v, best_value):
            best, best_value = tm, v
    return best, list(trained)
