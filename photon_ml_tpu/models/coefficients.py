"""Model coefficients: means + optional variances.

Reference: photon-lib .../model/Coefficients.scala:31-141. Dense jnp arrays
(the TPU frame: even "sparse" models score as dense vectors per feature shard;
huge feature spaces are handled by sharding the vector over the mesh, not by
hash maps).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def score(self, features_matvec) -> Array:
        """Dot-product scoring given a FeatureMatrix-like matvec callable."""
        return features_matvec(self.means)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros(dim, dtype))
