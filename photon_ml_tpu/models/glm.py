"""Generalized linear model classes.

Reference: photon-api .../supervised/** — GeneralizedLinearModel subclasses
each defining the mean (inverse-link) function:
LogisticRegressionModel (sigmoid, also a binary classifier with threshold),
LinearRegressionModel (identity), PoissonRegressionModel (exp),
SmoothedHingeLossLinearSVMModel (identity margin, binary classifier).
"""

from __future__ import annotations

import dataclasses
from typing import ClassVar, Optional

import jax
import jax.numpy as jnp

from ..ops.features import LabeledBatch
from ..ops.losses import LOGISTIC, POISSON, SMOOTHED_HINGE, SQUARED, PointwiseLoss
from .coefficients import Coefficients

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """Base GLM: coefficients + the task's mean function.

    ``score(batch)`` is the raw margin (features.coef + offset);
    ``predict_mean`` applies the inverse link.
    """

    coefficients: Coefficients
    task: ClassVar[str] = "none"
    loss: ClassVar[Optional[PointwiseLoss]] = None

    def score(self, batch: LabeledBatch) -> Array:
        return batch.margins(self.coefficients.means)

    def compute_mean(self, margins: Array) -> Array:
        raise NotImplementedError

    def predict_mean(self, batch: LabeledBatch) -> Array:
        return self.compute_mean(self.score(batch))


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LogisticRegressionModel(GeneralizedLinearModel):
    task: ClassVar[str] = "logistic_regression"
    loss: ClassVar[PointwiseLoss] = LOGISTIC

    def compute_mean(self, margins: Array) -> Array:
        return jax.nn.sigmoid(margins)

    def predict_class(self, batch: LabeledBatch, threshold: float = 0.5) -> Array:
        return (self.predict_mean(batch) > threshold).astype(jnp.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LinearRegressionModel(GeneralizedLinearModel):
    task: ClassVar[str] = "linear_regression"
    loss: ClassVar[PointwiseLoss] = SQUARED

    def compute_mean(self, margins: Array) -> Array:
        return margins


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class PoissonRegressionModel(GeneralizedLinearModel):
    task: ClassVar[str] = "poisson_regression"
    loss: ClassVar[PointwiseLoss] = POISSON

    def compute_mean(self, margins: Array) -> Array:
        return jnp.exp(margins)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SmoothedHingeLossLinearSVMModel(GeneralizedLinearModel):
    task: ClassVar[str] = "smoothed_hinge_loss_linear_svm"
    loss: ClassVar[PointwiseLoss] = SMOOTHED_HINGE

    def compute_mean(self, margins: Array) -> Array:
        return margins

    def predict_class(self, batch: LabeledBatch, threshold: float = 0.0) -> Array:
        return (self.score(batch) > threshold).astype(jnp.int32)


MODEL_CLASSES = {
    "logistic_regression": LogisticRegressionModel,
    "linear_regression": LinearRegressionModel,
    "poisson_regression": PoissonRegressionModel,
    "smoothed_hinge_loss_linear_svm": SmoothedHingeLossLinearSVMModel,
}


def model_for_task(task: str, coefficients: Coefficients) -> GeneralizedLinearModel:
    """Task-type -> model dispatch (reference: GeneralizedLinearModel factories)."""
    try:
        cls = MODEL_CLASSES[task.lower()]
    except KeyError:
        raise KeyError(f"Unknown training task: {task!r}") from None
    return cls(coefficients=coefficients)
