"""GAME model classes: fixed-effect, random-effect, and composite GAME models.

Reference: photon-lib/.../model/ — GameModel (map coordinateId -> model,
scores summed across coordinates, GameModel.scala:99-104), FixedEffectModel
(broadcast coefficients + dot products, FixedEffectModel.scala:55),
RandomEffectModel (per-entity coefficient lookup joined by entity id, score 0
for unseen entities, RandomEffectModel.scala:70,254+).

TPU re-design: a random-effect model is a *padded per-entity sparse matrix*
(entity-major ``coef_indices i32[E, S]`` / ``coef_values f32[E, S]``, indices
into the shard's global feature space, padded with -1) — the device-friendly
form of the reference's RDD[(entityId, GLM)]. Host keeps the entityId -> row
dict. Scoring gathers the entity row then dot-products in the entity's
subspace; unseen entities contribute 0.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.features import LabeledBatch
from .coefficients import Coefficients
from .glm import GeneralizedLinearModel, model_for_task

Array = jax.Array


def score_entity_ell(
    coef_indices: Array,  # i32[E, S] sorted ascending per row, -1 padded
    coef_values: Array,  # f[E, S]
    entity_rows: Array,  # i32[n], -1 = unseen entity
    feat_idx: Array,  # i32[n, F]
    feat_val: Array,  # f[n, F]
) -> Array:
    """Pure scoring kernel: per-row dot product against per-entity sparse
    coefficient vectors (RandomEffectModel.score semantics; jit/vmap/shard-safe).

    Per row i: score = sum_k feat_val[i,k] * w_e[feat_idx[i,k]] with w_e the
    sparse vector of entity entity_rows[i]; the lookup is a searchsorted into
    the entity's sorted support (-1 padding replaced by a +inf sentinel keeps
    the row sorted)."""
    pos, hit = ell_support_positions(coef_indices, entity_rows, feat_idx)
    return score_entity_ell_at(coef_values, entity_rows, pos, hit, feat_val)


@jax.jit
def ell_support_positions(
    coef_indices: Array,  # i32[E, S] sorted ascending per row, -1 padded
    entity_rows: Array,  # i32[n], -1 = unseen entity
    feat_idx: Array,  # i32[n, F]
):
    """Precompute (pos, hit) mapping each row's ELL features into its entity's
    sorted coefficient support.

    The support LAYOUT (coef_indices) is fixed per dataset while coefficient
    VALUES change every coordinate-descent sweep — so the vmapped
    searchsorted (the expensive part of scoring: a log(S) gather chain per
    feature on TPU) runs ONCE per dataset, and every subsequent score is one
    (row, pos) gather (score_entity_ell_at). Measured at bench shapes
    (n=500k) this takes RE scoring from ~1.7s to ~0.25s. The -1 padding is
    replaced by a +inf sentinel so each support row stays sorted.
    """
    safe_rows = jnp.maximum(entity_rows, 0)
    ent_idx = jnp.take(coef_indices, safe_rows, axis=0)  # [n, S]
    big = jnp.iinfo(jnp.int32).max
    ent_idx = jnp.where(ent_idx < 0, big, ent_idx)

    def one(ei, fi):
        pos = jnp.clip(jnp.searchsorted(ei, fi), 0, ei.shape[0] - 1)
        return pos.astype(jnp.int32), jnp.take(ei, pos) == fi

    return jax.vmap(one)(ent_idx, feat_idx)


@jax.jit
def ell_row_subspace(
    coef_indices: Array,  # i32[E, S] sorted ascending per row, -1 padded
    entity_rows: Array,  # i32[n], -1 = unseen entity
    feat_idx: Array,  # i32[n, F]
    feat_val: Array,  # f[n, F]
) -> Array:
    """Densify each row's ELL features into its entity's subspace layout:
    x_sub[i, s] = sum over the row's features that land at support position s.

    Like :func:`ell_support_positions`, this depends only on the support
    LAYOUT and the feature VALUES — both fixed per dataset — so it runs once
    and is cached; every subsequent score is then a contiguous row gather of
    the [E, S] coefficient table plus an elementwise dot
    (:func:`score_entity_rows_dense`), instead of an n*F random 2-D gather
    per sweep (measured ~10x at n=500k bench shapes)."""
    pos, hit = ell_support_positions(coef_indices, entity_rows, feat_idx)
    n = feat_idx.shape[0]
    S = coef_indices.shape[1]
    x_sub = jnp.zeros((n, S), feat_val.dtype)
    return x_sub.at[jnp.arange(n)[:, None], pos].add(
        jnp.where(hit, feat_val, 0.0)
    )


@jax.jit
def score_entity_rows_dense(
    coef_values: Array,  # f[E, S]
    entity_rows: Array,  # i32[n], -1 = unseen entity
    x_sub: Array,  # f[n, S] from ell_row_subspace
) -> Array:
    """Score with per-row subspace features already densified: one row gather
    + masked elementwise dot."""
    safe_rows = jnp.maximum(entity_rows, 0)
    w = jnp.take(coef_values, safe_rows, axis=0)  # [n, S]
    scores = jnp.sum(w * x_sub, axis=1)
    return jnp.where(entity_rows >= 0, scores, 0.0)


@jax.jit
def score_entity_rows_dense_lanes(
    coef_values: Array,  # f[E, S, L] lane-stacked per-entity coefficients
    entity_rows: Array,  # i32[n], -1 = unseen entity
    x_sub: Array,  # f[n, S] from ell_row_subspace
) -> Array:
    """Lane-stacked :func:`score_entity_rows_dense`: [n, L] scores for L
    lambda lanes sharing one densified-subspace cache — the sweep executor's
    RE scoring kernel (game/lanes.py)."""
    safe_rows = jnp.maximum(entity_rows, 0)
    w = jnp.take(coef_values, safe_rows, axis=0)  # [n, S, L]
    scores = jnp.sum(w * x_sub[:, :, None], axis=1)  # [n, L]
    return jnp.where(entity_rows[:, None] >= 0, scores, 0.0)


@jax.jit
def score_entity_ell_at(
    coef_values: Array,  # f[E, S]
    entity_rows: Array,  # i32[n], -1 = unseen entity
    pos: Array,  # i32[n, F] from ell_support_positions
    hit: Array,  # bool[n, F]
    feat_val: Array,  # f[n, F]
) -> Array:
    """Scoring with the searchsorted already resolved: one 2-D gather of
    coef_values at (entity_row, pos) index pairs plus a masked dot. The
    gather keeps (row, col) pairs instead of a flattened row*S+col index so
    E*S beyond int32 range cannot overflow."""
    safe_rows = jnp.maximum(entity_rows, 0)
    w = coef_values[safe_rows[:, None], pos]  # [n, F]
    scores = jnp.sum(jnp.where(hit, w * feat_val, 0.0), axis=1)
    return jnp.where(entity_rows >= 0, scores, 0.0)


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """One GLM applied to every sample's features from one feature shard."""

    model: GeneralizedLinearModel
    feature_shard: str

    @property
    def coefficients(self) -> Coefficients:
        return self.model.coefficients

    def score(self, batch: LabeledBatch) -> Array:
        """Margins WITHOUT the batch offset: coordinate scores compose by
        summation, offsets are added once by the consumer."""
        return batch.features.matvec(self.model.coefficients.means)


@dataclasses.dataclass
class RandomEffectModel:
    """Per-entity GLMs for one random-effect type over one feature shard."""

    random_effect_type: str  # id-tag column, e.g. "userId"
    feature_shard: str
    task: str
    entity_ids: np.ndarray  # object[E] host-side ids (row order of the arrays)
    coef_indices: Array  # i32[E, S] global feature indices, -1 padded
    coef_values: Array  # f[E, S]
    variances: Optional[Array] = None  # f[E, S] if computed
    _id_to_row: Optional[Dict[str, int]] = dataclasses.field(default=None, repr=False)

    def __post_init__(self):
        if self._id_to_row is None:
            self._id_to_row = {str(e): i for i, e in enumerate(self.entity_ids)}

    def __getstate__(self):
        # the coordinate-descent hot path tags trained models with a weakref
        # provenance mark (_support_layout_of, game/coordinate.py) — weakrefs
        # are unpicklable, so drop it; unpickled models fall back to the
        # memoized array-comparison layout check
        state = dict(self.__dict__)
        state.pop("_support_layout_of", None)
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    @property
    def num_entities(self) -> int:
        return len(self.entity_ids)

    def entity_row(self, entity_id: str) -> int:
        """Row index for an entity, -1 if unseen."""
        return self._id_to_row.get(str(entity_id), -1)

    def rows_for(self, entity_ids: Sequence) -> np.ndarray:
        return np.asarray([self.entity_row(e) for e in entity_ids], dtype=np.int64)

    def dense_coefficients(self, dim: int) -> np.ndarray:
        """Materialize [E, dim] dense coefficients (small models / tests)."""
        out = np.zeros((self.num_entities, dim))
        idx = np.asarray(self.coef_indices)
        val = np.asarray(self.coef_values)
        for e in range(self.num_entities):
            m = idx[e] >= 0
            out[e, idx[e][m]] = val[e][m]
        return out

    def score_ell_rows(
        self, entity_rows: Array, feat_idx: Array, feat_val: Array
    ) -> Array:
        """Score rows in ELL layout: row i gets features (feat_idx[i], feat_val[i])
        and entity row entity_rows[i] (-1 => unseen => score 0).

        Delegates to :func:`score_entity_ell`."""
        return score_entity_ell(
            self.coef_indices, self.coef_values, entity_rows, feat_idx, feat_val
        )


@dataclasses.dataclass
class GameModel:
    """coordinateId -> model; total score = sum of coordinate scores
    (GameModel.scala:99-104)."""

    models: Dict[str, object]  # FixedEffectModel | RandomEffectModel
    task: str = "logistic_regression"

    def __getitem__(self, name: str):
        return self.models[name]

    def __contains__(self, name: str) -> bool:
        return name in self.models

    def coordinates(self) -> List[str]:
        return list(self.models)

    def updated(self, name: str, model) -> "GameModel":
        new = dict(self.models)
        new[name] = model
        return GameModel(models=new, task=self.task)
