from .coefficients import Coefficients
from .glm import (
    GeneralizedLinearModel,
    LinearRegressionModel,
    LogisticRegressionModel,
    MODEL_CLASSES,
    PoissonRegressionModel,
    SmoothedHingeLossLinearSVMModel,
    model_for_task,
)

__all__ = [
    "Coefficients",
    "GeneralizedLinearModel",
    "LogisticRegressionModel",
    "LinearRegressionModel",
    "PoissonRegressionModel",
    "SmoothedHingeLossLinearSVMModel",
    "MODEL_CLASSES",
    "model_for_task",
]
