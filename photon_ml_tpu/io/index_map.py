"""Feature index maps: (name, term) feature identity -> dense column index.

Reference: photon-api .../index/IndexMap.scala + DefaultIndexMap /
PalDBIndexMap, and the NameAndTerm feature identity
(photon-client .../data/avro/NameAndTerm.scala). Feature keys concatenate
name + "\\u0001" + term; the intercept is the reserved key
"(INTERCEPT)" + "\\u0001" + "" (Constants.scala:31-42).

The in-memory map is a plain dict (DefaultIndexMap). The reference's PalDB
off-heap store exists so thousands of JVM executors can mmap one immutable
index; the TPU-native analogue is a flat binary file (sorted key blob +
offsets, written once at indexing time) that loads zero-copy via numpy — see
``save``/``load``. Index building at scale is a one-time host-side step
(SURVEY.md §2.1 P11).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..robust.atomic import atomic_write, atomic_write_json
from ..robust.retry import io_call

DELIMITER = ""
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
INTERCEPT_KEY = INTERCEPT_NAME + DELIMITER + INTERCEPT_TERM

_MAGIC = b"PHIDX001"
_MAGIC2 = b"PHIDX002"  # key-sorted, mmap-searchable (MmapIndexMap)

# offsets and indices are stored little-endian int64 ("<q"); size every
# header read from the dtype rather than a bare 8
_I64 = np.dtype(np.int64).itemsize


def feature_key(name: str, term: str = "") -> str:
    return name + DELIMITER + term


def split_feature_key(key: str) -> Tuple[str, str]:
    name, _, term = key.partition(DELIMITER)
    return name, term


class IndexMap:
    """Immutable feature-key -> index bijection for one feature shard."""

    def __init__(self, key_to_index: Dict[str, int]):
        self._k2i = key_to_index
        self._i2k: Optional[List[str]] = None

    @property
    def size(self) -> int:
        return len(self._k2i)

    def __len__(self) -> int:
        return len(self._k2i)

    def __contains__(self, key: str) -> bool:
        return key in self._k2i

    def get_index(self, key: str) -> int:
        """-1 for unseen features (IndexMap.NULL_KEY semantics)."""
        return self._k2i.get(key, -1)

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._i2k is None:
            i2k = [""] * len(self._k2i)
            for k, i in self._k2i.items():
                i2k[i] = k
            self._i2k = i2k
        return self._i2k[index] if 0 <= index < len(self._i2k) else None

    @property
    def intercept_index(self) -> Optional[int]:
        idx = self.get_index(INTERCEPT_KEY)
        return None if idx < 0 else idx

    def keys(self) -> Iterator[str]:
        return iter(self._k2i)

    def items(self) -> Iterator[Tuple[str, int]]:
        return iter(self._k2i.items())

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_keys(keys: Iterable[str], add_intercept: bool = True) -> "IndexMap":
        uniq = sorted(set(keys) - {INTERCEPT_KEY})
        if add_intercept:
            uniq.append(INTERCEPT_KEY)
        return IndexMap({k: i for i, k in enumerate(uniq)})

    @staticmethod
    def from_name_terms(
        name_terms: Iterable[Tuple[str, str]], add_intercept: bool = True
    ) -> "IndexMap":
        return IndexMap.from_keys(
            (feature_key(n, t) for n, t in name_terms), add_intercept
        )

    # -- binary store (PalDB-equivalent immutable index file) ---------------

    def save(self, path: str):
        """Write a flat binary store: header, i64 key-blob offsets, i64 global
        indices, utf-8 key blob. Entry k's key is blob[offsets[k]:offsets[k+1]]
        and maps to indices[k] — indices are stored explicitly, so a store may
        hold any subset of a global map (hash partitions included). Loading is
        one read + two numpy views (the "off-heap store" role of PalDBIndexMap)."""
        entries = [
            (k.encode("utf-8"), i)
            for k, i in sorted(self._k2i.items(), key=lambda kv: kv[1])
        ]
        _write_store(_MAGIC, entries, path)

    @staticmethod
    def load(path: str) -> "IndexMap":
        def _read(path):
            with open(path, "rb") as f:
                magic = f.read(8)
                if magic != _MAGIC:
                    raise ValueError(f"{path}: bad index store magic {magic!r}")
                (n,) = struct.unpack("<q", f.read(8))
                offsets = np.frombuffer(f.read(_I64 * (n + 1)), dtype=np.int64)
                indices = np.frombuffer(f.read(_I64 * n), dtype=np.int64)
                return n, offsets, indices, f.read()

        # transient read failures retry (site io.index_map_load); a bad magic
        # is a ValueError and fails immediately
        n, offsets, indices, blob = io_call(_read, path, site="io.index_map_load")
        k2i = {
            blob[offsets[k] : offsets[k + 1]].decode("utf-8"): int(indices[k])
            for k in range(n)
        }
        return IndexMap(k2i)


def save_partitioned(index_map: IndexMap, out_dir: str, num_partitions: int, shard: str):
    """Write the index as hash-partitioned mmap stores + metadata, matching
    the layout produced by FeatureIndexingDriver (one store per partition;
    partition = hash(key) % n, PalDBIndexMap.scala:69-105 semantics)."""
    os.makedirs(out_dir, exist_ok=True)
    parts: List[Dict[str, int]] = [dict() for _ in range(num_partitions)]
    for k, i in index_map.items():
        parts[_partition(k, num_partitions)][k] = i
    for p, mapping in enumerate(parts):
        MmapIndexMap.write(
            mapping.items(), os.path.join(out_dir, f"index-{shard}-{p:05d}.bin")
        )
    atomic_write_json(
        os.path.join(out_dir, f"_index-{shard}-meta.json"),
        {"shard": shard, "numPartitions": num_partitions, "size": len(index_map)},
    )


def load_partitioned(out_dir: str, shard: str):
    """Open the partitioned stores as zero-heap mmap views (v2 'PHIDX002'
    layout); v1 'PHIDX001' stores from older runs load into an in-memory
    IndexMap for compatibility."""
    def _read_meta():
        with open(os.path.join(out_dir, f"_index-{shard}-meta.json")) as f:
            return json.load(f)

    meta = io_call(_read_meta, site="io.index_map_load")
    part_paths = [
        os.path.join(out_dir, f"index-{shard}-{p:05d}.bin")
        for p in range(meta["numPartitions"])
    ]
    with open(part_paths[0], "rb") as f:
        magic = f.read(8)
    if magic == _MAGIC2:
        return PartitionedIndexMap(
            [MmapIndexMap.open(p) for p in part_paths], meta["size"]
        )
    merged: Dict[str, int] = {}
    for p in part_paths:
        merged.update(IndexMap.load(p).items())
    return IndexMap(merged)


def _write_store(magic: bytes, entries: List[Tuple[bytes, int]], path: str):
    """Shared v1/v2 store layout: magic, i64 n, i64 offsets[n+1], i64
    indices[n], key blob. v1 orders entries by index, v2 by key."""
    n = len(entries)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum([len(k) for k, _ in entries], out=offsets[1:])
    indices = np.asarray([i for _, i in entries], dtype=np.int64)
    # atomic: a crashed indexing run must not leave a torn store that a later
    # training run mmaps (robust.atomic — the output-committer property)
    with atomic_write(path, "wb") as f:
        f.write(magic)
        f.write(struct.pack("<q", n))
        f.write(offsets.tobytes())
        f.write(indices.tobytes())
        f.write(b"".join(k for k, _ in entries))


def _partition(key: str, n: int) -> int:
    # deterministic across runs (unlike Python's salted hash)
    h = 2166136261
    for b in key.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % n


class MmapIndexMap:
    """Zero-heap, memory-mapped index store: the PalDBIndexMap role
    (photon-api .../index/PalDBIndexMap.scala:43-278 — thousands of executors
    mmap one immutable off-heap store instead of materializing per-process
    hashmaps). The v2 store keeps entries sorted BY KEY, so lookups are
    binary searches over the mapped key blob — nothing is copied onto the
    Python heap; the OS page cache is shared across processes on a host.

    Interface-compatible with IndexMap (get_index / get_feature_name /
    items / intercept_index), so every consumer takes either."""

    def __init__(self, mm, offsets: np.ndarray, indices: np.ndarray,
                 blob_start: int, path: str):
        self._mm = mm
        self._offsets = offsets      # i64[n+1] into the key blob (key-sorted)
        self._indices = indices      # i64[n]  global index per sorted key
        self._blob_start = blob_start
        self._path = path
        self._rev: Optional[np.ndarray] = None  # index -> sorted-entry pos

    def __len__(self) -> int:
        return len(self._indices)

    @property
    def size(self) -> int:
        return len(self._indices)

    def _key_at(self, k: int) -> bytes:
        s = self._blob_start
        return bytes(self._mm[s + self._offsets[k]: s + self._offsets[k + 1]])

    def get_index(self, key: str) -> int:
        target = key.encode("utf-8")
        lo, hi = 0, len(self._indices)
        while lo < hi:
            mid = (lo + hi) // 2
            k = self._key_at(mid)
            if k < target:
                lo = mid + 1
            elif k > target:
                hi = mid
            else:
                return int(self._indices[mid])
        return -1

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._rev is None:
            self._rev = np.argsort(self._indices)
        pos = np.searchsorted(self._indices, index, sorter=self._rev)
        if pos >= len(self._indices):
            return None
        entry = int(self._rev[pos])
        if int(self._indices[entry]) != index:
            return None
        return self._key_at(entry).decode("utf-8")

    @property
    def intercept_index(self) -> Optional[int]:
        idx = self.get_index(INTERCEPT_KEY)
        return None if idx < 0 else idx

    def keys(self) -> Iterator[str]:
        for k in range(len(self._indices)):
            yield self._key_at(k).decode("utf-8")

    def items(self) -> Iterator[Tuple[str, int]]:
        for k in range(len(self._indices)):
            yield self._key_at(k).decode("utf-8"), int(self._indices[k])

    # -- store --------------------------------------------------------------

    @staticmethod
    def write(items: Iterable[Tuple[str, int]], path: str):
        """Write a key-sorted v2 store ('PHIDX002')."""
        _write_store(
            _MAGIC2, sorted((k.encode("utf-8"), i) for k, i in items), path
        )

    @staticmethod
    def open(path: str) -> "MmapIndexMap":
        import mmap as _mmap

        def _map():
            with open(path, "rb") as f:
                return _mmap.mmap(f.fileno(), 0, access=_mmap.ACCESS_READ)

        mm = io_call(_map, site="io.index_map_load")
        if mm[:8] != _MAGIC2:
            raise ValueError(f"{path}: bad v2 index store magic {bytes(mm[:8])!r}")
        (n,) = struct.unpack("<q", mm[8:16])
        off0 = 16
        offsets = np.frombuffer(mm, dtype=np.int64, count=n + 1, offset=off0)
        indices = np.frombuffer(
            mm, dtype=np.int64, count=n, offset=off0 + _I64 * (n + 1)
        )
        blob_start = off0 + _I64 * (n + 1) + _I64 * n
        return MmapIndexMap(mm, offsets, indices, blob_start, path)


class PartitionedIndexMap:
    """Hash-partitioned set of mmap stores looked up per key — the
    PalDBIndexMap partition routing (getIndex hashes the key to pick the
    store, PalDBIndexMap.scala:69-105). Same interface as IndexMap."""

    def __init__(self, parts: List[MmapIndexMap], size: int):
        self._parts = parts
        self._size = size
        # per-occurrence ingest calls get_index once per feature instance;
        # memoize resolved keys so repeats are dict hits, not binary searches
        self._memo: Dict[str, int] = {}
        self._rev_part: Optional[np.ndarray] = None
        self._rev_entry: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return self._size

    @property
    def size(self) -> int:
        return self._size

    def get_index(self, key: str) -> int:
        idx = self._memo.get(key)
        if idx is None:
            idx = self._parts[_partition(key, len(self._parts))].get_index(key)
            self._memo[key] = idx
        return idx

    def __contains__(self, key: str) -> bool:
        return self.get_index(key) >= 0

    def _build_reverse(self):
        # one-time merged reverse map: global index -> (partition, entry)
        self._rev_part = np.full(self._size, -1, dtype=np.int32)
        self._rev_entry = np.zeros(self._size, dtype=np.int64)
        for pi, p in enumerate(self._parts):
            idx = p._indices
            ok = (idx >= 0) & (idx < self._size)
            self._rev_part[idx[ok]] = pi
            self._rev_entry[idx[ok]] = np.flatnonzero(ok)

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._rev_part is None:
            self._build_reverse()
        if not (0 <= index < self._size) or self._rev_part[index] < 0:
            return None
        part = self._parts[int(self._rev_part[index])]
        return part._key_at(int(self._rev_entry[index])).decode("utf-8")

    @property
    def intercept_index(self) -> Optional[int]:
        idx = self.get_index(INTERCEPT_KEY)
        return None if idx < 0 else idx

    def keys(self) -> Iterator[str]:
        for p in self._parts:
            yield from p.keys()

    def items(self) -> Iterator[Tuple[str, int]]:
        for p in self._parts:
            yield from p.items()
