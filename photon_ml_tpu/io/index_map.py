"""Feature index maps: (name, term) feature identity -> dense column index.

Reference: photon-api .../index/IndexMap.scala + DefaultIndexMap /
PalDBIndexMap, and the NameAndTerm feature identity
(photon-client .../data/avro/NameAndTerm.scala). Feature keys concatenate
name + "\\u0001" + term; the intercept is the reserved key
"(INTERCEPT)" + "\\u0001" + "" (Constants.scala:31-42).

The in-memory map is a plain dict (DefaultIndexMap). The reference's PalDB
off-heap store exists so thousands of JVM executors can mmap one immutable
index; the TPU-native analogue is a flat binary file (sorted key blob +
offsets, written once at indexing time) that loads zero-copy via numpy — see
``save``/``load``. Index building at scale is a one-time host-side step
(SURVEY.md §2.1 P11).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

DELIMITER = ""
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
INTERCEPT_KEY = INTERCEPT_NAME + DELIMITER + INTERCEPT_TERM

_MAGIC = b"PHIDX001"


def feature_key(name: str, term: str = "") -> str:
    return name + DELIMITER + term


def split_feature_key(key: str) -> Tuple[str, str]:
    name, _, term = key.partition(DELIMITER)
    return name, term


class IndexMap:
    """Immutable feature-key -> index bijection for one feature shard."""

    def __init__(self, key_to_index: Dict[str, int]):
        self._k2i = key_to_index
        self._i2k: Optional[List[str]] = None

    @property
    def size(self) -> int:
        return len(self._k2i)

    def __len__(self) -> int:
        return len(self._k2i)

    def __contains__(self, key: str) -> bool:
        return key in self._k2i

    def get_index(self, key: str) -> int:
        """-1 for unseen features (IndexMap.NULL_KEY semantics)."""
        return self._k2i.get(key, -1)

    def get_feature_name(self, index: int) -> Optional[str]:
        if self._i2k is None:
            i2k = [""] * len(self._k2i)
            for k, i in self._k2i.items():
                i2k[i] = k
            self._i2k = i2k
        return self._i2k[index] if 0 <= index < len(self._i2k) else None

    @property
    def intercept_index(self) -> Optional[int]:
        idx = self.get_index(INTERCEPT_KEY)
        return None if idx < 0 else idx

    def keys(self) -> Iterator[str]:
        return iter(self._k2i)

    def items(self) -> Iterator[Tuple[str, int]]:
        return iter(self._k2i.items())

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_keys(keys: Iterable[str], add_intercept: bool = True) -> "IndexMap":
        uniq = sorted(set(keys) - {INTERCEPT_KEY})
        if add_intercept:
            uniq.append(INTERCEPT_KEY)
        return IndexMap({k: i for i, k in enumerate(uniq)})

    @staticmethod
    def from_name_terms(
        name_terms: Iterable[Tuple[str, str]], add_intercept: bool = True
    ) -> "IndexMap":
        return IndexMap.from_keys(
            (feature_key(n, t) for n, t in name_terms), add_intercept
        )

    # -- binary store (PalDB-equivalent immutable index file) ---------------

    def save(self, path: str):
        """Write a flat binary store: header, i64 key-blob offsets, i64 global
        indices, utf-8 key blob. Entry k's key is blob[offsets[k]:offsets[k+1]]
        and maps to indices[k] — indices are stored explicitly, so a store may
        hold any subset of a global map (hash partitions included). Loading is
        one read + two numpy views (the "off-heap store" role of PalDBIndexMap)."""
        items = sorted(self._k2i.items(), key=lambda kv: kv[1])
        n = len(items)
        encoded = [k.encode("utf-8") for k, _ in items]
        indices = np.asarray([i for _, i in items], dtype=np.int64)
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum([len(e) for e in encoded], out=offsets[1:])
        with open(path, "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<q", n))
            f.write(offsets.tobytes())
            f.write(indices.tobytes())
            f.write(b"".join(encoded))

    @staticmethod
    def load(path: str) -> "IndexMap":
        with open(path, "rb") as f:
            magic = f.read(8)
            if magic != _MAGIC:
                raise ValueError(f"{path}: bad index store magic {magic!r}")
            (n,) = struct.unpack("<q", f.read(8))
            offsets = np.frombuffer(f.read(8 * (n + 1)), dtype=np.int64)
            indices = np.frombuffer(f.read(8 * n), dtype=np.int64)
            blob = f.read()
        k2i = {
            blob[offsets[k] : offsets[k + 1]].decode("utf-8"): int(indices[k])
            for k in range(n)
        }
        return IndexMap(k2i)


def save_partitioned(index_map: IndexMap, out_dir: str, num_partitions: int, shard: str):
    """Write the index as hash-partitioned stores + metadata, matching the
    layout produced by FeatureIndexingDriver (one store per partition;
    partition = hash(key) % n, PalDBIndexMap.scala:69-105 semantics)."""
    os.makedirs(out_dir, exist_ok=True)
    parts: List[Dict[str, int]] = [dict() for _ in range(num_partitions)]
    for k, i in index_map.items():
        parts[_partition(k, num_partitions)][k] = i
    for p, mapping in enumerate(parts):
        IndexMap(mapping).save(os.path.join(out_dir, f"index-{shard}-{p:05d}.bin"))
    with open(os.path.join(out_dir, f"_index-{shard}-meta.json"), "w") as f:
        json.dump({"shard": shard, "numPartitions": num_partitions, "size": len(index_map)}, f)


def load_partitioned(out_dir: str, shard: str) -> IndexMap:
    with open(os.path.join(out_dir, f"_index-{shard}-meta.json")) as f:
        meta = json.load(f)
    merged: Dict[str, int] = {}
    for p in range(meta["numPartitions"]):
        part = IndexMap.load(os.path.join(out_dir, f"index-{shard}-{p:05d}.bin"))
        merged.update(part.items())
    return IndexMap(merged)


def _partition(key: str, n: int) -> int:
    # deterministic across runs (unlike Python's salted hash)
    h = 2166136261
    for b in key.encode("utf-8"):
        h = ((h ^ b) * 16777619) & 0xFFFFFFFF
    return h % n
