"""GAME / GLM model persistence in the reference's on-disk layout.

Reference: photon-client .../data/avro/ModelProcessingUtils.scala:77-625.
Layout (verified against the reference's checked-in fixture models):

    modelDir/
      model-metadata.json
      fixed-effect/<coordinateId>/
        id-info                      # line 1: feature shard id
        coefficients/part-00000.avro # one BayesianLinearModelAvro record
      random-effect/<coordinateId>/
        id-info                      # line 1: random-effect type (id tag)
                                     # line 2: feature shard id
        coefficients/part-*.avro     # one record per entity (modelId = entity)

Coefficients serialize as (name, term, value) triples through the shard's
IndexMap, so models interoperate with Photon ML deployments.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Mapping, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..models.coefficients import Coefficients
from ..models.game import FixedEffectModel, GameModel, RandomEffectModel
from ..models.glm import GeneralizedLinearModel, model_for_task
from ..robust.atomic import atomic_write, atomic_write_json
from ..robust.retry import io_call
from .avro import iter_avro_directory, write_avro_file
from .index_map import IndexMap, feature_key, split_feature_key
from .schemas import BAYESIAN_LINEAR_MODEL_AVRO

# Interop class names (reference: photon-api .../supervised/**)
_MODEL_CLASS_NAMES = {
    "logistic_regression": "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    "linear_regression": "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    "poisson_regression": "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    "smoothed_hinge_loss_linear_svm": "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_CLASS_NAME_TO_TASK = {v: k for k, v in _MODEL_CLASS_NAMES.items()}


def _coefficients_to_record(
    model_id: str,
    means: np.ndarray,
    variances: Optional[np.ndarray],
    index_map: IndexMap,
    task: str,
    sparsity_threshold: float = 0.0,
) -> dict:
    def triples(vec):
        out = []
        for i in np.nonzero(np.abs(vec) > sparsity_threshold)[0]:
            key = index_map.get_feature_name(int(i))
            if key is None:
                continue
            name, term = split_feature_key(key)
            out.append({"name": name, "term": term, "value": float(vec[i])})
        return out

    rec = {
        "modelId": model_id,
        "modelClass": _MODEL_CLASS_NAMES.get(task),
        "means": triples(means),
        "variances": None if variances is None else triples(variances),
        "lossFunction": None,
    }
    return rec


def _record_to_vector(rec_items, index_map: IndexMap, dim: int) -> np.ndarray:
    vec = np.zeros(dim)
    for t in rec_items:
        key = feature_key(t["name"], t["term"])
        idx = index_map.get_index(key)
        if idx >= 0:
            vec[idx] = t["value"]
    return vec


def save_glm(
    path: str,
    model: GeneralizedLinearModel,
    index_map: IndexMap,
    model_id: str = "",
    sparsity_threshold: float = 0.0,
):
    """Write a single GLM as one BayesianLinearModelAvro record file."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    coef = model.coefficients
    rec = _coefficients_to_record(
        model_id,
        np.asarray(coef.means),
        None if coef.variances is None else np.asarray(coef.variances),
        index_map,
        type(model).task,
        sparsity_threshold,
    )
    write_avro_file(path, BAYESIAN_LINEAR_MODEL_AVRO, [rec])


def load_glm(path: str, index_map: IndexMap, task: Optional[str] = None):
    recs = list(iter_avro_directory(path))
    if len(recs) != 1:
        raise ValueError(f"{path}: expected 1 model record, found {len(recs)}")
    rec = recs[0]
    task = task or _CLASS_NAME_TO_TASK.get(rec.get("modelClass") or "", "linear_regression")
    dim = len(index_map)
    means = _record_to_vector(rec["means"], index_map, dim)
    variances = (
        _record_to_vector(rec["variances"], index_map, dim)
        if rec.get("variances")
        else None
    )
    dt = jnp.asarray(0.0).dtype  # default float dtype (f32 on TPU, f64 under x64)
    coef = Coefficients(
        means=jnp.asarray(means, dt),
        variances=None if variances is None else jnp.asarray(variances, dt),
    )
    return model_for_task(task, coef)


def index_fingerprint(index_maps: Mapping[str, IndexMap]) -> dict:
    """Per-shard digests of the (feature key -> index) bijection, stamped
    into ``model-metadata.json`` at save time. ``keys`` digests the key SET
    (order-independent) — two indices with equal ``keys`` but different
    ``layout`` hold the same features at permuted positions, which the
    (name, term)-keyed load remaps losslessly. A ``layout`` match means the
    index is bitwise-identical, so warm-start priors align with no scan."""
    shards = {}
    for shard in sorted(index_maps):
        imap = index_maps[shard]
        h_keys = hashlib.sha256()
        h_layout = hashlib.sha256()
        for key, idx in sorted(imap.items()):
            kb = key.encode("utf-8")
            h_keys.update(kb)
            h_keys.update(b"\x00")
            h_layout.update(kb)
            h_layout.update(f":{idx}\x00".encode("utf-8"))
        shards[shard] = {
            "size": len(imap),
            "keys": h_keys.hexdigest(),
            "layout": h_layout.hexdigest(),
        }
    return {"version": 1, "shards": shards}


def _iter_model_coefficient_dirs(model_dir: str):
    """Yield (coordinate, feature_shard, coefficients_dir) for every
    sub-model in the reference layout."""
    for kind in ("fixed-effect", "random-effect"):
        root = os.path.join(model_dir, kind)
        if not os.path.isdir(root):
            continue
        for name in sorted(os.listdir(root)):
            base = os.path.join(root, name)
            if not os.path.isdir(base):
                continue
            with open(os.path.join(base, "id-info")) as f:
                first = f.readline().strip()
                shard = f.readline().strip() if kind == "random-effect" else first
            yield name, shard, os.path.join(base, "coefficients")


def check_prior_compatibility(
    model_dir: str, index_maps: Mapping[str, IndexMap]
) -> Dict[str, str]:
    """Verify a warm-start prior's feature space against the current index
    before ``--incremental-training`` loads it.

    Returns ``{shard: "exact" | "remap"}``. ``exact``: the stored
    fingerprint matches the current index bitwise. ``remap``: the indices
    differ but every prior feature exists under the current index, so the
    (name, term)-keyed load relocates each coefficient correctly. Any prior
    feature MISSING from the current index is refused with a typed error —
    ``load_game_model`` would silently drop those coefficients, mis-centering
    the prior instead of failing."""
    meta_path = os.path.join(model_dir, "model-metadata.json")
    stored = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            stored = (json.load(f).get("featureIndexFingerprint") or {}).get(
                "shards", {}
            )
    current = index_fingerprint(index_maps)["shards"]

    verdict: Dict[str, str] = {}
    scan_shards = set()
    for name, shard, coef_dir in _iter_model_coefficient_dirs(model_dir):
        if shard in verdict or shard in scan_shards:
            continue
        if shard not in index_maps:
            raise ValueError(
                "--incremental-training refused: prior model features absent "
                f"from the current feature index (shard {shard!r} of "
                f"{model_dir} has no current index at all); rebuild the "
                "feature index to cover the prior model"
            )
        got, want = stored.get(shard), current.get(shard)
        if got and want and got.get("layout") == want.get("layout"):
            verdict[shard] = "exact"
        elif got and want and got.get("keys") == want.get("keys"):
            verdict[shard] = "remap"
        else:
            scan_shards.add(shard)

    # no (or mismatched) fingerprint: scan the coefficient triples themselves
    for name, shard, coef_dir in _iter_model_coefficient_dirs(model_dir):
        if shard not in scan_shards:
            continue
        imap = index_maps[shard]
        missing = 0
        example = None
        for rec in iter_avro_directory(coef_dir):
            for part in ("means", "variances"):
                for t in rec.get(part) or ():
                    key = feature_key(t["name"], t["term"])
                    if key not in imap:
                        missing += 1
                        example = example or key
        if missing:
            raise ValueError(
                "--incremental-training refused: prior model features absent "
                f"from the current feature index ({missing} coefficient(s) of "
                f"shard {shard!r}, e.g. {example!r}); a silent load would "
                "mis-align the warm-start priors — rebuild the feature index "
                "to cover the prior model"
            )
        verdict[shard] = "remap"
        scan_shards.discard(shard)
    return verdict


def save_game_model(
    model_dir: str,
    game_model: GameModel,
    index_maps: Mapping[str, IndexMap],
    metadata: Optional[dict] = None,
    sparsity_threshold: float = 0.0,
    records_per_file: int = 100_000,
):
    os.makedirs(model_dir, exist_ok=True)
    meta = {
        "modelType": game_model.task.upper(),
        "featureIndexFingerprint": index_fingerprint(index_maps),
        **(metadata or {}),
    }
    # every file in the layout lands atomically (temp+fsync+rename,
    # robust.atomic) and retries transient failures at site io.model_save: a
    # crashed/flaky save never leaves a torn file a later load half-reads
    io_call(
        atomic_write_json,
        os.path.join(model_dir, "model-metadata.json"),
        meta, indent=2,
        site="io.model_save",
    )

    def _write_id_info(path, text):
        with atomic_write(path, "w") as f:
            f.write(text)

    for name, sub in game_model.models.items():
        if isinstance(sub, FixedEffectModel):
            base = os.path.join(model_dir, "fixed-effect", name)
            os.makedirs(os.path.join(base, "coefficients"), exist_ok=True)
            io_call(
                _write_id_info, os.path.join(base, "id-info"),
                sub.feature_shard + "\n", site="io.model_save",
            )
            save_glm(
                os.path.join(base, "coefficients", "part-00000.avro"),
                sub.model,
                index_maps[sub.feature_shard],
                model_id=name,
                sparsity_threshold=sparsity_threshold,
            )
        elif isinstance(sub, RandomEffectModel):
            base = os.path.join(model_dir, "random-effect", name)
            os.makedirs(os.path.join(base, "coefficients"), exist_ok=True)
            io_call(
                _write_id_info, os.path.join(base, "id-info"),
                sub.random_effect_type + "\n" + sub.feature_shard + "\n",
                site="io.model_save",
            )
            imap = index_maps[sub.feature_shard]
            idx = np.asarray(sub.coef_indices)
            val = np.asarray(sub.coef_values)
            var = None if sub.variances is None else np.asarray(sub.variances)

            def entity_records():
                for e, ent in enumerate(sub.entity_ids):
                    m = idx[e] >= 0
                    means = [
                        {
                            "name": (kv := split_feature_key(imap.get_feature_name(int(j))))[0],
                            "term": kv[1],
                            "value": float(v),
                        }
                        for j, v in zip(idx[e][m], val[e][m])
                        if abs(v) > sparsity_threshold
                    ]
                    variances = None
                    if var is not None:
                        variances = [
                            {
                                "name": (kv := split_feature_key(imap.get_feature_name(int(j))))[0],
                                "term": kv[1],
                                "value": float(v),
                            }
                            for j, v in zip(idx[e][m], var[e][m])
                        ]
                    yield {
                        "modelId": str(ent),
                        "modelClass": _MODEL_CLASS_NAMES.get(sub.task),
                        "means": means,
                        "variances": variances,
                        "lossFunction": None,
                    }

            # chunk into part files
            part = 0
            chunk = []
            for rec in entity_records():
                chunk.append(rec)
                if len(chunk) >= records_per_file:
                    write_avro_file(
                        os.path.join(base, "coefficients", f"part-{part:05d}.avro"),
                        BAYESIAN_LINEAR_MODEL_AVRO,
                        chunk,
                    )
                    part += 1
                    chunk = []
            write_avro_file(
                os.path.join(base, "coefficients", f"part-{part:05d}.avro"),
                BAYESIAN_LINEAR_MODEL_AVRO,
                chunk,
            )
        else:
            raise TypeError(f"Unknown sub-model type for {name}: {type(sub)}")


def load_game_model(
    model_dir: str, index_maps: Mapping[str, IndexMap], task: Optional[str] = None
) -> GameModel:
    meta_path = os.path.join(model_dir, "model-metadata.json")
    meta = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            meta = json.load(f)
    task = task or meta.get("modelType", "LINEAR_REGRESSION").lower()

    models: Dict[str, object] = {}
    fe_dir = os.path.join(model_dir, "fixed-effect")
    if os.path.isdir(fe_dir):
        for name in sorted(os.listdir(fe_dir)):
            base = os.path.join(fe_dir, name)
            if not os.path.isdir(base):
                continue
            with open(os.path.join(base, "id-info")) as f:
                shard = f.readline().strip()
            glm = load_glm(os.path.join(base, "coefficients"), index_maps[shard], task)
            models[name] = FixedEffectModel(model=glm, feature_shard=shard)

    re_dir = os.path.join(model_dir, "random-effect")
    if os.path.isdir(re_dir):
        for name in sorted(os.listdir(re_dir)):
            base = os.path.join(re_dir, name)
            if not os.path.isdir(base):
                continue
            with open(os.path.join(base, "id-info")) as f:
                re_type = f.readline().strip()
                shard = f.readline().strip()
            imap = index_maps[shard]
            ids, vecs, variances = [], [], []
            has_var = False
            for rec in iter_avro_directory(os.path.join(base, "coefficients")):
                ids.append(rec["modelId"])
                items = [
                    (imap.get_index(feature_key(t["name"], t["term"])), t["value"])
                    for t in rec["means"]
                ]
                vecs.append([(i, v) for i, v in items if i >= 0])
                if rec.get("variances"):
                    has_var = True
                    vitems = [
                        (imap.get_index(feature_key(t["name"], t["term"])), t["value"])
                        for t in rec["variances"]
                    ]
                    variances.append({i: v for i, v in vitems if i >= 0})
                else:
                    variances.append({})
            S = max((len(v) for v in vecs), default=1) or 1
            E = len(ids)
            idx = np.full((E, S), -1, dtype=np.int32)
            val = np.zeros((E, S))
            var = np.zeros((E, S)) if has_var else None
            for e, items in enumerate(vecs):
                items.sort()
                for k, (i, v) in enumerate(items):
                    idx[e, k] = i
                    val[e, k] = v
                    if var is not None:
                        var[e, k] = variances[e].get(i, 0.0)
            models[name] = RandomEffectModel(
                random_effect_type=re_type,
                feature_shard=shard,
                task=task,
                entity_ids=np.asarray(ids, dtype=object),
                coef_indices=jnp.asarray(idx),
                coef_values=jnp.asarray(val, jnp.asarray(0.0).dtype),
                variances=None if var is None else jnp.asarray(var, jnp.asarray(0.0).dtype),
            )
    return GameModel(models=models, task=task)
