"""Pure-Python Avro binary codec: Object Container Files, read + write.

The reference's wire format is Avro-on-HDFS (photon-avro-schemas/*.avsc,
AvroDataReader/AvroUtils in photon-client). This environment has no avro/
fastavro package, so the codec is implemented from the Avro 1.x specification:

- zigzag-varint ints/longs, little-endian float/double, length-prefixed
  bytes/string, records as concatenated fields, arrays/maps as count-prefixed
  blocks (negative count => byte size follows), unions as branch-index +
  value, enums as int index, fixed as raw bytes;
- Object Container Files: magic ``Obj\\x01``, file-metadata map with
  ``avro.schema`` / ``avro.codec``, 16-byte sync marker, then
  (count, size, payload, sync) blocks; codecs ``null`` and ``deflate``
  (raw zlib, wbits=-15).

Reader-vs-writer schema resolution follows the Avro spec's resolution rules
(pass ``reader_schema=`` to ``read_avro_file``/``iter_avro_directory``):
record fields match by name, writer-only fields are skipped, reader-only
fields take their defaults, numeric promotions (int->long/float/double,
long->float/double, float->double) and string<->bytes conversions apply, and
unions resolve branch-by-branch — so evolved production data decodes against
the current schema.

Decoding is the host-side IO hot path that feeds the TPU; the pure-Python
loop is enough to saturate a single chip for the benchmark datasets, and the
record layer is deliberately isolated (``_read_datum``/``_write_datum``) so a
C++ decode kernel can replace it without touching callers.
"""

from __future__ import annotations

import io as _io
import json
import mmap
import os
import struct
import zlib
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple, Union

from ..robust.atomic import atomic_write
from ..robust.retry import io_call

MAGIC = b"Obj\x01"
SYNC_SIZE = 16
_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}

Schema = Union[str, dict, list]


# ---------------------------------------------------------------------------
# schema handling
# ---------------------------------------------------------------------------


class SchemaEnv:
    """Named-type registry for record/enum/fixed references."""

    def __init__(self):
        self.named: Dict[str, dict] = {}

    def register(self, schema: dict):
        name = schema.get("name")
        if name:
            ns = schema.get("namespace")
            full = f"{ns}.{name}" if ns and "." not in name else name
            self.named[full] = schema
            self.named[name.split(".")[-1]] = schema

    def resolve(self, schema: Schema) -> Schema:
        if isinstance(schema, str) and schema not in _PRIMITIVES:
            if schema in self.named:
                return self.named[schema]
            short = schema.split(".")[-1]
            if short in self.named:
                return self.named[short]
            raise ValueError(f"Unknown named type: {schema}")
        return schema


def _walk_register(schema: Schema, env: SchemaEnv):
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "error"):
            env.register(schema)
            for f in schema["fields"]:
                _walk_register(f["type"], env)
        elif t in ("enum", "fixed"):
            env.register(schema)
        elif t == "array":
            _walk_register(schema["items"], env)
        elif t == "map":
            _walk_register(schema["values"], env)
    elif isinstance(schema, list):
        for s in schema:
            _walk_register(s, env)


def parse_schema(schema: Union[str, Schema]) -> Tuple[Schema, SchemaEnv]:
    if isinstance(schema, str) and (schema.lstrip()[:1] in "{["):
        schema = json.loads(schema)
    env = SchemaEnv()
    _walk_register(schema, env)
    return schema, env


# ---------------------------------------------------------------------------
# binary decoder
# ---------------------------------------------------------------------------


def _iter_block_counts(r: "_Reader") -> Iterator[int]:
    """Yield per-block item counts of an Avro array/map encoding (negative
    count means a byte size follows; 0 terminates)."""
    while True:
        count = r.read_long()
        if count == 0:
            return
        if count < 0:
            r.read_long()  # byte size, unused
            count = -count
        yield count


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, data: bytes):
        self.buf = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        p = self.pos
        self.pos = p + n
        return self.buf[p : p + n]

    def read_long(self) -> int:
        b = self.buf
        p = self.pos
        shift = 0
        acc = 0
        while True:
            byte = b[p]
            p += 1
            acc |= (byte & 0x7F) << shift
            if byte < 0x80:
                break
            shift += 7
        self.pos = p
        return (acc >> 1) ^ -(acc & 1)

    def read_float(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def read_double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")

    def at_end(self) -> bool:
        return self.pos >= len(self.buf)


def _read_datum(r: _Reader, schema: Schema, env: SchemaEnv) -> Any:
    schema = env.resolve(schema)
    if isinstance(schema, str):
        t = schema
    elif isinstance(schema, list):
        idx = r.read_long()
        return _read_datum(r, schema[idx], env)
    else:
        t = schema["type"]
        if isinstance(t, (dict, list)):
            return _read_datum(r, t, env)

    if t == "null":
        return None
    if t == "boolean":
        return r.read(1) == b"\x01"
    if t in ("int", "long"):
        return r.read_long()
    if t == "float":
        return r.read_float()
    if t == "double":
        return r.read_double()
    if t == "bytes":
        return r.read_bytes()
    if t == "string":
        return r.read_string()
    if t == "record" or t == "error":
        return {
            f["name"]: _read_datum(r, f["type"], env) for f in schema["fields"]
        }
    if t == "enum":
        return schema["symbols"][r.read_long()]
    if t == "fixed":
        return r.read(schema["size"])
    if t == "array":
        out: List[Any] = []
        items = schema["items"]
        for count in _iter_block_counts(r):
            for _ in range(count):
                out.append(_read_datum(r, items, env))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        values = schema["values"]
        for count in _iter_block_counts(r):
            for _ in range(count):
                key = r.read_string()  # key must decode before the value
                m[key] = _read_datum(r, values, env)
        return m
    if t == "union":
        idx = r.read_long()
        return _read_datum(r, schema["types"][idx], env)
    raise ValueError(f"Unsupported Avro type: {t!r}")


# ---------------------------------------------------------------------------
# reader-vs-writer schema resolution (Avro spec "Schema Resolution")
# ---------------------------------------------------------------------------

_PROMOTIONS = {
    "int": {"int", "long", "float", "double"},
    "long": {"long", "float", "double"},
    "float": {"float", "double"},
    "double": {"double"},
    "string": {"string", "bytes"},
    "bytes": {"bytes", "string"},
}


def _type_name(schema: Schema, env: SchemaEnv) -> str:
    schema = env.resolve(schema)
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    t = schema["type"]
    if isinstance(t, (dict, list)):
        return _type_name(t, env)
    return t


def _short_name(schema: dict) -> str:
    return schema.get("name", "").split(".")[-1]


def _match(writer: Schema, reader: Schema, wenv: SchemaEnv, renv: SchemaEnv) -> bool:
    """Can data written with `writer` resolve into `reader`? (shallow check —
    deep mismatches surface as errors during decode)."""
    wt, rt = _type_name(writer, wenv), _type_name(reader, renv)
    if wt in _PROMOTIONS:
        return rt in _PROMOTIONS[wt]
    if wt in ("null", "boolean"):
        return rt == wt
    if wt == "union" or rt == "union":
        return True  # branch-level matching happens at decode time
    if wt != rt:
        return False
    if wt in ("record", "error", "enum", "fixed"):
        w, r = wenv.resolve(writer), renv.resolve(reader)
        return _short_name(w) == _short_name(r)
    return True  # array/map: item/value checked during decode


def _read_resolved(
    r: _Reader, writer: Schema, reader: Schema, wenv: SchemaEnv, renv: SchemaEnv
) -> Any:
    """Decode a datum written as `writer` into the shape of `reader`."""
    writer = wenv.resolve(writer)
    reader = renv.resolve(reader)

    # unwrap {"type": <complex>} wrappers and the nonstandard
    # {"type": "union", "types": [...]} union spelling
    if isinstance(writer, dict):
        if writer.get("type") == "union":
            writer = writer["types"]
        elif isinstance(writer.get("type"), (dict, list)):
            return _read_resolved(r, writer["type"], reader, wenv, renv)
    if isinstance(reader, dict):
        if reader.get("type") == "union":
            reader = reader["types"]
        elif isinstance(reader.get("type"), (dict, list)):
            return _read_resolved(r, writer, reader["type"], wenv, renv)

    # writer union: read the branch index, resolve that branch against reader
    if isinstance(writer, list):
        idx = r.read_long()
        return _read_resolved(r, writer[idx], reader, wenv, renv)
    # reader union (writer is not): first matching reader branch
    if isinstance(reader, list):
        for branch in reader:
            if _match(writer, branch, wenv, renv):
                return _read_resolved(r, writer, branch, wenv, renv)
        raise ValueError(
            f"cannot resolve writer type {_type_name(writer, wenv)!r} "
            f"into reader union {reader}"
        )

    wt = writer if isinstance(writer, str) else writer["type"]
    rt = reader if isinstance(reader, str) else reader["type"]

    if wt in _PRIMITIVES:
        if rt not in _PROMOTIONS.get(wt, {wt}):
            raise ValueError(f"cannot promote writer {wt!r} to reader {rt!r}")
        value = _read_datum(r, wt, wenv)
        if wt in ("int", "long") and rt in ("float", "double"):
            return float(value)
        if wt == "string" and rt == "bytes":
            return value.encode("utf-8")
        if wt == "bytes" and rt == "string":
            return value.decode("utf-8")
        return value

    if wt != rt:
        raise ValueError(f"writer type {wt!r} does not resolve to reader {rt!r}")

    if wt in ("record", "error"):
        if _short_name(writer) != _short_name(reader):
            raise ValueError(
                f"record name mismatch: writer {_short_name(writer)!r} "
                f"vs reader {_short_name(reader)!r}"
            )
        reader_fields = {f["name"]: f for f in reader["fields"]}
        out: Dict[str, Any] = {}
        seen = set()
        for wf in writer["fields"]:
            name = wf["name"]
            rf = reader_fields.get(name)
            if rf is None:
                _read_datum(r, wf["type"], wenv)  # skip writer-only field
            else:
                out[name] = _read_resolved(r, wf["type"], rf["type"], wenv, renv)
                seen.add(name)
        for name, rf in reader_fields.items():
            if name not in seen:
                if "default" not in rf:
                    raise ValueError(
                        f"reader field {name!r} missing from writer data and "
                        "has no default"
                    )
                out[name] = _default_value(rf["type"], rf["default"], renv)
        return out

    if wt == "enum":
        symbol = writer["symbols"][r.read_long()]
        if symbol not in reader["symbols"]:
            if "default" in reader:
                return reader["default"]
            raise ValueError(f"enum symbol {symbol!r} not in reader schema")
        return symbol

    if wt == "fixed":
        if writer["size"] != reader["size"]:
            raise ValueError("fixed size mismatch between writer and reader")
        return r.read(writer["size"])

    if wt == "array":
        out_list: List[Any] = []
        for count in _iter_block_counts(r):
            for _ in range(count):
                out_list.append(
                    _read_resolved(r, writer["items"], reader["items"], wenv, renv)
                )
        return out_list

    if wt == "map":
        m: Dict[str, Any] = {}
        for count in _iter_block_counts(r):
            for _ in range(count):
                key = r.read_string()
                m[key] = _read_resolved(
                    r, writer["values"], reader["values"], wenv, renv
                )
        return m

    raise ValueError(f"Unsupported Avro type in resolution: {wt!r}")


def _default_value(schema: Schema, default: Any, env: SchemaEnv) -> Any:
    """Materialize a reader-schema field default (JSON shape -> datum). Per
    the spec, a union field's default conforms to the union's FIRST branch."""
    schema = env.resolve(schema)
    if isinstance(schema, list):
        return _default_value(schema[0], default, env)
    t = schema if isinstance(schema, str) else schema["type"]
    if t == "bytes" and isinstance(default, str):
        return default.encode("iso-8859-1")
    if t in ("int", "long") and default is not None:
        return int(default)
    if t in ("float", "double") and default is not None:
        return float(default)
    return default


# ---------------------------------------------------------------------------
# binary encoder
# ---------------------------------------------------------------------------


class _Writer:
    __slots__ = ("out",)

    def __init__(self):
        self.out = _io.BytesIO()

    def write(self, b: bytes):
        self.out.write(b)

    def write_long(self, n: int):
        n = (n << 1) ^ (n >> 63) if n < 0 else n << 1
        while True:
            b = n & 0x7F
            n >>= 7
            if n:
                self.out.write(bytes([b | 0x80]))
            else:
                self.out.write(bytes([b]))
                break

    def write_float(self, v: float):
        self.out.write(struct.pack("<f", v))

    def write_double(self, v: float):
        self.out.write(struct.pack("<d", v))

    def write_bytes(self, b: bytes):
        self.write_long(len(b))
        self.out.write(b)

    def write_string(self, s: str):
        self.write_bytes(s.encode("utf-8"))

    def getvalue(self) -> bytes:
        return self.out.getvalue()


def _union_branch(schema: list, datum: Any, env: SchemaEnv) -> int:
    """Pick the union branch for a datum (null vs first matching type)."""
    for i, s in enumerate(schema):
        rs = env.resolve(s)
        t = rs if isinstance(rs, str) else rs.get("type")
        if datum is None and t == "null":
            return i
        if datum is not None and t != "null":
            if t == "string" and isinstance(datum, str):
                return i
            if t in ("int", "long") and isinstance(datum, int) and not isinstance(datum, bool):
                return i
            if t in ("float", "double") and isinstance(datum, (int, float)) and not isinstance(datum, bool):
                return i
            if t == "boolean" and isinstance(datum, bool):
                return i
            if t == "bytes" and isinstance(datum, bytes):
                return i
            if t in ("record", "error", "map") and isinstance(datum, dict):
                return i
            if t == "array" and isinstance(datum, (list, tuple)):
                return i
            if t in ("enum",) and isinstance(datum, str):
                return i
            if t == "fixed" and isinstance(datum, bytes):
                return i
    raise ValueError(f"No union branch for datum {datum!r} in {schema}")


def _write_datum(w: _Writer, schema: Schema, datum: Any, env: SchemaEnv):
    schema = env.resolve(schema)
    if isinstance(schema, list):
        idx = _union_branch(schema, datum, env)
        w.write_long(idx)
        _write_datum(w, schema[idx], datum, env)
        return
    t = schema if isinstance(schema, str) else schema["type"]
    if isinstance(t, (dict, list)):
        _write_datum(w, t, datum, env)
        return
    if t == "union":  # nonstandard {"type": "union", "types": [...]} spelling
        _write_datum(w, schema["types"], datum, env)
        return

    if t == "null":
        return
    if t == "boolean":
        w.write(b"\x01" if datum else b"\x00")
    elif t in ("int", "long"):
        w.write_long(int(datum))
    elif t == "float":
        w.write_float(float(datum))
    elif t == "double":
        w.write_double(float(datum))
    elif t == "bytes":
        w.write_bytes(datum)
    elif t == "string":
        w.write_string(datum)
    elif t in ("record", "error"):
        for f in schema["fields"]:
            name = f["name"]
            if name in datum:
                value = datum[name]
            elif "default" in f:
                value = f["default"]
            else:
                raise KeyError(f"Record missing field {name!r}")
            _write_datum(w, f["type"], value, env)
    elif t == "enum":
        w.write_long(schema["symbols"].index(datum))
    elif t == "fixed":
        w.write(datum)
    elif t == "array":
        if datum:
            w.write_long(len(datum))
            for item in datum:
                _write_datum(w, schema["items"], item, env)
        w.write_long(0)
    elif t == "map":
        if datum:
            w.write_long(len(datum))
            for k, v in datum.items():
                w.write_string(k)
                _write_datum(w, schema["values"], v, env)
        w.write_long(0)
    else:
        raise ValueError(f"Unsupported Avro type: {t!r}")


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------


def read_avro_file(
    path: str,
    reader_schema: Optional[Union[str, Schema]] = None,
    row_range: Optional[Tuple[int, int]] = None,
) -> Tuple[Schema, List[dict]]:
    """Read one .avro Object Container File -> (writer schema, records).

    With ``reader_schema``, records are resolved into the reader's shape
    (field defaults, numeric promotion, skipped writer-only fields); it may
    be a schema or a pre-parsed ``(schema, SchemaEnv)`` pair.

    With ``row_range=(start, stop)``, only records in that index window come
    back; blocks wholly outside the window are skipped WITHOUT decompressing
    or decoding (the per-host input split of the multi-process runtime —
    each host pays IO+decode for ~1/P of the data). The file is memory-mapped,
    so skipped payload pages are never read from disk.

    Transient IO failures (OSError) retry under the default backoff policy
    at site ``io.avro_read`` (the reference's Spark task retry)."""
    return io_call(
        _read_avro_file, path, reader_schema, row_range, site="io.avro_read"
    )


def _read_avro_file(
    path: str,
    reader_schema: Optional[Union[str, Schema]] = None,
    row_range: Optional[Tuple[int, int]] = None,
) -> Tuple[Schema, List[dict]]:
    with open(path, "rb") as f:
        try:
            data = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:  # zero-length file
            raise ValueError(f"{path}: not an Avro object container file")
        with data:
            r = _Reader(data)
            if r.read(4) != MAGIC:
                raise ValueError(f"{path}: not an Avro object container file")
            meta_schema = {"type": "map", "values": "bytes"}
            env0 = SchemaEnv()
            meta = _read_datum(r, meta_schema, env0)
            schema_json = meta["avro.schema"].decode("utf-8")
            codec = meta.get("avro.codec", b"null").decode("utf-8")
            schema, env = parse_schema(schema_json)
            sync = r.read(SYNC_SIZE)

            if reader_schema is not None:
                if isinstance(reader_schema, tuple):
                    rschema, renv = reader_schema
                else:
                    rschema, renv = parse_schema(reader_schema)

            records: List[dict] = []
            row_idx = 0
            while not r.at_end():
                count = r.read_long()
                size = r.read_long()
                if row_range is not None and row_idx >= row_range[1]:
                    break  # past the window: nothing left to decode
                if row_range is not None and row_idx + count <= row_range[0]:
                    r.pos += size  # skip payload pages entirely
                    if r.pos + SYNC_SIZE > len(r.buf):
                        raise ValueError(f"{path}: truncated block (corrupt file)")
                    if r.read(SYNC_SIZE) != sync:
                        raise ValueError(
                            f"{path}: sync marker mismatch (corrupt file)"
                        )
                    row_idx += count
                    continue
                payload = r.read(size)
                if codec == "deflate":
                    payload = zlib.decompress(payload, -15)
                elif codec != "null":
                    raise ValueError(f"Unsupported Avro codec: {codec}")
                br = _Reader(payload)
                block: List[dict] = []
                if reader_schema is None:
                    for _ in range(count):
                        block.append(_read_datum(br, schema, env))
                else:
                    for _ in range(count):
                        block.append(_read_resolved(br, schema, rschema, env, renv))
                if row_range is not None:
                    lo = max(row_range[0] - row_idx, 0)
                    hi = min(row_range[1] - row_idx, count)
                    block = block[lo:hi]
                records.extend(block)
                row_idx += count
                block_sync = r.read(SYNC_SIZE)
                if block_sync != sync:
                    raise ValueError(f"{path}: sync marker mismatch (corrupt file)")
            return schema, records


def count_avro_rows(path: str) -> int:
    """Record count of an Object Container File from block headers alone —
    no decompression, no record decode. Retries transient IO failures at
    site ``io.avro_read``."""
    return io_call(_count_avro_rows, path, site="io.avro_read")


def _count_avro_rows(path: str) -> int:
    with open(path, "rb") as f:
        try:
            data = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        except ValueError:
            raise ValueError(f"{path}: not an Avro object container file")
        with data:
            r = _Reader(data)
            if r.read(4) != MAGIC:
                raise ValueError(f"{path}: not an Avro object container file")
            _read_datum(r, {"type": "map", "values": "bytes"}, SchemaEnv())
            r.pos += SYNC_SIZE
            total = 0
            while not r.at_end():
                count = r.read_long()
                size = r.read_long()
                # a corrupt/hostile header could rewind the cursor (negative
                # size => infinite loop) or overflow the total; validate like
                # the read path's block-skip does
                if count < 0 or size < 0 or r.pos + size + SYNC_SIZE > len(data):
                    raise ValueError(
                        f"{path}: corrupt Avro block header "
                        f"(count={count}, size={size} at offset {r.pos})"
                    )
                r.pos += size + SYNC_SIZE
                total += count
            return total


def list_avro_parts(path: str) -> List[str]:
    """Part files of an Avro dataset directory (or the single file itself)."""
    if os.path.isfile(path):
        return [path]
    return [
        os.path.join(path, name)
        for name in sorted(os.listdir(path))
        if not name.startswith((".", "_")) and name.endswith(".avro")
    ]


def iter_avro_directory(
    path: str, reader_schema: Optional[Union[str, Schema]] = None
) -> Iterator[dict]:
    """Read all part files of an Avro dataset directory (or a single file),
    mirroring how the reference consumes HDFS output dirs."""
    if reader_schema is not None and not isinstance(reader_schema, tuple):
        reader_schema = parse_schema(reader_schema)  # parse once for all parts
    for part in list_avro_parts(path):
        yield from read_avro_file(part, reader_schema)[1]


def write_avro_file(
    path: str,
    schema: Union[str, Schema],
    records: Iterable[dict],
    codec: str = "deflate",
    sync_interval_records: int = 4000,
):
    schema_obj, env = parse_schema(schema)
    schema_json = json.dumps(schema_obj)
    sync = os.urandom(SYNC_SIZE)

    header = _Writer()
    header.write(MAGIC)
    _write_datum(
        header,
        {"type": "map", "values": "bytes"},
        {"avro.schema": schema_json.encode("utf-8"), "avro.codec": codec.encode("utf-8")},
        env,
    )
    header.write(sync)

    def flush_block(out, buf: _Writer, count: int):
        if count == 0:
            return
        payload = buf.getvalue()
        if codec == "deflate":
            co = zlib.compressobj(level=6, wbits=-15)
            payload = co.compress(payload) + co.flush()
        elif codec != "null":
            raise ValueError(f"Unsupported Avro codec: {codec}")
        head = _Writer()
        head.write_long(count)
        head.write_long(len(payload))
        out.write(head.getvalue())
        out.write(payload)
        out.write(sync)

    # atomic (robust.atomic): a crash mid-write leaves no torn .avro behind —
    # readers see the old file or the complete new one, never a truncated
    # container (the reference gets this from the HDFS output committer)
    with atomic_write(path, "wb") as out:
        out.write(header.getvalue())
        buf = _Writer()
        count = 0
        for rec in records:
            _write_datum(buf, schema_obj, rec, env)
            count += 1
            if count >= sync_interval_records:
                flush_block(out, buf, count)
                buf = _Writer()
                count = 0
        flush_block(out, buf, count)
