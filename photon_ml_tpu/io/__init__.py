"""IO package with lazy submodule exports.

``io.avro`` and ``io.index_map`` are jax-free by design (lint rule R8) so
the post-hoc report path (`cli report`) can read saved models and feature
indexes on a dev box with no accelerator stack. ``io.data`` / ``io.model_io``
import jax; resolving every name lazily (PEP 562) keeps `import
photon_ml_tpu.io` itself jax-free.
"""

_EXPORTS = {
    "read_avro_file": "avro",
    "write_avro_file": "avro",
    "iter_avro_directory": "avro",
    "parse_schema": "avro",
    "InputColumnsNames": "columns",
    "FeatureShardConfig": "data",
    "RawDataset": "data",
    "read_avro_dataset": "data",
    "read_avro_dataset_chunked": "data",
    "read_avro_part_pieces": "data",
    "scan_index_maps_pipelined": "data",
    "resolve_ingest_workers": "data",
    "read_libsvm": "data",
    "records_to_dataset": "data",
    "build_index_maps": "data",
    "IndexMap": "index_map",
    "INTERCEPT_KEY": "index_map",
    "feature_key": "index_map",
    "split_feature_key": "index_map",
    "save_glm": "model_io",
    "load_glm": "model_io",
    "save_game_model": "model_io",
    "load_game_model": "model_io",
}

__all__ = list(_EXPORTS)


def __getattr__(name):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(f".{submodule}", __name__), name)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))
