from .avro import iter_avro_directory, parse_schema, read_avro_file, write_avro_file
from .columns import InputColumnsNames
from .data import (
    FeatureShardConfig,
    RawDataset,
    build_index_maps,
    read_avro_dataset,
    read_avro_dataset_chunked,
    read_libsvm,
    records_to_dataset,
)
from .index_map import INTERCEPT_KEY, IndexMap, feature_key, split_feature_key
from .model_io import load_game_model, load_glm, save_game_model, save_glm

__all__ = [
    "read_avro_file",
    "write_avro_file",
    "iter_avro_directory",
    "parse_schema",
    "FeatureShardConfig",
    "InputColumnsNames",
    "RawDataset",
    "read_avro_dataset",
    "read_avro_dataset_chunked",
    "read_libsvm",
    "records_to_dataset",
    "build_index_maps",
    "IndexMap",
    "INTERCEPT_KEY",
    "feature_key",
    "split_feature_key",
    "save_glm",
    "load_glm",
    "save_game_model",
    "load_game_model",
]
